"""Benchmark suite — all five BASELINE.md configs (+2b, +6) + the HTTP
serving path (solo, concurrent, executor) + the on-device golden-parity
smoke.

Prints ONE JSON line per metric (12+ lines):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}
then a FINAL line restating the north-star headline (config #5's
gang_placement metric) with EVERY metric of the run embedded under
detail.all_metrics — the driver records the output tail, so that one line
carries the whole round even under truncation.
`vs_baseline` = 50ms-target / measured (>1 beats the target).

Configs (BASELINE.md "Benchmark configs to reproduce"):
  1. 1 driver + 8 executors on 10 nodes, tightly-pack
  2. 100 FIFO drivers x 8 executors, 500 nodes, distribute-evenly,
     skippable=False — strict-FIFO blocking EXERCISED
  3. dynamic-allocation min=2/max=32, 200 apps, 1k nodes
  4. 5 instance-groups, heterogeneous node shapes, 5k nodes
     (grouped_fifo_pack, vmapped over groups)
  5. 10k-node x 1k-app batched admission (north star)
plus `serving_http`: wall-clock p50 of POST /predicates through the real
HTTP server + extender + batched solver + write-back (the served path,
cmd/endpoints.go:28-42 equivalent).

Device-timing method: this machine reaches the TPU through a tunnel whose
RPC round-trip (~70 ms) would swamp a single-call timing, and
`jax.block_until_ready` does not reliably wait on the experimental
backend — only a host transfer does. So kernel service time is measured as
the MARGINAL cost of extending a dependent window chain:
(T(chain of K_long) - T(chain of K_short)) / (K_long - K_short), each chain
forced by one host transfer of its final output. Fixed RPC/dispatch
overhead cancels; what remains is the true per-window device time — what
pipelined serving pays. p50 over repeated marginal measurements. The
admission kernels are data-independent (same XLA program whether apps
admit or block), so recycling windows through the chain is timing-faithful.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

TARGET_MS = 50.0


def _enable_compile_cache():
    """Persistent XLA compilation cache next to the repo: window-shape
    buckets compile once per MACHINE instead of once per process (a fresh
    bench process otherwise pays tens of seconds of Mosaic/XLA compiles
    before its first serving window; a real deployment ships the same
    cache in its image)."""
    import os

    from spark_scheduler_tpu.server.config import InstallConfig

    InstallConfig.enable_jax_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    )


def _make_cluster(rng, n_nodes, num_zones, *, cpu=(8, 96), mem=(16, 256), gpu=(0, 2)):
    import jax

    from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF

    avail = np.empty((n_nodes, 3), np.int32)
    avail[:, 0] = rng.integers(*cpu, size=n_nodes)
    avail[:, 1] = rng.integers(*mem, size=n_nodes)
    avail[:, 2] = rng.integers(*gpu, size=n_nodes)
    return jax.device_put(
        ClusterTensors(
            available=avail,
            schedulable=avail.copy(),
            zone_id=rng.integers(0, num_zones, size=n_nodes).astype(np.int32),
            name_rank=rng.permutation(n_nodes).astype(np.int32),
            label_rank_driver=np.full(n_nodes, INT32_INF, np.int32),
            label_rank_executor=np.full(n_nodes, INT32_INF, np.int32),
            unschedulable=np.zeros(n_nodes, bool),
            ready=np.ones(n_nodes, bool),
            valid=np.ones(n_nodes, bool),
        )
    )


def _make_batches(rng, n_apps, window, emax, *, exec_count=None, skippable=True):
    import jax

    from spark_scheduler_tpu.ops.batched import make_app_batch

    driver = rng.integers(1, 4, size=(n_apps, 3)).astype(np.int32)
    driver[:, 2] = 0
    execs = rng.integers(1, 6, size=(n_apps, 3)).astype(np.int32)
    execs[:, 2] = 0
    if exec_count is None:
        counts = rng.integers(1, emax + 1, size=n_apps).astype(np.int32)
    else:
        counts = np.full(n_apps, exec_count, np.int32)
    return [
        jax.device_put(
            make_app_batch(
                driver[lo : lo + window],
                execs[lo : lo + window],
                counts[lo : lo + window],
                skippable=np.full(min(window, n_apps - lo), skippable, bool),
            )
        )
        for lo in range(0, n_apps, window)
    ]


def _measure_marginal_ms(chain, n_batches, k_short=2, repeats=5):
    """p50 of the marginal per-window time of a dependent device chain.

    The chain-length spread is ADAPTIVE: tunnel RPC jitter is tens of ms
    per call, so the long chain is sized until its delta over the short
    chain dominates jitter (>= ~400 ms of device work over >= 30 windows),
    else fast windows (a few ms) drown in noise and the marginal is
    jitter-dominated (observed: a 10 ms/window config swinging 9-50 ms
    run-to-run with a 10-window spread)."""
    chain(max(12, n_batches))  # compile + warm (also the correctness run)

    def timed(k):
        t0 = time.perf_counter()
        chain(k)
        return time.perf_counter() - t0

    # Crude per-window estimate to size the spread.
    t2 = min(timed(k_short) for _ in range(2))
    k_long = k_short + 30
    while True:
        t_long = min(timed(k_long) for _ in range(2))
        if t_long - t2 >= 0.4 or k_long >= 512:
            break
        k_long = min(512, k_long * 4)

    marginals_ms = []
    for _ in range(repeats):
        t_short = min(timed(k_short) for _ in range(2))
        t_long = min(timed(k_long) for _ in range(2))
        marginals_ms.append((t_long - t_short) * 1e3 / (k_long - k_short))
    return float(np.percentile(marginals_ms, 50))


# Every metric of the run, compact, for the final self-contained summary
# line (VERDICT r3 #6: the driver records the output TAIL; individual
# metric lines earlier in the run may not survive truncation).
_RESULTS: list = []


def _record(metric, value, unit, vs_baseline, detail=None):
    entry = {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }
    if metric.startswith(("serving", "fleet")):
        # Fleet-era serving lines declare their topology: how many
        # clusters served the load and how many gangs spilled to a
        # sibling. Single-cluster sections are explicitly 1/0; the fleet
        # sections override via their own entries.
        entry["clusters"] = (detail or {}).get("clusters", 1)
        entry["spillovers"] = (detail or {}).get("spillovers", 0)
    if detail is not None:
        # Per-metric detail rides into the FINAL all-metrics line so the
        # driver's truncated output tail still proves bench rigor
        # (windows_measured, per-repeat bands, path counts — VERDICT r4 #5).
        entry["detail"] = detail
    _RESULTS.append(entry)


def _emit(metric, window_ms, window_apps, extra=None):
    import jax

    per_app = window_ms / window_apps
    detail = {
        "window_apps": window_apps,
        "per_app_ms": round(per_app, 4),
        "decisions_per_s": round(window_apps / (window_ms / 1e3), 1),
        "device": str(jax.devices()[0]),
        **(extra or {}),
    }
    _record(
        metric, round(window_ms, 3), "ms", round(TARGET_MS / window_ms, 2),
        detail=detail,
    )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(window_ms, 3),
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / window_ms, 2),
                "detail": detail,
            }
        ),
        flush=True,
    )


def _windowed_chain(cluster, batches, fill, emax, num_zones, *, force_xla=False):
    """Queue-mode solves route through fifo_pack_auto: the Pallas VMEM-
    resident kernel on TPU (ops/pallas_fifo.py), the XLA scan elsewhere —
    the routing the public queue-admission API applies. (The serving path's
    segmented windows re-sort per segment and always use the XLA scan.)

    The force_xla arm threads the availability through the DONATED carry
    entry (ops/batched.batched_fifo_pack_carry): available_after reuses
    the carry buffer in place — the same double-buffer discipline the
    pipelined serving engine runs — instead of a copy-on-write [N, 3]
    clone per window."""
    import jax
    import jax.numpy as jnp

    from spark_scheduler_tpu.ops.pallas_fifo import fifo_pack_auto

    if force_xla:
        from spark_scheduler_tpu.models.cluster import cluster_statics
        from spark_scheduler_tpu.ops.batched import batched_fifo_pack_carry

        statics = cluster_statics(cluster)

        def chain(k):
            # Fresh device copy per chain: each window DONATES the carry,
            # so the caller-owned starting availability must not be
            # consumed across chain() invocations.
            avail = jnp.array(cluster.available, copy=True)
            admitted = []
            for i in range(k):
                out = batched_fifo_pack_carry(
                    avail, statics, batches[i % len(batches)],
                    fill=fill, emax=emax, num_zones=num_zones,
                )
                avail = out.available_after
                admitted.append(out.admitted)
            return np.asarray(jax.numpy.concatenate(admitted))

        return chain

    def chain(k):
        c = cluster
        admitted = []
        for i in range(k):
            out = fifo_pack_auto(
                c, batches[i % len(batches)], fill=fill, emax=emax,
                num_zones=num_zones, prefer_pallas=not force_xla,
            )
            c = dataclasses.replace(c, available=out.available_after)
            admitted.append(out.admitted)
        return np.asarray(jax.numpy.concatenate(admitted))  # forces the chain

    return chain


def bench_config1(rng):
    """#1: 1 driver + 8 executors on 10 nodes, tightly-pack — the
    examples/extender.yml smoke shape, timed as a B=1 admission window."""
    cluster = _make_cluster(rng, 10, 4)
    batches = _make_batches(rng, 12, 1, 8, exec_count=8)
    chain = _windowed_chain(cluster, batches, "tightly-pack", 8, 4)
    ms = _measure_marginal_ms(chain, len(batches))
    _emit("config1_small_gang_service_ms_10_nodes", ms, 1, {"nodes": 10})


def bench_config2(rng):
    """#2: 100 FIFO drivers x 8 executors, 500 nodes, distribute-evenly,
    skippable=False — strict-FIFO blocking engaged (resource.go:241-249)."""
    cluster = _make_cluster(rng, 500, 4)
    batches = _make_batches(rng, 1200, 100, 8, exec_count=8, skippable=False)
    chain = _windowed_chain(cluster, batches, "distribute-evenly", 8, 4)
    ms = _measure_marginal_ms(chain, len(batches))
    _emit(
        "config2_fifo100_window_service_ms_500_nodes",
        ms,
        100,
        {"nodes": 500, "strict_fifo": True, "fill": "distribute-evenly"},
    )


def bench_config2_az_aware(rng):
    """#2b (VERDICT r2 #2 done-criterion): the same 100-driver FIFO window
    with the az-aware single-AZ strategy — per-zone pack + efficiency-scored
    zone selection INSIDE the scan step — must stay within ~2x of the plain
    fills."""
    cluster = _make_cluster(rng, 500, 4)
    batches = _make_batches(rng, 1200, 100, 8, exec_count=8, skippable=False)
    chain = _windowed_chain(cluster, batches, "az-aware-tightly-pack", 8, 4)
    ms = _measure_marginal_ms(chain, len(batches))
    _emit(
        "config2b_fifo100_az_aware_window_service_ms_500_nodes",
        ms,
        100,
        {"nodes": 500, "strict_fifo": True, "fill": "az-aware-tightly-pack"},
    )


def bench_config3(rng):
    """#3: dynamic allocation min=2/max=32, 200 apps, 1k nodes. Gang
    admission reserves min executors; the reservation shells are sized max,
    so the kernel runs with emax=32 slot padding (sparkpods.go:110-138)."""
    cluster = _make_cluster(rng, 1_000, 4)
    batches = _make_batches(rng, 2_400, 200, 32, exec_count=2)
    chain = _windowed_chain(cluster, batches, "tightly-pack", 32, 4)
    ms = _measure_marginal_ms(chain, len(batches))
    _emit(
        "config3_dynalloc_window_service_ms_1k_nodes",
        ms,
        200,
        {"nodes": 1000, "min_executors": 2, "max_executors": 32},
    )


def bench_config4(rng):
    """#4: 5 instance-groups, heterogeneous node shapes, 5k nodes — one
    grouped_fifo_pack_auto over stacked per-group subproblems (per-group
    Pallas kernels on a single chip, the vmapped scan on meshes)
    (failover.go:276-313 grouping, SURVEY.md §5.7)."""
    import jax

    from spark_scheduler_tpu.parallel.mesh import make_solver_mesh
    from spark_scheduler_tpu.parallel.solve import (
        grouped_fifo_pack_auto,
        stack_groups,
    )

    shapes = [  # (cpu-range, mem-range, gpu-range) per group — heterogeneous
        ((4, 16), (8, 32), (0, 1)),
        ((8, 32), (32, 128), (0, 1)),
        ((16, 96), (64, 512), (0, 2)),
        ((8, 64), (16, 128), (1, 5)),
        ((32, 128), (128, 1024), (0, 1)),
    ]
    clusters, app_batches = [], []
    for cpu, mem, gpu in shapes:
        clusters.append(
            jax.device_get(_make_cluster(rng, 1_000, 4, cpu=cpu, mem=mem, gpu=gpu))
        )
        app_batches.append(_make_batches(rng, 40, 40, 8)[0])
    stacked_cluster, stacked_apps = stack_groups(clusters, app_batches)
    stacked_cluster = jax.device_put(stacked_cluster)
    stacked_apps = jax.device_put(stacked_apps)
    mesh = make_solver_mesh(n_groups=1)  # single chip: vmap carries the groups

    def chain(k):
        c = stacked_cluster
        admitted = []
        for _ in range(k):
            out = grouped_fifo_pack_auto(
                mesh, c, stacked_apps, fill="tightly-pack", emax=8, num_zones=4
            )
            c = dataclasses.replace(c, available=out.available_after)
            admitted.append(out.admitted)
        return np.asarray(jax.numpy.concatenate(admitted))

    ms = _measure_marginal_ms(chain, 1)
    _emit(
        "config4_5group_heterogeneous_window_service_ms_5k_nodes",
        ms,
        200,
        {"nodes": 5000, "groups": 5, "apps_per_group_window": 40},
    )


def bench_config5(rng, defer=False):
    """#5 (north star): 10k nodes x 1k apps, windows of 100 —
    the steady-state placement latency under 1k-concurrent load is the
    per-window service time (see module docstring). Served by the Pallas
    queue kernel on TPU; the XLA-scan line is reported alongside so the
    kernel-level speedup stays visible round over round.

    With defer=True, MEASURE now but return a closure that emits later:
    the headline must be the last recorded metric, but measuring it after
    the serving benches inflated it ~2x (accumulated process state +
    box heat on the 1-core rig: 4.2 ms full-bench vs 2.3 ms standalone).
    Measuring right after the parity smoke keeps the marginal-chain
    timing on a quiet process."""
    from spark_scheduler_tpu.ops.pallas_fifo import pallas_available

    n_apps, window, emax = 1_000, 100, 8
    cluster = _make_cluster(rng, 10_000, 4)
    batches = _make_batches(rng, n_apps, window, emax)

    xla_ms = None
    if pallas_available():
        # Companion line: the XLA scan on the same shapes. Skipped when the
        # backend has no Mosaic — the main line below IS the scan then, and
        # measuring the identical path twice would just double the slowest
        # bench config.
        xla_chain = _windowed_chain(
            cluster, batches, "tightly-pack", emax, 4, force_xla=True
        )
        xla_ms = _measure_marginal_ms(xla_chain, len(batches))

    chain = _windowed_chain(cluster, batches, "tightly-pack", emax, 4)
    full = chain(len(batches))
    n_admitted = int(full.sum())
    ms = _measure_marginal_ms(chain, len(batches))

    def emit():
        if xla_ms is not None:
            _emit(
                "config5_xla_scan_window_service_ms_10k_nodes_1k_apps",
                xla_ms,
                window,
                {"nodes": 10_000, "path": "lax.scan (batched_fifo_pack)"},
            )
        _emit(
            "gang_placement_p50_window_service_ms_10k_nodes_1k_apps",
            ms,
            window,
            {
                "nodes": 10_000,
                "admitted_of_1k": n_admitted,
                "path": (
                    "pallas VMEM-resident queue kernel"
                    if pallas_available()
                    else "lax.scan (pallas unavailable on this backend)"
                ),
                "xla_scan_ms": (
                    round(xla_ms, 3) if xla_ms is not None else None
                ),
                "r02_ms": 10.51,
            },
        )

    if defer:
        return emit
    emit()


def bench_config6_beyond_baseline(rng):
    """BEYOND the baseline matrix: the north-star workload at 10x the node
    scale (100k nodes x 1k apps). The Pallas queue kernel keeps the whole
    availability tensor (~1.2 MB) in VMEM, so the admission scan keeps its
    shape — demonstrating the single-chip headroom past BASELINE.md's
    largest config."""
    n_apps, window, emax = 1_000, 100, 8
    cluster = _make_cluster(rng, 100_000, 4)
    batches = _make_batches(rng, n_apps, window, emax)
    chain = _windowed_chain(cluster, batches, "tightly-pack", emax, 4)
    ms = _measure_marginal_ms(chain, len(batches))
    _emit(
        "config6_beyond_baseline_window_service_ms_100k_nodes",
        ms,
        window,
        {"nodes": 100_000, "note": "10x the baseline node scale"},
    )


def _serving_fixture(
    n_nodes=500, max_window=None, transport="threaded", ingest="python",
):
    _enable_compile_cache()
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.server.http import SchedulerHTTPServer
    from spark_scheduler_tpu.testing.harness import INSTANCE_GROUP_LABEL, new_node
    from spark_scheduler_tpu.store.backend import InMemoryBackend

    backend = InMemoryBackend()
    node_names = []
    for i in range(n_nodes):
        n = new_node(f"bench-n{i}", zone=f"zone{i % 4}")
        backend.add_node(n)
        node_names.append(n.name)
    cfg_kw = {} if max_window is None else {"predicate_max_window": max_window}
    app = build_scheduler_app(
        backend,
        InstallConfig(
            fifo=True, sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL, **cfg_kw,
        ),
    )
    # Generous request budget: the first window of each row-count bucket
    # pays an XLA compile (~tens of seconds on a remote TPU). Load shedding
    # off: a bench must measure the backlog, not refuse it.
    server = SchedulerHTTPServer(
        app, host="127.0.0.1", port=0, request_timeout_s=600.0,
        transport=transport, ingest=ingest, shed_queue_depth=0,
    )
    server.start()
    return backend, app, server, node_names


def _post_predicate(conn, driver, node_names):
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    body = json.dumps({"Pod": pod_to_k8s(driver), "NodeNames": node_names}).encode()
    t0 = time.perf_counter()
    conn.request("POST", "/predicates", body=body)
    resp = json.loads(conn.getresponse().read())
    return resp, (time.perf_counter() - t0) * 1e3


_RTT_FLOOR: dict = {}


def _prune_fields(app):
    """`pruned` + `prune_escalations` on every serving JSON line (ISSUE 10),
    plus the O(K + changed) planner evidence (ISSUE 12): per-window prune
    phase means (plan / gather / offset ms), the plan/gather reuse hits,
    and the planner's rows-scanned ledger. Default-off configs report
    {False, 0, ...zeros} — the prune A/B arms live in the
    candidate_pruning section (hack/prune_bench.py)."""
    st = getattr(app.solver, "prune_stats", None) or {}
    windows = max(int(st.get("windows", 0)), 1)
    return {
        "pruned": bool(st.get("windows")),
        "prune_escalations": int(st.get("escalations", 0)),
        "prune_plan_ms_mean": round(st.get("plan_ms", 0.0) / windows, 4),
        "prune_gather_ms_mean": round(
            st.get("gather_ms", 0.0) / windows, 4
        ),
        "prune_offset_ms_mean": round(
            st.get("offset_ms", 0.0) / windows, 4
        ),
        "prune_plan_reuse": int(st.get("plan_reuse", 0)),
        "prune_gather_reuse": int(st.get("gather_reuse", 0)),
        "prune_planner_rows_scanned": int(
            st.get("planner_rows_scanned", 0)
        ),
        "prune_planner_sweep_rows": int(
            st.get("planner_sweep_rows", 0)
        ),
    }


def _build_fields(app) -> dict:
    """`build_ms` + the mirror-sync row ledgers on every serving JSON line
    (ISSUE 13): the per-window tensor-build wall time, rows the DENSE
    mirror sweep examined (0 in steady state — the O(changed) claim as a
    counter), and rows the event-fed dirty-set sync examined instead."""
    st = getattr(app.solver, "build_stats", None) or {}
    builds = max(int(st.get("builds", 0)), 1)
    return {
        "build_ms": round(st.get("build_ms", 0.0) / builds, 4),
        "mirror_rows_compared": int(st.get("mirror_rows_compared", 0)),
        # ISSUE 15: the dense-sweep event count and the device-pool size
        # on every serving line — the pooled sparse-debit claim (0 dense
        # syncs at any pool size) rides the same trajectory fields.
        "mirror_dense_syncs": int(st.get("mirror_dense_syncs", 0)),
        "pool": int(getattr(app.solver, "pool_size", 1)),
        "pooled_debit_rows": int(st.get("pooled_debit_rows", 0)),
        "build_dirty_rows": int(st.get("dirty_rows", 0)),
        "build_incremental": int(st.get("incremental_builds", 0)),
        "build_full_snapshots": int(st.get("full_snapshots", 0)),
    }


def _scale_fields(app, n_nodes) -> dict:
    """`n_nodes` + `upload_bytes_per_event` on every serving JSON line
    (ISSUE 11): the roster size the section served at, and the average
    h2d bytes per device-state upload event (full blobs + availability
    deltas + static row-deltas) — the number the million-node tier drives
    to O(changed). The BENCH_* trajectory tracks this tier across rounds
    on these two fields."""
    st = getattr(app.solver, "device_state_stats", None) or {}
    events = (
        st.get("full_uploads", 0)
        + st.get("delta_uploads", 0)
        + st.get("static_delta_uploads", 0)
    )
    return {
        "n_nodes": int(n_nodes),
        "upload_bytes_per_event": (
            round(st.get("upload_bytes", 0) / events, 1) if events else 0.0
        ),
    }


def _device_rtt_floor_ms() -> float:
    """One minimal device round trip (dispatch + pull a scalar), p50 of 7.
    Over this environment's tunneled TPU this alone exceeds the 50 ms
    latency target — EVERY serving section reports it so per-request
    latencies read against the transport floor, not against zero.
    Memoized per process (the floor is a property of the link)."""
    if "ms" in _RTT_FLOOR:
        return _RTT_FLOOR["ms"]
    import jax
    import jax.numpy as jnp

    samples = []
    x = jax.device_put(jnp.zeros(1, jnp.int32))
    for _ in range(7):
        t0 = time.perf_counter()
        np.asarray(x + 1)
        samples.append((time.perf_counter() - t0) * 1e3)
    _RTT_FLOOR["ms"] = round(float(np.percentile(samples, 50)), 2)
    return _RTT_FLOOR["ms"]


def _recorder_phase_stats(app) -> dict:
    """Per-phase device/host timings of the decisions a serving section
    actually served, pulled from the flight recorder's ring: p50 of
    featurize (host tensor build), solve (device dispatch->decisions), and
    commit (reservation write-back). Every serving section reports these
    so a latency number decomposes without a profiler run."""
    recorder = getattr(app, "recorder", None)
    if recorder is None:
        return {}
    out = {}
    records = recorder.query(limit=recorder.capacity)
    for phase in (
        "featurize_ms",
        "featurize_snapshot_ms",
        "featurize_tensors_ms",
        "featurize_domains_ms",
        "featurize_fifo_ms",
        "solve_ms",
        "commit_ms",
    ):
        vals = [
            r["phases"][phase]
            for r in records
            if r.get("phases", {}).get(phase) is not None
        ]
        if vals:
            out[f"{phase[:-3]}_p50_ms"] = round(
                float(np.percentile(vals, 50)), 3
            )
    return out


def bench_serving_http(rng, transport="threaded", ingest="python"):
    """Wall-clock p50 of the SERVED path with a SINGLE sequential client:
    POST /predicates -> extender -> batched solver -> reservation
    write-back, over a 500-node cluster. Includes host tensor deltas,
    device dispatch, and (on tunneled TPU) the relay RPC — the end-to-end
    number an idle kube-scheduler sees per call. Runs per transport
    (threaded | async) so the A/B is measured on the same box."""
    import http.client

    from spark_scheduler_tpu.testing.harness import static_allocation_spark_pods

    backend, app, server, node_names = _serving_fixture(
        transport=transport, ingest=ingest
    )
    ingest_lane = server.ingest_name  # post-degrade: what actually served
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
    latencies_ms = []
    n_requests, warmup = 40, 6
    try:
        for i in range(n_requests):
            driver = static_allocation_spark_pods(f"bench-app-{i}", 8)[0]
            backend.add_pod(driver)
            resp, dt_ms = _post_predicate(conn, driver, node_names)
            if not resp.get("NodeNames"):
                raise RuntimeError(f"bench request {i} failed: {resp}")
            if i >= warmup:
                latencies_ms.append(dt_ms)
            backend.bind_pod(driver, resp["NodeNames"][0])
    finally:
        conn.close()
        dev_stats = dict(app.solver.device_state_stats)
        phase_stats = _recorder_phase_stats(app)
        batcher_fuse = server.batcher.stats()["fuse_windows"]
        server.stop()
    p50 = float(np.percentile(latencies_ms, 50))
    suffix = "" if transport == "threaded" else f"_{transport}"
    if ingest != "python":
        suffix = f"{suffix}_{ingest}"
    _emit(
        f"serving_http_predicate_p50_ms_500_nodes{suffix}",
        p50,
        1,
        {
            "nodes": 500,
            "transport": transport,
            "ingest": ingest_lane,
            "requests": len(latencies_ms),
            "p95_ms": round(float(np.percentile(latencies_ms, 95)), 3),
            "path": "HTTP /predicates -> batched admission -> write-back",
            # Cluster state is device-resident (delta row scatter rides the
            # async dispatch); the one BLOCKING round trip per request is
            # the decision pull (VERDICT r2 #3).
            "device_round_trips_per_request": 1,
            "device_state": dev_stats,
            "device_rtt_floor_ms": _device_rtt_floor_ms(),
            "device_phases": phase_stats,
            # Windows per device dispatch this section ran with (1 =
            # unfused; the fused A/B lives in the fused_dispatch section).
            "fused_k": batcher_fuse,
            **_prune_fields(app),
            **_build_fields(app),
            **_scale_fields(app, 500),
            "r02_ms": 119.68,
        },
    )


def _threaded_phase(port, backend, client_sequences):
    """One load phase: a thread per client, PREBUILT request bodies, pod
    lifecycle via direct backend calls (dict ops — what the watch stream
    would deliver). Measured alternatives on this 2-core box: process-per-
    client and persistent worker processes both lose 30-50% to scheduling
    and fork overhead; colocated threads that mostly block on sockets are
    the cheapest honest load generator here. Client-side pod construction
    and JSON serialization happen before the clock starts — a real
    kube-scheduler never routes its own cost through this process."""
    import http.client
    import threading

    lats: list = []
    errs: list = []
    lock = threading.Lock()

    def client(rows):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
            for pod, body in rows:
                backend.add_pod(pod)
                t0 = time.perf_counter()
                conn.request("POST", "/predicates", body=body)
                resp = json.loads(conn.getresponse().read())
                dt_ms = (time.perf_counter() - t0) * 1e3
                nodes = resp.get("NodeNames") or []
                if not nodes:
                    raise RuntimeError(f"{pod.name} failed: {resp}")
                backend.bind_pod(pod, nodes[0])
                with lock:
                    lats.append(dt_ms)
            conn.close()
        except Exception as exc:  # surfaced after join
            errs.append(exc)

    threads = [
        threading.Thread(target=client, args=(rows,))
        for rows in client_sequences
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    if errs:
        raise errs[0]
    return lats, wall_s


def _driver_rows(phase, n_clients, rounds, node_names, execs=8):
    """Per-client [(driver pod, prebuilt /predicates body)] sequences."""
    from spark_scheduler_tpu.server.kube_io import pod_to_k8s
    from spark_scheduler_tpu.testing.harness import static_allocation_spark_pods

    out = []
    for ci in range(n_clients):
        rows = []
        for r in range(rounds):
            driver = static_allocation_spark_pods(
                f"cb-{phase}-{ci}-{r}", execs
            )[0]
            body = json.dumps(
                {"Pod": pod_to_k8s(driver), "NodeNames": node_names}
            ).encode()
            rows.append((driver, body))
        out.append(rows)
    return out


def _reset_cluster_state(backend, app):
    """Between bench repeats: delete every reservation, demand, and pod
    through the same caches the scheduler writes, so listener-maintained
    aggregates (usage tracker, overhead) stay consistent and the next
    repeat starts from an empty 500-node cluster."""
    for rr in list(backend.list("resourcereservations")):
        app.rr_cache.delete(rr.namespace, rr.name)
    for d in list(backend.list("demands")):
        app.demand_cache.delete(d.namespace, d.name)
    for pod in list(backend.list_pods()):
        backend.delete_pod(pod)


def bench_serving_http_concurrent(rng, transport="threaded"):
    """The VERDICT r2 #1 metric: CONCURRENT clients against /predicates.
    The PredicateBatcher coalesces whatever arrives while the previous
    window solves into one pack_window device program; the pipelined
    dispatch-before-fetch loop overlaps window solves with decision pulls.
    Load: colocated client threads with prebuilt bodies (_threaded_phase —
    measured cheaper than any process-based generator on this 2-core box).
    k repeats from a reset cluster give ≥50 measured windows and a
    run-to-run variance band (VERDICT r3 #7).

    Capacity: every app reserves 9 CPU / 9 Gi on an 8x500 = 4000 CPU
    cluster; each repeat admits (2+8)x32 = 320 gangs = 2880 CPU (72%)
    and then RESETS, leaving strict-FIFO hypothetical-prefix headroom
    (each request re-packs all its pending earlier drivers —
    resource.go:221-258 semantics)."""
    _bench_serving_concurrent(
        rng, n_nodes=500, n_clients=32, per_client=8, warmup_rounds=2,
        repeats=3, suffix="500_nodes", transport=transport,
    )


def bench_serving_http_concurrent_10k(rng, transport="threaded", ingest="python"):
    """VERDICT r4 #1: the SERVED system at north-star scale. Every serving
    metric before r5 was captured at 500 nodes; the 10k-node 26x number was
    kernel-only. This drives 1000 driver gang admissions over HTTP against
    a 10,000-node cluster — real batcher, pipelined windows, write-back,
    ~100-request windows (predicate_max_window=128) — and asserts no node
    ended over-committed. Done-bar: >= 100 decisions/s, p50 <= 300 ms."""
    _bench_serving_concurrent(
        rng, n_nodes=10_000, n_clients=100, per_client=5, warmup_rounds=1,
        repeats=2, suffix="10k_nodes", max_window=128,
        inprocess_control=(transport == "threaded" and ingest == "python"),
        transport=transport, ingest=ingest,
    )


def bench_serving_http_concurrent_64c(rng, transport="threaded"):
    """The windowed design's intended regime: MORE concurrency per core.
    At 64 colocated clients the mean window doubles (16 vs 7.8 at 32
    clients) and both throughput AND p50 improve — amortization beats
    queueing. Kept alongside the 32-client config (the round-over-round
    comparable) so the artifact shows the windowing thesis directly."""
    # warmup_rounds=1: (1+4)x64 = 320 gangs = 2880 of 4000 CPU per repeat —
    # the same 72% budget as the 32-client config. A second warmup round
    # would push 86% and strict-FIFO hypothetical prefixes (each request
    # re-packs its pending earlier drivers) overflow the cluster.
    _bench_serving_concurrent(
        rng, n_nodes=500, n_clients=64, per_client=4, warmup_rounds=1,
        repeats=3, suffix="500_nodes_64_clients", transport=transport,
    )


def _bench_serving_concurrent(
    rng, *, n_nodes, n_clients, per_client, warmup_rounds, repeats, suffix,
    max_window=None, inprocess_control=False, transport="threaded",
    ingest="python",
):
    if transport != "threaded":
        suffix = f"{suffix}_{transport}"
    if ingest != "python":
        suffix = f"{suffix}_{ingest}"
    backend, app, server, node_names = _serving_fixture(
        n_nodes, max_window=max_window, transport=transport, ingest=ingest
    )
    ingest_lane = server.ingest_name  # post-degrade: what actually served

    def precompile_window_buckets():
        """Force the device compiles for every window SHAPE BUCKET the run
        can hit, so measurement never stalls on a fresh compile (a real
        deployment pre-warms the same way; the compiles persist in the
        .jax_cache across processes).

        The Pallas window path buckets a window of S requests x R max rows
        to (s_pad in 4*8^k, r_pad in 16*4^k) — see
        solver._build_segmented_window. Under FIFO a request re-packs all
        its PENDING earlier drivers, so live row depth reaches the
        in-flight client count and S reaches the batcher max window:
        enumerate the full (s_pad, r_pad) grid up to those bounds (an
        earlier version warmed only a handful of flat row-count buckets,
        missed the deep-row shapes, and the 10k run ate several 20-40 s
        mid-measurement compiles — p95 blew out to 42 s)."""
        from spark_scheduler_tpu.core.solver import WindowRequest
        from spark_scheduler_tpu.models.resources import Resources

        solver = app.solver
        tensors = solver.build_tensors_cached(backend.list_nodes(), {}, {})
        one = Resources.from_quantities("1", "1Gi")
        window_cap = max_window or 32  # batcher default max_window
        s_buckets = []
        s = 4
        while True:
            s_buckets.append(s)
            if s >= window_cap:
                break
            s *= 8
        # Max FIFO row depth ~= in-flight clients (every earlier pending
        # driver is a hypothetical row) + the request's own row.
        r_buckets = []
        r = 16
        while True:
            r_buckets.append(r)
            if r >= n_clients + 1:
                break
            r *= 4
        for s_pad in s_buckets:
            for r_pad in r_buckets:
                reqs = [
                    WindowRequest(
                        rows=[(one, one, 8, True)] * (r_pad - 1)
                        + [(one, one, 8, False)],
                        driver_candidate_names=node_names,
                    )
                    for _ in range(s_pad)
                ]
                solver.pack_window("tightly-pack", tensors, reqs)

    from spark_scheduler_tpu.tracing import tracer

    lats: list = []
    repeat_dps: list = []
    repeat_walls: list = []
    solve_spans: list = []
    run_windows = 0
    try:
        precompile_window_buckets()
        for rep in range(repeats):
            if rep:
                _reset_cluster_state(backend, app)
            _threaded_phase(
                server.port, backend,
                _driver_rows(f"w{rep}", n_clients, warmup_rounds, node_names),
            )
            tracer().clear()  # only run-phase solve spans
            windows_before = server.batcher.windows_served
            rep_lats, rep_wall = _threaded_phase(
                server.port, backend,
                _driver_rows(f"r{rep}", n_clients, per_client, node_names),
            )
            # Exact run-phase window count from the batcher (the tracer's
            # span ring evicts under load and would undercount).
            run_windows += server.batcher.windows_served - windows_before
            lats.extend(rep_lats)
            repeat_dps.append(n_clients * per_client / rep_wall)
            repeat_walls.append(rep_wall)
            solve_spans.extend(
                s for s in tracer().finished_spans() if s["name"] == "solve"
            )
        # In-process control at the same scale: windows of driver gang
        # admissions through the REAL windowed path (dispatch/complete on
        # the live app — reservations, overhead, epoch machinery, write
        # caches) with no HTTP framing, so the artifact separates the
        # scheduler's decision rate from the 1-core rig's request rate.
        # Before server.stop() (stop closes the solver).
        inproc = None
        if inprocess_control:
            from spark_scheduler_tpu.core.extender import ExtenderArgs
            from spark_scheduler_tpu.testing.harness import (
                static_allocation_spark_pods,
            )

            ext = app.extender
            window, n_windows = 32, 10

            def dispatch_window(tag, k):
                drivers = []
                for j in range(window):
                    pods = static_allocation_spark_pods(
                        f"inw-{tag}-{k}-{j}", 8
                    )
                    backend.add_pod(pods[0])
                    drivers.append(pods[0])
                return drivers, ext.predicate_window_dispatch(
                    [
                        ExtenderArgs(pod=d, node_names=list(node_names))
                        for d in drivers
                    ]
                )

            def complete_window(drivers, t):
                results = ext.predicate_window_complete(t)
                for d, r in zip(drivers, results):
                    if not r.node_names:
                        raise RuntimeError(f"{d.name}: {r.outcome}")
                    backend.bind_pod(d, r.node_names[0])

            # PIPELINED like the serving batcher: dispatch k+1 before
            # completing k. One window ahead is enough — the decision pull
            # starts EAGERLY on the fetch pool at dispatch time, so by the
            # time k completes its blob has had a full window cycle on the
            # wire; deeper pipelines measured no better (each unfetched
            # prior adds reconstruction work at fetch, A/B'd depth 1 vs 3
            # under matched tunnel conditions).
            complete_window(*dispatch_window("warm", 0))
            t0 = time.perf_counter()
            prev = dispatch_window("run", 0)
            for k in range(1, n_windows):
                nxt = dispatch_window("run", k)
                complete_window(*prev)
                prev = nxt
            complete_window(*prev)
            inproc_wall = time.perf_counter() - t0
            inproc = {
                "decisions_per_s": round(window * n_windows / inproc_wall, 1),
                "windows_of": window,
                "windows": n_windows,
                "transport": "none",
                "ingest": "none",
                "pipelined": True,
                "fused_k": 1,
                "path": (
                    "predicate_window_dispatch/complete, no HTTP framing"
                ),
            }
    finally:
        stats = server.batcher.stats()
        dev_stats = dict(app.solver.device_state_stats)
        phase_stats = _recorder_phase_stats(app)
        ingest_stats = server.ingest_stats()
        server.stop()  # quiesce before the invariant walk below
    # System-level invariant at this scale: no node over-committed by the
    # reservations the run left behind (reservations + overhead <=
    # allocatable per node) — the served decisions are valid, not just
    # fast. Shared definition with the invariant soak; ENFORCED below after
    # the metrics are emitted. Success path only: a run that already raised
    # keeps its own (actionable) exception instead of a walk over
    # half-applied state chaining on top of it.
    from spark_scheduler_tpu.testing.harness import overcommit_violations

    violations = overcommit_violations(app, backend)
    overcommitted = len({name for name, _ in violations})
    total = n_clients * per_client * repeats
    # Aggregate = total requests / total wall time (NOT the arithmetic mean
    # of per-repeat rates, which overstates throughput when repeats vary).
    wall_s = sum(repeat_walls)
    p50 = float(np.percentile(lats, 50))

    # Transport floor evidence: one minimal device round trip — per-request
    # latency is transport-bound over a tunneled TPU; THROUGHPUT is what
    # windowing buys (shared helper so every serving section reports it).
    rtt_floor_ms = _device_rtt_floor_ms()

    solve_p50_ms = (
        round(float(np.percentile([s["duration_ms"] for s in solve_spans], 50)), 3)
        if solve_spans
        else None
    )
    rig_ceiling, rig_err = _rig_ceiling_or_none(
        n_names=n_nodes, transport=transport
    )
    detail = {
        "nodes": n_nodes,
        "transport": transport,
        "ingest": ingest_lane,
        # Zero-copy hit ratio / decode time / fallback count on the
        # native lane; a lane marker otherwise.
        "ingest_stats": ingest_stats,
        "overcommitted_nodes": overcommitted,
        "concurrent_clients": n_clients,
        "requests": total,
        "repeats": repeats,
        "p50_ms": round(p50, 3),
        "p95_ms": round(float(np.percentile(lats, 95)), 3),
        "decisions_per_s_measured": round(total / wall_s, 1),
        # Run-to-run variance band across the k reset repeats.
        "decisions_per_s_by_repeat": [round(x, 1) for x in repeat_dps],
        "decisions_per_s_min_max": [
            round(min(repeat_dps), 1), round(max(repeat_dps), 1)
        ],
        "mean_window": stats["mean_window"],
        "max_window_seen": stats["max_window_seen"],
        "device_state": dev_stats,
        # Which device program served the windows (VERDICT r3 #3: the
        # segmented Pallas path serves /predicates on TPU).
        "window_path_counts": dict(app.solver.window_path_counts),
        "device_rtt_floor_ms": rtt_floor_ms,
        "device_phases": phase_stats,
        # Windows per device dispatch (1 = unfused serving; the fused
        # claim only engages when solver.fuse-windows > 1).
        "fused_k": stats["fuse_windows"],
        **_prune_fields(app),
        **_build_fields(app),
        **_scale_fields(app, n_nodes),
        # Same rig, null handler, SAME body size (10k-node requests carry
        # ~200 KB of node names): what the 1-core HTTP harness itself can
        # carry — decisions/s saturating this floor is a rig limit, not a
        # scheduler limit (cf. executor bench's http_rig_utilization).
        "http_rig_ceiling_req_per_s": rig_ceiling,
        **({"http_rig_ceiling_error": rig_err} if rig_err else {}),
        "host_cpus": os.cpu_count(),
        # Per-WINDOW server-side solve span (dispatch + blocking decision
        # pull actually awaited — ~0 when the pipeline hides the fetch),
        # over the spans surviving the tracer ring; the window COUNT comes
        # from the batcher and is exact.
        "window_solve_p50_ms": solve_p50_ms,
        "windows_measured": run_windows,
        "solve_spans_sampled": len(solve_spans),
        "load_generator": "colocated threads, prebuilt bodies (see _threaded_phase)",
        "path": "concurrent HTTP /predicates -> windowed pack_window solve",
        "r02": "unbatched serving: 8.4 decisions/s, p50 119.7 ms",
    }
    if inproc is not None:
        detail["inprocess_control"] = inproc
        _record(
            f"serving_inprocess_decisions_per_s_{suffix}",
            inproc["decisions_per_s"], "decisions/s",
            round(inproc["decisions_per_s"] / 100.0, 2),
            detail=inproc,
        )
        print(
            json.dumps(
                {
                    "metric": f"serving_inprocess_decisions_per_s_{suffix}",
                    "value": inproc["decisions_per_s"],
                    "unit": "decisions/s",
                    "vs_baseline": round(
                        inproc["decisions_per_s"] / 100.0, 2
                    ),
                    "clusters": 1,
                    "spillovers": 0,
                    "detail": inproc,
                }
            ),
            flush=True,
        )
    _emit(f"serving_http_concurrent_p50_ms_{suffix}", p50, 1, detail)
    # The windowing headline: decisions/s under concurrent load
    # (vs_baseline > 1 = beats the 100 decisions/s target).
    dps = total / wall_s
    _record(
        f"serving_http_concurrent_decisions_per_s_{suffix}",
        round(dps, 1), "decisions/s", round(dps / 100.0, 2),
        detail=detail,
    )
    print(
        json.dumps(
            {
                "metric": f"serving_http_concurrent_decisions_per_s_{suffix}",
                "value": round(dps, 1),
                "unit": "decisions/s",
                "vs_baseline": round(dps / 100.0, 2),
                "clusters": 1,
                "spillovers": 0,
                "detail": detail,
            }
        ),
        flush=True,
    )
    if violations:
        # Enforced AFTER the metrics are emitted so the artifact records
        # the run; a nonzero count means the served decisions broke the
        # reservations+overhead <= allocatable invariant.
        raise RuntimeError(
            f"over-committed nodes after {suffix} serving run: "
            f"{violations[:8]}"
        )


_RIG_CEILING: dict = {}


def _rig_ceiling_or_none(
    n_threads: int = 16, per: int = 30, n_names: int = 500,
    transport: str = "threaded",
) -> tuple:
    """(ceiling, None) or (None, error string). The rig ceiling is CONTEXT
    for a section's primary metrics, not a primary metric itself: a client-
    thread failure while measuring it (ADVICE r5 low #2 — it used to raise
    mid-detail-build) must not discard serving results already measured.
    Callers record the error string alongside a None ceiling instead."""
    try:
        return _http_rig_ceiling(n_threads, per, n_names, transport), None
    except Exception as exc:
        return None, f"{type(exc).__name__}: {exc}"


class _NullRoutes:
    """Zero-work route table for the async null-handler rig: the same
    canned decision the threaded null handler returns."""

    _RESP = None

    def __init__(self):
        from spark_scheduler_tpu.server.routing import Response

        self._resp = Response(200, b'{"NodeNames": ["bench-node-00000"]}')

    def handle(self, req):
        return self._resp

    def handle_nowait(self, req, respond, schedule_timeout=None):
        respond(self._resp)


def _null_server(transport: str):
    """(server_handle, port, stop_fn) for a null handler on `transport` —
    identical response bytes either way, so the ceiling A/B isolates the
    transport stack itself."""
    import threading

    if transport == "async":
        from spark_scheduler_tpu.server.transport_async import AsyncTransport

        t = AsyncTransport(_NullRoutes(), "127.0.0.1", 0, request_timeout_s=60.0)
        t.start()
        return t.port, t.stop
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Null(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            resp = b'{"NodeNames": ["bench-node-00000"]}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(resp)))
            self.end_headers()
            self.wfile.write(resp)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Null)
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    def stop():
        srv.shutdown()
        srv.server_close()

    return srv.server_address[1], stop


def _http_rig_ceiling(
    n_threads: int = 16, per: int = 30, n_names: int = 500,
    transport: str = "threaded",
) -> float:
    """Control measurement: the SAME client rig (colocated threads,
    keep-alive http.client, predicate-shaped bodies carrying `n_names`
    node names — ~10 KB at 500, ~200 KB at 10k) against a null handler
    that only reads the body and returns a canned decision — zero
    scheduler work. On a 1-core bench box the HTTP stack + client rig
    alone cap the measurable request rate; serving throughput bars must be
    read against this harness floor the same way solo p50 is read against
    the tunnel RTT floor. Measured PER TRANSPORT (the A/B the async
    event loop exists for). Memoized per (body size, transport)."""
    memo_key = ("req_per_s", n_threads, per, n_names, transport)
    if memo_key in _RIG_CEILING:
        return _RIG_CEILING[memo_key]
    import http.client
    import threading

    port, stop = _null_server(transport)
    names = [f"bench-node-{i:05d}" for i in range(n_names)]
    body = json.dumps({"Pod": {"metadata": {}}, "NodeNames": names}).encode()

    errors: list = []

    def client():
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            for _ in range(per):
                conn.request(
                    "POST", "/predicates", body,
                    {"Content-Type": "application/json"},
                )
                conn.getresponse().read()
            conn.close()
        except Exception as exc:  # fail LOUDLY: a silently-dead client
            errors.append(exc)    # thread would skew the memoized ceiling
            raise

    ths = [threading.Thread(target=client) for _ in range(n_threads)]
    t0 = time.perf_counter()
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    wall = time.perf_counter() - t0
    stop()
    if errors:
        raise RuntimeError(f"rig-ceiling client failed: {errors[0]!r}")
    _RIG_CEILING[memo_key] = round(n_threads * per / wall, 1)
    return _RIG_CEILING[memo_key]


def bench_transport_rig_ceiling(rng):
    """The tentpole A/B headline: the null-handler rig ceiling per
    transport, same client rig, same 500-name predicate bodies. The async
    line's vs_baseline is (async / threaded) / 2 — >= 1 means the event
    loop at least DOUBLED the ceiling the served path was saturating."""
    threaded = _http_rig_ceiling(transport="threaded")
    async_ = _http_rig_ceiling(transport="async")
    ratio = round(async_ / threaded, 2) if threaded else None
    for transport, value, vs in (
        ("threaded", threaded, 1.0),
        ("async", async_, round((ratio or 0.0) / 2.0, 2)),
    ):
        entry = {
            "metric": f"http_rig_ceiling_req_per_s_{transport}",
            "value": value,
            "unit": "req/s",
            "vs_baseline": vs,
            "detail": {
                "transport": transport,
                "ingest": "python",
                "async_over_threaded": ratio,
                "clients": 16,
                "body": "predicate-shaped, 500 node names",
                "path": "null handler: read body, canned decision",
                "r05_threaded": 372.4,
            },
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_ingest_decode(rng):
    """Ingest hot path in isolation, no server: turn a 10k-name predicate
    body (~200 KB — the north-star wire shape) into (pod, node_names) via
    (a) the python lane (json.loads + extender_args_from_k8s), (b) the
    native JSON fast path, (c) the native binary protocol. CPU-only and
    seconds-cheap, so the lane A/B lands in every round's artifact even
    where the full 10k serving sections are solve-bound (this container's
    CPU backend). Skips to a recorded zero when the toolchain is absent."""
    from spark_scheduler_tpu import native
    from spark_scheduler_tpu.server import ingest as ingest_mod
    from spark_scheduler_tpu.server.kube_io import (
        extender_args_from_k8s,
        pod_to_k8s,
    )
    from spark_scheduler_tpu.testing.harness import (
        static_allocation_spark_pods,
    )

    names = [f"bench-node-{i:05d}" for i in range(10_000)]
    driver = static_allocation_spark_pods("ingest-bench", 8)[0]
    pod_raw = pod_to_k8s(driver)
    body_json = json.dumps({"Pod": pod_raw, "NodeNames": names}).encode()
    body_bin = ingest_mod.encode_predicate_binary(pod_raw, names)
    reps = 30

    def timed(fn):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e3  # ms/request

    python_ms = timed(lambda: extender_args_from_k8s(json.loads(body_json)))
    arms = {"python_json": python_ms}
    if native.available():
        codec = ingest_mod.NativeIngestCodec()

        def native_json():
            assert codec.decode_predicate_body(body_json, binary=False)

        def native_bin():
            assert codec.decode_predicate_body(body_bin, binary=True)

        arms["native_json"] = timed(native_json)
        arms["native_binary"] = timed(native_bin)
    for arm, ms in arms.items():
        speedup = round(python_ms / ms, 1) if ms else None
        entry = {
            "metric": f"ingest_decode_10k_names_ms_{arm}",
            "value": round(ms, 3),
            "unit": "ms",
            # Bar: the python lane itself is the 1.0 reference.
            "vs_baseline": speedup,
            "detail": {
                "names": len(names),
                "body_bytes": len(
                    body_bin if arm == "native_binary" else body_json
                ),
                "repeats": reps,
                "speedup_vs_python": speedup,
                "native_available": native.available(),
                "path": "predicate body -> (pod, node_names) ticket",
            },
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_serving_http_executors(rng, transport="threaded"):
    """Executor binding throughput: after a driver's gang admission, every
    executor request walks the reservation ladder (already-bound / unbound /
    reschedule, resource.go:376-428) — host-side state work with no device
    solve in the common case. Concurrent executor requests ride the same
    predicate batcher; this measures the served executor path end to end.

    Alongside the HTTP number the bench emits two controls: the null-handler
    rig ceiling (_http_rig_ceiling) and an IN-PROCESS binding phase — the
    same extender/stores/windowed path, no HTTP framing — so the artifact
    separates what the scheduler can bind from what the 1-core bench rig
    can carry."""
    import http.client

    from spark_scheduler_tpu.testing.harness import static_allocation_spark_pods

    from spark_scheduler_tpu.server.kube_io import pod_to_k8s

    backend, app, server, node_names = _serving_fixture(transport=transport)
    server_ingest_lane = server.ingest_name
    n_apps, execs_per_app, n_workers = 8, 16, 16
    exec_pods = []
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=600)
    for i in range(n_apps):
        pods = static_allocation_spark_pods(f"exb-{i}", execs_per_app)
        backend.add_pod(pods[0])
        resp, _ = _post_predicate(conn, pods[0], node_names)
        if not resp.get("NodeNames"):
            raise RuntimeError(f"driver exb-{i} failed: {resp}")
        backend.bind_pod(pods[0], resp["NodeNames"][0])
        exec_pods.extend(pods[1:])
    conn.close()

    # Prebuilt bodies + thread-per-worker (see _threaded_phase).
    sequences = [
        [
            (
                p,
                json.dumps(
                    {"Pod": pod_to_k8s(p), "NodeNames": node_names}
                ).encode(),
            )
            for p in exec_pods[i::n_workers]
        ]
        for i in range(n_workers)
    ]
    inproc_bps = None
    try:
        lats, wall_s = _threaded_phase(server.port, backend, sequences)
        # In-process control: bind another fleet of executors through the
        # REAL windowed path (predicate_window_dispatch/complete on the
        # same live app + stores) with no HTTP framing. Runs before
        # server.stop() (stop closes the solver). Threaded arm only — the
        # control has no transport in it and would just repeat.
        from spark_scheduler_tpu.core.extender import ExtenderArgs

        ext = app.extender
        inproc_pods = []
        if transport == "threaded":
            for i in range(n_apps):
                pods = static_allocation_spark_pods(f"exi-{i}", execs_per_app)
                backend.add_pod(pods[0])
                r = ext.predicate(
                    ExtenderArgs(pod=pods[0], node_names=list(node_names))
                )
                if not r.node_names:
                    raise RuntimeError(f"driver exi-{i} failed: {r.outcome}")
                backend.bind_pod(pods[0], r.node_names[0])
                inproc_pods.extend(pods[1:])

        def bind_window(pods):
            for p in pods:
                backend.add_pod(p)
            t = ext.predicate_window_dispatch(
                [
                    ExtenderArgs(pod=p, node_names=list(node_names))
                    for p in pods
                ]
            )
            results = ext.predicate_window_complete(t)
            for p, r in zip(pods, results):
                if not r.node_names:
                    raise RuntimeError(f"{p.name}: {r.outcome}")
                backend.bind_pod(p, r.node_names[0])

        window = n_workers
        if transport == "threaded":
            bind_window(inproc_pods[:window])  # warm
            rest = inproc_pods[window:]
            t0 = time.perf_counter()
            for i in range(0, len(rest), window):
                bind_window(rest[i : i + window])
            inproc_wall = time.perf_counter() - t0
            inproc_bps = round(len(rest) / inproc_wall, 1)
    finally:
        phase_stats = _recorder_phase_stats(app)
        server.stop()
    rig_ceiling, rig_err = _rig_ceiling_or_none(transport=transport)
    p50 = float(np.percentile(lats, 50))
    bps = len(lats) / wall_s
    msuffix = "" if transport == "threaded" else f"_{transport}"
    detail = {
        "nodes": 500,
        "transport": transport,
        "ingest": server_ingest_lane,
        "executors": len(lats),
        "p95_ms": round(float(np.percentile(lats, 95)), 3),
        "bindings_per_s": round(bps, 1),
        "device_rtt_floor_ms": _device_rtt_floor_ms(),
        "device_phases": phase_stats,
        # Same rig, null handler: the 1-core HTTP harness floor the HTTP
        # number saturates (bindings_per_s / ceiling = scheduler share).
        "http_rig_ceiling_req_per_s": rig_ceiling,
        **({"http_rig_ceiling_error": rig_err} if rig_err else {}),
        "http_rig_utilization": (
            round(bps / rig_ceiling, 3) if rig_ceiling else None
        ),
        "host_cpus": os.cpu_count(),
        "fused_k": 1,  # executor ladder is host-side; no fused dispatch
        **_prune_fields(app),
        **_build_fields(app),
        **_scale_fields(app, 500),
        "load_generator": "colocated threads, prebuilt bodies (see _threaded_phase)",
        "path": "concurrent executor /predicates -> reservation ladder (host-side)",
    }
    _emit(
        f"serving_http_executor_p50_ms_500_nodes{msuffix}",
        p50,
        1,
        detail,
    )
    if inproc_bps is None:
        return
    # The scheduler-side capability, free of the rig floor: the same
    # windowed executor path in process.
    _record(
        "serving_executor_bindings_per_s_inprocess_500_nodes",
        inproc_bps, "bindings/s", round(inproc_bps / 500.0, 2),
        detail={
            "windows_of": window,
            "executors": len(rest),
            "transport": "none",
            "ingest": "none",
            "path": "predicate_window_dispatch/complete, no HTTP framing",
            "target": "VERDICT r4 #2: >= 500 bindings/s",
        },
    )
    print(
        json.dumps(
            {
                "metric": "serving_executor_bindings_per_s_inprocess_500_nodes",
                "value": inproc_bps,
                "unit": "bindings/s",
                "vs_baseline": round(inproc_bps / 500.0, 2),
                "clusters": 1,
                "spillovers": 0,
                "detail": {"windows_of": window, "executors": len(rest)},
            }
        ),
        flush=True,
    )


def bench_host_featurize(rng):
    """The feature store's O(changed) claim, MEASURED: per-window host
    featurize (feature snapshot + host tensor build) at 1k/10k/100k nodes,
    three arms per size —

      cold    a node event forced the O(nodes) roster re-walk;
      steady  50 incremental reservation events land between windows but
              no node churn (the serving steady state): the snapshot
              serves the resident roster and re-copies only the dirty
              usage aggregate;
      legacy  the pre-feature-store per-window rebuild (full list_nodes +
              fresh {name: node} dict + per-node overhead dict copies +
              usage array walk + tensor build), run against the same live
              components.

    Host-only (build_tensors builds numpy tensors; no device dispatch) —
    this is exactly the host layer the pipelined serving loop pays per
    window. Bar (ISSUE 5): steady-state p50 at 10k nodes >= 5x faster
    than the legacy rebuild."""
    from spark_scheduler_tpu.models.kube import Container, Pod
    from spark_scheduler_tpu.models.resources import Resources
    from spark_scheduler_tpu.models.reservations import (
        new_resource_reservation,
    )
    from spark_scheduler_tpu.server.app import build_scheduler_app
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )

    for n_nodes in (1_000, 10_000, 100_000):
        backend = InMemoryBackend()
        names = []
        for i in range(n_nodes):
            node = new_node(f"hf-n{i}", zone=f"zone{i % 4}")
            backend.add_node(node)
            names.append(node.name)
        # Populate the overhead aggregate (unreserved pods bound to nodes):
        # the legacy arm's per-node dict copies must have entries to copy.
        for i in range(0, n_nodes, 20):
            backend.add_pod(
                Pod(
                    name=f"hf-ov-{i}",
                    namespace="kube-system",
                    node_name=names[i],
                    scheduler_name="default-scheduler",
                    phase="Running",
                    containers=[
                        Container(
                            requests=Resources.from_quantities("100m", "64Mi")
                        )
                    ],
                )
            )
        app = build_scheduler_app(
            backend,
            InstallConfig(
                sync_writes=True, instance_group_label=INSTANCE_GROUP_LABEL
            ),
        )
        solver, store = app.solver, app.extender.features
        rrm = app.reservation_manager
        oc = app.overhead_computer

        def featurize():
            snap = store.snapshot()
            return solver.build_tensors(
                snap.nodes, snap.usage, snap.overhead,
                full_node_list=True, topo_version=snap.nodes_version,
            )

        def legacy_featurize():
            # The old per-window rebuild, faithfully: full list + dict +
            # per-node overhead copies + usage array + tensor build.
            topo = backend.nodes_version
            all_nodes = backend.list_nodes()
            _by_name = {n.name: n for n in all_nodes}
            usage = rrm.reserved_usage()
            overhead = {
                name: res.copy()
                for name, res in oc.get_overhead(all_nodes).items()
            }
            return solver.build_tensors(
                all_nodes, usage, overhead,
                full_node_list=True, topo_version=topo,
            )

        def one_reservation_event(j):
            # One incremental commit between windows: a small gang's
            # reservation lands (usage-tracker scatter, O(slots)).
            driver = static_allocation_spark_pods(f"hf-app-{n_nodes}-{j}", 2)[0]
            rr = new_resource_reservation(
                names[j % n_nodes],
                [names[(j + 1) % n_nodes], names[(j + 2) % n_nodes]],
                driver,
                Resources.from_quantities("1", "1Gi"),
                Resources.from_quantities("1", "1Gi"),
            )
            app.rr_cache.create(rr)

        reps = 20 if n_nodes <= 10_000 else 8
        featurize()  # warm: arena sync + registry interning + first copies

        steady_ms = []
        for j in range(reps + 50):
            one_reservation_event(j)
            t0 = time.perf_counter()
            featurize()
            dt = (time.perf_counter() - t0) * 1e3
            if j >= 50:  # the ISSUE's 50 incremental events are warm-up
                steady_ms.append(dt)

        cold_ms = []
        for j in range(min(reps, 8)):
            node = backend.get_node(names[j])
            backend.update("nodes", node)  # node event: roster goes dirty
            t0 = time.perf_counter()
            featurize()
            cold_ms.append((time.perf_counter() - t0) * 1e3)

        legacy_ms = []
        for _ in range(reps):
            t0 = time.perf_counter()
            legacy_featurize()
            legacy_ms.append((time.perf_counter() - t0) * 1e3)

        steady = float(np.percentile(steady_ms, 50))
        cold = float(np.percentile(cold_ms, 50))
        legacy = float(np.percentile(legacy_ms, 50))
        speedup = legacy / steady if steady > 0 else float("inf")
        label = f"{n_nodes // 1000}k"
        entry = {
            "metric": f"host_featurize_steady_p50_ms_{label}_nodes",
            "value": round(steady, 4),
            "unit": "ms",
            # At 10k nodes (the bar's scale): speedup/5 — >= 1.0 clears
            # the "steady-state featurize >= 5x over the per-window
            # rebuild" acceptance bar. Other sizes report the raw speedup.
            "vs_baseline": round(
                speedup / 5.0 if n_nodes == 10_000 else speedup, 2
            ),
            "detail": {
                "nodes": n_nodes,
                "steady_p50_ms": round(steady, 4),
                "cold_p50_ms": round(cold, 4),
                "legacy_rebuild_p50_ms": round(legacy, 4),
                "speedup_vs_legacy_rebuild": round(speedup, 2),
                "events_between_windows": 1,
                "store": store.stats(),
                "path": (
                    "feature snapshot + host tensor build, no device "
                    "dispatch"
                ),
            },
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)
        app.stop()


def bench_serving_inprocess(rng):
    """VERDICT r4 #7: the 'locally-attached accelerator pays the few-ms
    solve' claim as a measured number instead of prose. Runs the serving
    path in process against a LOCAL jax backend in a subprocess
    (hack/inprocess_bench.py) — no HTTP hop, no device tunnel — so the
    per-call cost is the solve + host cycle itself."""
    import os
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "inprocess_bench.py"
    )
    out = subprocess.run(
        [sys.executable, script],
        capture_output=True, text=True, timeout=900,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"inprocess bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    data = json.loads(lines[-1])
    data.setdefault("transport", "none")
    data.setdefault("ingest", "none")  # in-process: no serving lane in play
    data.setdefault("n_nodes", data.get("nodes", 500))
    data.setdefault("upload_bytes_per_event", None)
    p50 = data["p50_ms"]
    _record(
        "serving_inprocess_predicate_p50_ms_500_nodes",
        p50, "ms", round(TARGET_MS / p50, 2), detail=data,
    )
    print(
        json.dumps(
            {
                "metric": "serving_inprocess_predicate_p50_ms_500_nodes",
                "value": p50,
                "unit": "ms",
                "vs_baseline": round(TARGET_MS / p50, 2),
                "clusters": 1,
                "spillovers": 0,
                "detail": data,
            }
        ),
        flush=True,
    )


def bench_multi_device_serving(rng):
    """The multi-device window-solve engine at north-star scale: in-process
    pipelined serving windows over a 10,240-node cluster in 8 instance
    groups, one arm per device-pool size (1 = the single-device serving
    path, the engine disabled). Runs as a subprocess
    (hack/multidevice_bench.py) because the arms need an 8-device virtual
    CPU mesh forced before jax initializes — the bench process's backend
    is already bound. One JSON line per device count; the pooled arms'
    vs_baseline is (speedup over the single-device path) / 1.5 — >= 1
    means the engine cleared the 1.5x bar."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "hack",
        "multidevice_bench.py",
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=2400,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"multi-device bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    for line in lines:
        arm = json.loads(line)
        devices = arm["devices"]
        speedup = arm.get("speedup_vs_single_device") or 0.0
        vs = 1.0 if devices == 1 else round(speedup / 1.5, 2)
        entry = {
            "metric": (
                f"multi_device_serving_decisions_per_s_10k_nodes_{devices}dev"
            ),
            "value": arm["decisions_per_s"],
            "unit": "decisions/s",
            "vs_baseline": vs,
            "detail": arm,
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_fleet_scaling(rng):
    """Fleet federation scaling (ISSUE 19): F=4 concurrent per-cluster
    solver stacks behind one FleetFacade vs ONE cluster serving the same
    total load behind one pipeline, under simulated device RTT. Runs as a
    subprocess (hack/fleet_bench.py) because the >=4-slot pool rig is
    forced before jax initializes. The fleet arm asserts IN-ARM that
    aggregate decisions/s >= 3x the single-cluster control AND that every
    cluster's decisions are byte-identical to a standalone replay of its
    op stream (vs_baseline = speedup/3; >= 1 clears the bar). Lines carry
    the serving `clusters`/`spillovers` fields. The bench's stacked
    section (ISSUE 20) then A/Bs the fleet-fused dispatch over a
    SERIALIZED 40 ms tunnel — stacked vs unstacked interleaved reps,
    >=1.5x + stacked_dispatches>0 + forced_resolves==0 + byte-identity
    asserted in-arm; its lines carry `stacked_dispatches`/`stack_arms`."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "fleet_bench.py"
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=1200,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"fleet bench failed rc={out.returncode}: {out.stderr[-800:]}"
        )
    for line in lines:
        entry = json.loads(line)
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_candidate_pruning(rng):
    """Sound top-K candidate pruning A/B (the two-tier solve, ISSUE 10):
    window service time + per-window h2d bytes, full vs pruned, at 10k and
    100k nodes with a prune-slack sweep. Runs as a subprocess
    (hack/prune_bench.py) with pruned decisions ASSERTED byte-identical to
    the full arm's and the certificate-escalation rate reported per arm.
    The pruned 100k arms carry vs_baseline = speedup/3 (>= 1 clears the 3x
    window-service-time bar); h2d shrink carries its own >= 5x bar via
    h2d_shrink_vs_full in the detail."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "prune_bench.py"
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=3600,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"prune bench failed rc={out.returncode}: {out.stderr[-800:]}"
        )
    for line in lines:
        arm = json.loads(line)
        speedup = arm.get("speedup_vs_full")
        if arm["arm"] == "full":
            vs = 1.0
        elif arm["nodes"] >= 100_000:
            vs = round((speedup or 0.0) / 3.0, 2)  # the acceptance bar
        else:
            vs = round(speedup or 0.0, 2)  # informational scale point
        entry = {
            "metric": (
                f"candidate_pruning_window_p50_ms_"
                f"{arm['nodes'] // 1000}k_{arm['arm']}"
            ),
            "value": arm["window_p50_ms"],
            "unit": "ms",
            "vs_baseline": vs,
            "detail": arm,
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_host_scaling(rng):
    """Host-scaling sweep (ISSUE 11, the million-node tier): window
    service, node-event cost (update AND add), upload bytes per event,
    and warm-restart (promotion-analog) time at 10k / 100k / 1M nodes,
    in-process (hack/host_scaling_bench.py subprocess). The 1M arm
    carries the acceptance bar: window service and node-event cost within
    3x of the SAME RIG's 100k numbers (vs_baseline = 3 / worst ratio;
    >= 1 clears), with per-event upload bytes O(changed) — flat-ish
    across tiers, never proportional to N."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "hack", "host_scaling_bench.py",
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=5400,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"host scaling bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    tiers = {arm["n_nodes"]: arm for arm in map(json.loads, lines)}
    ref = tiers.get(100_000)
    for n, arm in sorted(tiers.items()):
        if ref is not None and n > ref["n_nodes"]:
            ratios = [
                arm["window_p50_ms"] / max(ref["window_p50_ms"], 1e-9),
                arm["node_update_ms_p50"]
                / max(ref["node_update_ms_p50"], 1e-9),
                arm["node_add_ms_p50"] / max(ref["node_add_ms_p50"], 1e-9),
            ]
            arm["vs_100k_ratios"] = [round(r, 2) for r in ratios]
            vs = round(3.0 / max(ratios), 2)  # >= 1 clears the 3x bar
        else:
            vs = 1.0
        entry = {
            "metric": f"host_scaling_window_p50_ms_{n}_nodes",
            "value": arm["window_p50_ms"],
            "unit": "ms",
            "vs_baseline": vs,
            "detail": arm,
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_fused_dispatch(rng):
    """Fused multi-window dispatch A/B (ISSUE 6 / ROADMAP Open item 2):
    decisions/s and amortized per-window round trip, fused vs unfused,
    under SIMULATED device RTT in {10, 50, 100} ms (testing/rtt_shim.py
    injects the tunneled-TPU boundary costs on CPU; real-TPU numbers land
    with the next on-silicon bench run) on pool sizes 1 and 2. Runs as a
    subprocess (hack/fused_dispatch_bench.py) because the pool arms need
    the 8-device virtual CPU mesh forced before jax initializes. One JSON
    line per arm; fused arms at RTT >= 50 carry vs_baseline =
    (speedup over single-window dispatch) / 3 — >= 1 clears the 3x
    acceptance bar."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "hack",
        "fused_dispatch_bench.py",
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=2400,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"fused dispatch bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    arms = [json.loads(line) for line in lines]
    # The 3x acceptance bar binds the DEEPEST fused arm per (pool, rtt)
    # at RTT >= 50 (fusion depth is a config knob; the bar is about what
    # the engine can amortize, not about every intermediate K).
    max_k: dict = {}
    for arm in arms:
        key = (arm["pool"], arm["rtt_ms"])
        max_k[key] = max(max_k.get(key, 1), arm["fused_k"])
    for arm in arms:
        speedup = arm.get("speedup_vs_unfused")
        bar_arm = (
            arm["fused_k"] == max_k[(arm["pool"], arm["rtt_ms"])]
            and arm["rtt_ms"] >= 50
        )
        if arm["fused_k"] == 1:
            vs = 1.0
        elif bar_arm:
            vs = round((speedup or 0.0) / 3.0, 2)
        else:
            vs = round(speedup or 0.0, 2)  # informational arm
        entry = {
            "metric": (
                f"fused_dispatch_decisions_per_s_rtt{arm['rtt_ms']}"
                f"_k{arm['fused_k']}_pool{arm['pool']}"
            ),
            "value": arm["decisions_per_s"],
            "unit": "decisions/s",
            "vs_baseline": vs,
            "detail": arm,
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)


def bench_recorder_overhead(rng):
    """Flight-recorder acceptance: the recorder's hot-path cost is
    MEASURED, not assumed. The identical driver-admission workload runs
    through the in-process windowed serving path (predicate_batch:
    dispatch + fetch + apply + write-back) against two live apps —
    recorder + solver telemetry ON (the default) vs OFF
    (`flight_recorder: false`, the control) — with rounds INTERLEAVED
    on/off so box drift hits both arms equally (sequential runs measured
    ±30% apart on this 2-core box from scheduling noise alone; interleaved
    p50s agree to a few percent). Reports the p50 overhead (headline) and
    the min-based floor (noise bound) — when the two straddle zero, the
    recorder's cost is below the box's measurement noise."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.testing.harness import (
        Harness,
        new_node,
        static_allocation_spark_pods,
    )

    window, rounds, warmup = 8, 40, 6
    names = [f"ro{i}" for i in range(64)]

    def make(flag, trace_path=None):
        h = Harness(
            binpack_algo="tightly-pack", fifo=True, flight_recorder=flag,
            trace_path=trace_path,
        )
        h.add_nodes(
            *[new_node(name, zone=f"zone{i % 3}")
              for i, name in enumerate(names)]
        )
        return h

    seq = [0]

    def one_round(h):
        args = []
        for _ in range(window):
            driver = static_allocation_spark_pods(f"ro-{seq[0]}", 4)[0]
            seq[0] += 1
            h.add_pods(driver)
            args.append(ExtenderArgs(pod=driver, node_names=names))
        t0 = time.perf_counter()
        results = h.extender.predicate_batch(args)
        dt_ms = (time.perf_counter() - t0) * 1e3
        bad = [res for res in results if not res.ok]
        if bad:
            raise RuntimeError(f"recorder bench admission failed: {bad}")
        # Reset to an empty cluster so every round (both arms) sees
        # identical state and window shapes.
        _reset_cluster_state(h.backend, h.app)
        return dt_ms / window

    # Third arm (ISSUE 17): recorder + trace sink — every window journaled
    # to JSONL on the serving path. Same 5% budget, same interleaving.
    import tempfile

    trace_path = os.path.join(
        tempfile.mkdtemp(prefix="bench-trace-"), "trace.jsonl"
    )
    h_on, h_off, h_sink = make(True), make(False), make(True, trace_path)
    for _ in range(warmup):
        one_round(h_on)
        one_round(h_off)
        one_round(h_sink)
    on_lats, off_lats, sink_lats = [], [], []
    for _ in range(rounds):
        on_lats.append(one_round(h_on))
        off_lats.append(one_round(h_off))
        sink_lats.append(one_round(h_sink))
    on_p50 = float(np.percentile(on_lats, 50))
    off_p50 = float(np.percentile(off_lats, 50))
    sink_p50 = float(np.percentile(sink_lats, 50))
    overhead_pct = (on_p50 - off_p50) / off_p50 * 100.0
    sink_pct = (sink_p50 - on_p50) / on_p50 * 100.0
    floor_pct = (
        (float(np.min(on_lats)) - float(np.min(off_lats)))
        / float(np.min(off_lats)) * 100.0
    )
    sink_floor_pct = (
        (float(np.min(sink_lats)) - float(np.min(on_lats)))
        / float(np.min(on_lats)) * 100.0
    )
    h_sink.app.trace_writer.flush()  # drain the encode queue before stats
    detail = {
        "recorder_on_p50_ms_per_decision": round(on_p50, 4),
        "recorder_off_p50_ms_per_decision": round(off_p50, 4),
        "recorder_sink_p50_ms_per_decision": round(sink_p50, 4),
        "overhead_floor_pct_min_based": round(floor_pct, 2),
        "trace_sink_overhead_pct_vs_recorder_on": round(sink_pct, 2),
        "trace_sink_floor_pct_min_based": round(sink_floor_pct, 2),
        "trace_events": h_sink.app.trace_writer.stats()["events"],
        "trace_write_errors": h_sink.app.trace_writer.stats()["write_errors"],
        "window": window,
        "rounds_measured": rounds,
        "decisions_recorded": h_on.app.recorder.stats()["total_recorded"],
        "note": (
            "interleaved on/off/sink predicate_batch rounds over 64 nodes, "
            "identical workload per arm"
        ),
    }
    h_sink.app.trace_writer.close()
    # Budget: the recorder must stay within 5% of the recorder-off path;
    # vs_baseline 1.0 inside the budget, fractional when it blows it.
    vs = 1.0 if overhead_pct <= 5.0 else round(5.0 / overhead_pct, 2)
    _record(
        "flight_recorder_overhead_pct",
        round(overhead_pct, 2), "pct", vs, detail=detail,
    )
    print(
        json.dumps(
            {
                "metric": "flight_recorder_overhead_pct",
                "value": round(overhead_pct, 2),
                "unit": "pct",
                "vs_baseline": vs,
                "detail": detail,
            }
        ),
        flush=True,
    )
    # Trace-sink budget (ISSUE 17 acceptance): sink-on vs recorder-on.
    vs_sink = 1.0 if sink_pct <= 5.0 else round(5.0 / sink_pct, 2)
    _record(
        "trace_sink_overhead_pct",
        round(sink_pct, 2), "pct", vs_sink,
        detail={
            "recorder_on_p50_ms_per_decision": round(on_p50, 4),
            "recorder_sink_p50_ms_per_decision": round(sink_p50, 4),
            "floor_pct_min_based": round(sink_floor_pct, 2),
        },
    )
    print(
        json.dumps(
            {
                "metric": "trace_sink_overhead_pct",
                "value": round(sink_pct, 2),
                "unit": "pct",
                "vs_baseline": vs_sink,
            }
        ),
        flush=True,
    )


def _ha_build_state(backend, n_nodes, gangs=96, seed_nodes=64):
    """Shared HA bench fixture: a promoted leader over `backend`, `gangs`
    placed gangs (admitted at a SMALL node count so setup stays cheap —
    reconcile/promotion cost is dominated by the node walks, not apps),
    then the fleet grown to `n_nodes`. Returns (leader, node_names)."""
    from spark_scheduler_tpu.core.extender import ExtenderArgs
    from spark_scheduler_tpu.ha.replica import build_replica
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import DEMAND_CRD
    from spark_scheduler_tpu.testing.harness import (
        INSTANCE_GROUP_LABEL,
        new_node,
        static_allocation_spark_pods,
    )

    backend.register_crd(DEMAND_CRD)
    config = InstallConfig(
        fifo=True,
        binpack_algo="tightly-pack",
        instance_group_label=INSTANCE_GROUP_LABEL,
        sync_writes=True,
        ha_enabled=True,
    )
    leader = build_replica(backend, "bench-leader", config=config)
    assert leader.lease.try_acquire()
    leader.promote()
    names = []
    for i in range(seed_nodes):
        node = new_node(f"ha-n{i}", zone=f"zone{i % 3}")
        backend.add_node(node)
        names.append(node.name)
    for g in range(gangs):
        pods = static_allocation_spark_pods(f"ha-app-{g}", 2)
        backend.add_pod(pods[0])
        res = leader.app.extender.predicate(
            ExtenderArgs(pod=pods[0], node_names=names)
        )
        assert res.ok, res.outcome
        backend.bind_pod(pods[0], res.node_names[0])
    for i in range(seed_nodes, n_nodes):
        node = new_node(f"ha-n{i}", zone=f"zone{i % 3}")
        backend.add_node(node)
        names.append(node.name)
    return leader, names


def bench_ha_failover(rng):
    """ISSUE 8 acceptance metrics.

    Promotion arms (10k durable-WAL / 100k in-memory): COLD start = what a
    replacement process pays before it can serve (WAL replay where
    applicable + app build + cache fill + failover reconcile + first
    feature snapshot) vs WARM standby promotion = a replica whose caches
    tailed backend events promoting in place (lease takeover + reconcile +
    snapshot). Bar: warm >= 5x faster than cold at 10k nodes.

    Sharded arm: 2 active replicas serving disjoint instance-group shards
    concurrently vs 1 replica serving everything, same workload, on the
    in-process pipeline. Bars: >= 1.5x decisions/s, decisions
    byte-identical per group (asserted, not just reported).

    Chaos arm: the HAChaosSoak engine (leader killed mid-burst, >= 3
    cycles) — zero double placements / reservation violations asserted
    inside, spike + fencing counters reported here."""
    from spark_scheduler_tpu.ha.lease import BackendLeaseStore, LeaseManager
    from spark_scheduler_tpu.ha.replica import build_replica
    from spark_scheduler_tpu.server.config import InstallConfig
    from spark_scheduler_tpu.store.backend import InMemoryBackend
    from spark_scheduler_tpu.store.durable import DurableBackend
    from spark_scheduler_tpu.testing.harness import INSTANCE_GROUP_LABEL

    # ---------------------------------------------- promotion: cold vs warm
    import tempfile

    for n_nodes, durable in ((10_000, True), (100_000, False)):
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "ha.jsonl")
            backend = (
                DurableBackend(path) if durable else InMemoryBackend()
            )
            leader, _names = _ha_build_state(backend, n_nodes)
            config = InstallConfig(
                fifo=True,
                binpack_algo="tightly-pack",
                instance_group_label=INSTANCE_GROUP_LABEL,
                sync_writes=True,
                ha_enabled=True,
            )
            # Warm standby built BEFORE the measurement: its caches filled
            # from the backend and its tailer keeps them hot. One election
            # tick = one heartbeat of standby life (lease still held by
            # the leader, feature arrays warmed) — heartbeats run
            # continuously in a real deployment.
            standby = build_replica(backend, "bench-standby", config=config)
            assert standby.run_election_once() == "standby"
            # COLD first (state is stable): a replacement process's full
            # path to serving.
            t0 = time.perf_counter()
            if durable:
                cold_backend = DurableBackend(
                    path, compact_on_load=False, follow=True
                )
            else:
                cold_backend = backend
            cold = build_replica(
                cold_backend,
                "bench-cold",
                config=config,
                lease=LeaseManager(
                    BackendLeaseStore(InMemoryBackend()), "bench-cold"
                ),
            )
            assert cold.lease.try_acquire()
            cold.promote()
            cold_ms = (time.perf_counter() - t0) * 1e3
            if durable:
                cold_backend.close()
            # WARM: clean handoff -> the standby's next election tick
            # takes over and promotes in place.
            leader.stop()
            assert standby.run_election_once() == "leader"
            warm_ms = standby.last_promotion_ms
            speedup = cold_ms / warm_ms if warm_ms else 0.0
            detail = {
                "nodes": n_nodes,
                "cold_ms": round(cold_ms, 1),
                "warm_ms": round(warm_ms, 2),
                "warm_reconcile_ms": round(standby.last_reconcile_ms, 2),
                "speedup": round(speedup, 1),
                "cold_includes_wal_replay": durable,
                "gangs": 96,
            }
            label = f"ha_promotion_{n_nodes // 1000}k"
            # Bar (at 10k): warm >= 5x cold -> vs_baseline >= 1.
            _record(
                label, round(warm_ms, 2), "ms", round(speedup / 5.0, 2),
                detail=detail,
            )
            print(json.dumps(_RESULTS[-1]), flush=True)
            standby.stop()
            if durable:
                backend.close()

    # ------------------------------------- sharded 2-replica vs 1-replica
    # + leader-kill chaos, in a SUBPROCESS (hack/ha_shard_bench.py) with
    # the persistent XLA compile cache NOT enabled: concurrently-serving
    # solvers in a cache-enabled process intermittently mis-solve reloaded
    # executables (spurious failure-fit / shifted placements; never
    # reproduced cache-off), and the arm's byte-identity assertions must
    # not inherit that flake. Two arms: pure CPU (informational — one XLA
    # CPU solve already saturates every core) and 50 ms simulated device
    # RTT (the tunneled-TPU regime; carries the >= 1.5x bar).
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "ha_shard_bench.py"
    )
    env = {k: v for k, v in os.environ.items()}
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True, env=env,
        timeout=1800,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"ha_shard_bench subprocess failed:\n{out.stderr[-2000:]}"
        )
    arms = {}
    for line in out.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
            arms[rec.pop("arm")] = rec
    rtt, pure, chaos = arms["rtt50"], arms["pure_cpu"], arms["chaos"]
    _record(
        "ha_sharded_serving",
        rtt["sharded_2replica_dps"],
        "decisions/s",
        round(rtt["speedup"] / 1.5, 2),  # bar: >= 1.5x single-replica
        detail={"rtt50": rtt, "pure_cpu": pure},
    )
    print(json.dumps(_RESULTS[-1]), flush=True)

    # ------------------------------------------------------------- chaos
    spikes = chaos["failover_spike_ms"]
    _record(
        "ha_chaos_soak",
        max(spikes) if spikes else 0,
        "ms",
        1.0
        if chaos["promotions"] == 3 and chaos["fenced_drops"] >= 3
        else 0.0,
        detail={
            **chaos,
            "double_placements": 0,  # asserted inside the soak engine
            "reservation_violations": 0,
        },
    )
    print(json.dumps(_RESULTS[-1]), flush=True)


def bench_fault_recovery(rng):
    """ISSUE 9 acceptance metrics: device-slot failure recovery measured
    through the served pipeline (subprocess, 8-device virtual CPU mesh —
    hack/fault_recovery_bench.py). Three arms over one seeded workload
    (1,280 nodes / 2 instance groups / 2-slot pool):

      steady      no faults — the throughput baseline;
      slot_kill   one slot dies mid-burst: quarantine + survivor
                  re-dispatch. Bar: decisions/s >= 0.5x steady
                  (vs_baseline = dip/0.5) with BYTE-IDENTICAL placements
                  (asserted in the subprocess, the run aborts otherwise);
                  recovery_spike_ms = the faulted window's wall latency
                  over the steady per-window median (time-to-recover);
      all_killed  the whole pool dies: the degraded greedy fallback
                  serves the rest of the burst byte-identically —
                  reported as the no-device throughput floor."""
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "hack",
        "fault_recovery_bench.py",
    )
    out = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=1200,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if out.returncode != 0 or len(lines) != 3:
        raise RuntimeError(
            f"fault-recovery bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}"
        )
    steady = json.loads(lines[0])
    for line in lines:
        arm = json.loads(line)
        name = arm["arm"]
        if name == "steady":
            vs = 1.0
        elif name == "slot_kill":
            vs = round(arm["dip_vs_steady"] / 0.5, 2)  # bar: >= 0.5x steady
        else:  # all_killed: serving at all, byte-identical, is the bar
            vs = 1.0 if arm.get("byte_identical_to_steady") else 0.0
        entry = {
            "metric": f"fault_recovery_{name}_decisions_per_s",
            "value": arm["decisions_per_s"],
            "unit": "decisions/s",
            "vs_baseline": vs,
            "detail": arm,
        }
        _RESULTS.append(entry)
        print(json.dumps(entry), flush=True)
    return steady


def bench_tpu_parity():
    """Golden-parity smoke on the REAL backend, folded into every bench run
    (VERDICT r2 #5): the same oracle assertions as the CPU golden suite,
    executed on whatever device the bench itself uses."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "tpu_parity_smoke",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "hack", "tpu_parity_smoke.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    verdict = mod.run()
    _record("tpu_parity", verdict["cases_checked"], "cases", 1.0)
    print(
        json.dumps(
            {
                "metric": "tpu_parity",
                "value": verdict["cases_checked"],
                "unit": "cases",
                "vs_baseline": 1.0,
                "detail": {"parity": verdict["parity"], "device": verdict["device"]},
            }
        ),
        flush=True,
    )


def bench_tpu_soak(total_steps: int = 1200):
    """Invariant soak ON SILICON, folded into every bench run: the same
    randomized engine as tests/test_invariant_soak.py (arrivals, kills,
    teardowns, churn, write faults, retries through pipelined windows;
    over-commit / exact-reservation / mirror / idempotency invariants),
    but with the serving windows solved by the Pallas window kernel — the
    CPU suite can only exercise the XLA scan. One metric line records the
    steps survived and which device program served the windows."""
    from spark_scheduler_tpu.testing.soak import Soak

    t0 = time.perf_counter()
    path_counts: dict = {}
    steps_done = 0
    strategies_completed = 0
    env_error = None
    per = total_steps // 3
    # Third leg at 500 nodes: production-scale candidate masks and window
    # shapes through the kernel under churn (the 12-node legs keep the op
    # mix dense; fresh-seed 500- and 1000-node soaks ran green before this
    # landed).
    for seed, strategy, n_nodes in (
        (42, "tightly-pack", 12),
        (43, "az-aware-tightly-pack", 12),
        (44, "single-az-tightly-pack", 500),
    ):
        soak = Soak(np.random.default_rng(seed), strategy, n_nodes=n_nodes)
        try:
            soak.run(per)
        except AssertionError:
            raise  # an INVARIANT violation is signal — fail the bench
        except Exception as exc:
            # Environment failures (the tunnel's remote-compile service
            # 500s intermittently on fresh shapes) must not kill the
            # artifact: record how far the soak got and the error. The
            # aborted strategy's served windows still count below.
            env_error = f"{type(exc).__name__}: {exc}"
        steps_done += soak.steps
        for k, v in soak.ext._solver.window_path_counts.items():
            path_counts[k] = path_counts.get(k, 0) + v
        if env_error is not None:
            break
        strategies_completed += 1
    detail = {
        "steps": steps_done,
        "strategies_completed": strategies_completed,
        "window_path_counts": path_counts,
        "wall_s": round(time.perf_counter() - t0, 1),
        "invariants": "over-commit, exact-reservation, drained-mirror, idempotent-retry",
    }
    if env_error is not None:
        detail["environment_error"] = env_error[:400]
    # vs_baseline reflects how much of the 3-strategy matrix actually ran
    # (ADVICE r5 low #1: an aborted soak used to record 1.0 and exit 0).
    vs_baseline = round(strategies_completed / 3.0, 2)
    _record("tpu_invariant_soak", steps_done, "steps", vs_baseline, detail=detail)
    print(
        json.dumps(
            {
                "metric": "tpu_invariant_soak",
                "value": steps_done,
                "unit": "steps",
                "vs_baseline": vs_baseline,
                "detail": detail,
            }
        ),
        flush=True,
    )
    if env_error is not None:
        # The partial metric above keeps the run's artifact; re-raising
        # AFTER recording hands the environment failure to guarded(), which
        # lands this section in failed_sections and makes the process exit
        # non-zero — same contract as every other section.
        raise RuntimeError(f"tpu soak aborted by environment: {env_error}")


def bench_elastic_autoscaler(total_steps: int = 600):
    """Elastic soak ON SILICON: the invariant-soak engine with the
    in-process autoscaler in the loop (testing/soak.py elastic mode) —
    bursts that cannot fit emit Demands, the autoscaler provisions nodes,
    gangs land on them, idle capacity cordons and drains. Every pass
    re-asserts drain safety (no node holding a hard or soft reservation is
    ever drained) on top of the four standing invariants, and the node
    count crossing the solver's padding buckets under load is exactly the
    recompile churn the 500-node leg exists to exercise. The headline is
    the closed-loop responsiveness: demand-to-fulfilled latency p50/p99 on
    the soak clock (real wall time plus the simulated idle-TTL jumps —
    p50 is the in-pass provision+fulfill cost in real ms, while p99 covers
    demands that sat through a simulated wait for a later pass)."""
    from spark_scheduler_tpu.testing.soak import Soak

    t0 = time.perf_counter()
    per = total_steps // 2
    latencies: list[float] = []
    counts_total = {
        "nodes_added": 0, "nodes_drained": 0,
        "demands_fulfilled": 0, "demands_unfulfillable": 0,
    }
    path_counts: dict = {}
    steps_done = 0
    env_error = None
    strategies_completed = 0
    for seed, strategy in ((47, "tightly-pack"), (48, "single-az-tightly-pack")):
        soak = Soak(
            np.random.default_rng(seed), strategy, n_nodes=10, elastic=True
        )
        try:
            soak.run(per)
        except AssertionError:
            raise  # invariant violations (incl. drain safety) fail the bench
        except Exception as exc:
            env_error = f"{type(exc).__name__}: {exc}"
        steps_done += soak.steps
        metrics = soak.h.autoscaler.metrics
        latencies.extend(metrics.scaleup_latency_samples())
        for k, v in metrics.counts().items():
            counts_total[k] += v
        for k, v in soak.ext._solver.window_path_counts.items():
            path_counts[k] = path_counts.get(k, 0) + v
        if env_error is not None:
            break
        strategies_completed += 1
    p50_ms = (
        round(float(np.percentile(latencies, 50)) * 1e3, 3) if latencies else None
    )
    p99_ms = (
        round(float(np.percentile(latencies, 99)) * 1e3, 3) if latencies else None
    )
    detail = {
        "steps": steps_done,
        "strategies_completed": strategies_completed,
        "demand_to_fulfilled_p50_ms": p50_ms,
        "demand_to_fulfilled_p99_ms": p99_ms,
        "demands_fulfilled": counts_total["demands_fulfilled"],
        "demands_unfulfillable": counts_total["demands_unfulfillable"],
        "nodes_added": counts_total["nodes_added"],
        "nodes_drained": counts_total["nodes_drained"],
        "window_path_counts": path_counts,
        "wall_s": round(time.perf_counter() - t0, 1),
        "invariants": (
            "over-commit, exact-reservation, drained-mirror, "
            "idempotent-retry, reservation-aware drain"
        ),
    }
    if env_error is not None:
        detail["environment_error"] = env_error[:400]
    vs_baseline = round(strategies_completed / 2.0, 2)
    _record(
        "elastic_autoscaler_demand_to_fulfilled_p50_ms",
        p50_ms if p50_ms is not None else 0,
        "ms", vs_baseline, detail=detail,
    )
    print(
        json.dumps(
            {
                "metric": "elastic_autoscaler_demand_to_fulfilled_p50_ms",
                "value": p50_ms if p50_ms is not None else 0,
                "unit": "ms",
                "vs_baseline": vs_baseline,
                "detail": detail,
            }
        ),
        flush=True,
    )
    if env_error is not None:
        raise RuntimeError(f"elastic soak aborted by environment: {env_error}")


def main() -> None:
    _enable_compile_cache()
    # svc1log INFO lines would flood the driver's output tail and drop
    # metric lines from the recorded artifact (VERDICT r2 #4) — route
    # service logs to devnull for the bench process.
    import os as _os

    from spark_scheduler_tpu.tracing import Svc1Logger, set_svc1log

    set_svc1log(Svc1Logger(stream=open(_os.devnull, "w")))

    rng = np.random.default_rng(0)
    failed_sections: list = []

    def guarded(name, fn, *args):
        """Degrade gracefully on ENVIRONMENT failures ONLY: the tunnel's
        remote-compile service 500s intermittently (observed
        JaxRuntimeError: INTERNAL ... remote_compile HTTP 500), and one
        flaky section must not cost the round its entire artifact. The
        failure is recorded loudly as its own metric line (value 0,
        vs_baseline 0) and in the final all-metrics summary, every other
        section still runs, and the process exits non-zero.
        AssertionError is NOT caught — parity-oracle mismatches and soak
        invariant violations are correctness signal and abort the run."""
        try:
            return fn(*args)
        except AssertionError:
            raise
        except Exception as exc:
            err = f"{type(exc).__name__}: {exc}"
            failed_sections.append(name)
            entry = {
                "metric": f"{name}_FAILED",
                "value": 0,
                "unit": "error",
                "vs_baseline": 0.0,
                "detail": {"error": err[:500]},
            }
            _RESULTS.append(entry)
            print(json.dumps(entry), flush=True)
            return None

    guarded("tpu_parity", bench_tpu_parity)
    guarded("tpu_invariant_soak", bench_tpu_soak)
    # Elastic leg: the autoscaler in the loop (node churn across padding
    # buckets + reservation-aware drain), demand-to-fulfilled p50/p99.
    guarded("elastic_autoscaler", bench_elastic_autoscaler)
    guarded("config1", bench_config1, rng)
    guarded("config2", bench_config2, rng)
    guarded("config2b", bench_config2_az_aware, rng)
    guarded("config3", bench_config3, rng)
    guarded("config4", bench_config4, rng)
    guarded("config6", bench_config6_beyond_baseline, rng)
    # Host featurize (feature store O(changed) evidence): host-only, so it
    # runs with the cheap kernel configs before the serving benches heat
    # the box.
    guarded("host_featurize", bench_host_featurize, rng)
    # HA failover (ISSUE 8): cold vs warm promotion at 10k/100k nodes,
    # sharded 2-replica vs 1-replica decisions/s (byte-identical per
    # group), leader-kill chaos cycle stats. Mostly host work; runs before
    # the serving benches heat the box.
    guarded("ha_failover", bench_ha_failover, rng)
    # Fault recovery (ISSUE 9): slot-kill mid-burst on a 2-slot pool
    # (subprocess, virtual CPU mesh) — decisions/s dip + time-to-recover,
    # byte-identical placements asserted; all-slots-killed reports the
    # degraded greedy-fallback floor.
    guarded("fault_recovery", bench_fault_recovery, rng)
    # North-star MEASUREMENT here — after the small kernel configs (whose
    # short chains are the jitter-sensitive ones: config1 measured 1.5 ms
    # quiet vs 4.7 ms after a config5 measurement) but BEFORE the serving
    # benches (whose process state inflated a last-measured config5 ~2x:
    # 4.2 ms vs 2.3 standalone). EMISSION stays last (the headline must be
    # the final metric). Dedicated generator: drawing config5's workload
    # from the shared stream here would shift the serving benches' random
    # mix and break round-over-round comparability (the kernel is
    # data-independent, so config5's own timing is seed-insensitive).
    emit_config5 = guarded(
        "config5", bench_config5, np.random.default_rng(5), True
    )
    # Transport A/B headline: null-handler rig ceiling per transport
    # (pure CPU HTTP; cheap, and the async >= 2x threaded bar lives here).
    guarded("transport_rig_ceiling", bench_transport_rig_ceiling, rng)
    # Ingest-lane decode A/B (CPU-only, seconds): json.loads vs the native
    # JSON fast path vs the binary protocol on a 10k-name body.
    guarded("ingest_decode", bench_ingest_decode, rng)
    guarded("serving_http", bench_serving_http, rng)
    guarded("serving_http_async", bench_serving_http, rng, "async")
    guarded(
        "serving_http_native", bench_serving_http, rng, "async", "native"
    )
    # Flight-recorder overhead: in-process on-vs-off control pair, cheap,
    # before the long concurrent benches heat the box.
    guarded("recorder_overhead", bench_recorder_overhead, rng)
    # In-process (subprocess, local cpu backend): runs alone, before the
    # concurrent benches, so nothing contends with it or them.
    guarded("serving_inprocess", bench_serving_inprocess, rng)
    # Multi-device window-solve engine (subprocess, 8-device virtual CPU
    # mesh): decisions/s at pool sizes 1/2/4/8 on the 10k-node x 8-group
    # topology; the pooled arms' bar is 1.5x the single-device path.
    guarded("multi_device_serving", bench_multi_device_serving, rng)
    # Fleet federation scaling (subprocess, 4 forced host devices): F=4
    # concurrent per-cluster stacks vs one consolidated cluster; >= 3x
    # aggregate decisions/s + per-cluster byte-identity asserted in-arm.
    guarded("fleet_scaling", bench_fleet_scaling, rng)
    # Fused multi-window dispatch A/B under simulated device RTT
    # (subprocess): the fused arms at RTT >= 50 ms carry the 3x bar.
    guarded("fused_dispatch", bench_fused_dispatch, rng)
    # Candidate pruning A/B (subprocess): pruned vs full window service
    # time + h2d at 10k/100k nodes, byte-identity asserted in-arm; the
    # pruned 100k arms carry the 3x window-service-time bar.
    guarded("candidate_pruning", bench_candidate_pruning, rng)
    # Host-scaling sweep (subprocess): 10k/100k/1M window service,
    # node-event cost, upload bytes/event, warm restart; the 1M arms
    # carry the within-3x-of-100k acceptance bar (ISSUE 11).
    guarded("host_scaling", bench_host_scaling, rng)
    # Executor bench BEFORE the long concurrent bench: the host-only
    # ladder numbers are the most sensitive to box heat / accumulated
    # process state, so measure them early.
    guarded("serving_http_executors", bench_serving_http_executors, rng)
    guarded(
        "serving_http_executors_async",
        bench_serving_http_executors, rng, "async",
    )
    guarded("serving_http_concurrent", bench_serving_http_concurrent, rng)
    guarded(
        "serving_http_concurrent_async",
        bench_serving_http_concurrent, rng, "async",
    )
    guarded(
        "serving_http_concurrent_64c", bench_serving_http_concurrent_64c, rng
    )
    guarded(
        "serving_http_concurrent_64c_async",
        bench_serving_http_concurrent_64c, rng, "async",
    )
    # North-star SCALE through the served stack (VERDICT r4 #1): both
    # transports — the async arm is the ceiling lift AT scale.
    guarded(
        "serving_http_concurrent_10k", bench_serving_http_concurrent_10k, rng
    )
    guarded(
        "serving_http_concurrent_10k_async",
        bench_serving_http_concurrent_10k, rng, "async",
    )
    # Native zero-copy ingest A/B at scale (ROADMAP Open item 1): the same
    # 10k-node drive on the native lane, both transports, against the
    # in-process control the threaded/python arm above emits — the
    # HTTP-vs-in-process gap closer (bar: >= 0.8x in-process). Skips to a
    # recorded zero-value section on toolchain-less hosts (the fixture
    # degrades with a RuntimeWarning and the `ingest` field says python).
    guarded(
        "serving_http_concurrent_10k_native",
        bench_serving_http_concurrent_10k, rng, "threaded", "native",
    )
    guarded(
        "serving_http_concurrent_10k_async_native",
        bench_serving_http_concurrent_10k, rng, "async", "native",
    )
    if emit_config5 is not None:
        emit_config5()  # north star — the headline, measured up top

    # FINAL line, re-stating the headline with EVERY metric of the run
    # embedded compactly: the driver records the output tail, and earlier
    # per-metric lines have been lost to truncation in past rounds
    # (VERDICT r3 #6). One line now carries the whole round. The headline
    # is selected BY NAME (the north-star gang_placement metric) rather
    # than positionally, so a degraded run cannot promote a serving
    # metric — or an error stub — to the round's headline.
    headline = next(
        (
            r
            for r in reversed(_RESULTS)
            if r["metric"].startswith("gang_placement_p50")
        ),
        _RESULTS[-1] if _RESULTS else None,
    )
    if headline is not None:
        print(
            json.dumps(
                {
                    **headline,
                    "detail": {
                        "summary": "all metrics of this bench run",
                        "failed_sections": failed_sections,
                        "all_metrics": _RESULTS,
                    },
                }
            ),
            flush=True,
        )
    if failed_sections:
        # A degraded artifact is still a FAILED run to any exit-code
        # watcher (the metric lines above carry the detail).
        raise SystemExit(1)


if __name__ == "__main__":
    main()
