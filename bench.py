"""North-star benchmark (BASELINE.md): gang-schedule 1k concurrent Spark apps
over a 10k-node cluster; target p50 placement latency < 50 ms on a single
TPU chip.

Model: the pending queue drains in admission windows of 100 apps (one
`batched_fifo_pack` call per window; availability threads between windows as
device-resident tensors, so consecutive windows form one dependent device
chain with no host round-trips — exactly how the serving layer drives the
solver). A window's decisions land when it completes, so the scheduler's
steady-state placement latency under 1k-concurrent load is the per-window
service time.

Measurement: this machine reaches the TPU through a tunnel whose RPC
round-trip (~70 ms) would swamp a single-call timing, and
`jax.block_until_ready` does not reliably wait on the experimental backend —
only a host transfer does. So the service time is measured as the MARGINAL
cost of extending a dependent window chain: (T(chain of 12) - T(chain of 2))
/ 10, each chain forced by one host transfer of its final [B] bool output.
The fixed RPC/dispatch overhead cancels; what remains is the true per-window
device time, which is what pipelined serving pays. p50 is taken over
repeated marginal measurements.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
`vs_baseline` = target_ms / measured_ms (>1 means beating the 50 ms target).
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np


def main() -> None:
    import jax

    from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
    from spark_scheduler_tpu.ops.batched import batched_fifo_pack, make_app_batch

    n_nodes, n_apps, window, emax, num_zones = 10_000, 1_000, 100, 8, 4
    k_short, k_long, repeats = 2, 12, 5
    rng = np.random.default_rng(0)

    avail = rng.integers(8, 96, size=(n_nodes, 3)).astype(np.int32)
    avail[:, 1] = rng.integers(16, 256, size=n_nodes)
    avail[:, 2] = rng.integers(0, 2, size=n_nodes)
    cluster = jax.device_put(
        ClusterTensors(
            available=avail,
            schedulable=avail.copy(),
            zone_id=rng.integers(0, num_zones, size=n_nodes).astype(np.int32),
            name_rank=rng.permutation(n_nodes).astype(np.int32),
            label_rank_driver=np.full(n_nodes, INT32_INF, np.int32),
            label_rank_executor=np.full(n_nodes, INT32_INF, np.int32),
            unschedulable=np.zeros(n_nodes, bool),
            ready=np.ones(n_nodes, bool),
            valid=np.ones(n_nodes, bool),
        )
    )
    driver = rng.integers(1, 4, size=(n_apps, 3)).astype(np.int32)
    driver[:, 2] = 0
    execs = rng.integers(1, 6, size=(n_apps, 3)).astype(np.int32)
    execs[:, 2] = 0
    counts = rng.integers(1, emax + 1, size=n_apps).astype(np.int32)
    batches = [
        jax.device_put(
            make_app_batch(
                driver[lo : lo + window],
                execs[lo : lo + window],
                counts[lo : lo + window],
                skippable=np.ones(window, bool),
            )
        )
        for lo in range(0, n_apps, window)
    ]

    def chain(k):
        """Drain the first k windows as one dependent device chain; force
        completion with a single host transfer. Returns total admitted."""
        c = cluster
        admitted = []
        for i in range(k):
            out = batched_fifo_pack(
                c, batches[i % len(batches)], fill="tightly-pack",
                emax=emax, num_zones=num_zones,
            )
            c = dataclasses.replace(c, available=out.available_after)
            admitted.append(out.admitted)
        return np.asarray(jax.numpy.concatenate(admitted))  # forces the chain

    full = chain(len(batches))  # compile + warm; also the correctness run
    n_admitted = int(full.sum())

    def timed(k):
        t0 = time.perf_counter()
        chain(k)
        return time.perf_counter() - t0

    timed(k_short), timed(k_long)  # warm both chain lengths
    marginals_ms = []
    for _ in range(repeats):
        t_short = min(timed(k_short) for _ in range(2))
        t_long = min(timed(k_long) for _ in range(2))
        marginals_ms.append((t_long - t_short) * 1e3 / (k_long - k_short))

    p50_ms = float(np.percentile(marginals_ms, 50))
    target_ms = 50.0
    print(
        json.dumps(
            {
                "metric": "gang_placement_p50_window_service_ms_10k_nodes_1k_apps",
                "value": round(p50_ms, 3),
                "unit": "ms",
                "vs_baseline": round(target_ms / p50_ms, 2),
                "detail": {
                    "window_apps": window,
                    "per_app_ms": round(p50_ms / window, 4),
                    "decisions_per_s": round(window / (p50_ms / 1e3), 1),
                    "admitted_of_1k": n_admitted,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
