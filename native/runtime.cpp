// Native runtime for the TPU scheduler framework.
//
// Two host-side components that sit on the request path around the XLA
// solver (the runtime slots of SURVEY.md §2d):
//
//   ClusterArena  — dense per-slot cluster state (allocatable, zone, flags,
//                   priority ranks) with O(1) upsert/remove and a single-call
//                   snapshot that materializes the ClusterTensors inputs
//                   (available = clip(alloc - usage - overhead),
//                   schedulable = clip(alloc - overhead)) into caller
//                   buffers. Replaces the per-request Python walk over all
//                   nodes (the reference rebuilds string-keyed maps per
//                   request, resources.go:61-100; we rebuild nothing).
//
//   ShardedQueue  — the async write-back queue (store/queue.go:22-144
//                   semantics): per-key dedup via an inflight set, FNV-1a
//                   sharding so one key always lands on the same consumer,
//                   bounded per-shard buffers, blocking/non-blocking add,
//                   blocking pop with timeout. Payloads stay in Python;
//                   the queue moves opaque u64 ticket ids.
//
// Exposed as a C ABI for ctypes. No Python.h dependency so it builds with
// a bare g++ -shared -fPIC.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

constexpr int kDims = 3;
constexpr int32_t kInt32Inf = 2147483647 / 2;  // models/resources.INT32_INF

inline int32_t clip64(int64_t v) {
  if (v > kInt32Inf) return kInt32Inf;
  if (v < -kInt32Inf) return -kInt32Inf;
  return static_cast<int32_t>(v);
}

// ----------------------------------------------------------- ClusterArena

struct ClusterArena {
  std::mutex mu;
  // Slot-indexed, grown on demand; slot indices are owned by the Python
  // NodeRegistry (stable across churn, recycled+masked like cluster.py).
  std::vector<int64_t> alloc;        // [cap * 3]
  std::vector<int32_t> zone_id;      // [cap]
  std::vector<int32_t> name_rank;    // [cap]
  std::vector<int32_t> lr_driver;    // [cap]
  std::vector<int32_t> lr_executor;  // [cap]
  std::vector<uint8_t> unschedulable;
  std::vector<uint8_t> ready;
  std::vector<uint8_t> valid;
  int64_t capacity = 0;

  void ensure(int64_t idx) {
    if (idx < capacity) return;
    int64_t cap = std::max<int64_t>(8, capacity);
    while (cap <= idx) cap *= 2;
    alloc.resize(cap * kDims, 0);
    zone_id.resize(cap, 0);
    name_rank.resize(cap, kInt32Inf);
    lr_driver.resize(cap, kInt32Inf);
    lr_executor.resize(cap, kInt32Inf);
    unschedulable.resize(cap, 0);
    ready.resize(cap, 0);
    valid.resize(cap, 0);
    capacity = cap;
  }
};

// ----------------------------------------------------------- ShardedQueue

struct Shard {
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<uint64_t> tickets;
};

struct ShardedQueue {
  std::vector<Shard> shards;
  size_t buffer_size;
  std::mutex inflight_mu;
  std::unordered_set<std::string> inflight;

  ShardedQueue(size_t buckets, size_t buffer)
      : shards(buckets), buffer_size(buffer) {}
};

uint32_t fnv1a32(const char* data, size_t len) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < len; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= 16777619u;
  }
  return h;
}

// ------------------------------------------------------------ native ingest
//
// The zero-copy serving lane (`server.ingest: native`): an incremental
// HTTP/1.1 request framer over connection-owned buffers plus a predicate
// body decoder that tokenizes the candidate-node-id bulk (the ~200 KB part
// of a 10k-node ExtenderArgs body) straight into a reusable arena slot —
// the Python side never json.loads the body on the hot path; it receives a
// ticket (pod sub-document span + a '\0'-separated name blob with an
// offsets table and an FNV-1a 64 digest) that the batcher and the solver's
// candidate-mask cache consume directly.
//
// Framing strictness mirrors server/transport_async.py exactly (RFC 7230
// 3.3.2): duplicate differing Content-Length and non-1*DIGIT forms are
// unframeable, Transfer-Encoding is rejected, oversize bodies drain in
// place for a 413 that keeps the keep-alive framing alive. Anything the
// fast-path decoder is not SURE about (escapes, duplicate keys, non-string
// entries, invalid UTF-8) returns 0 so the caller falls back to the Python
// parser — correctness is never traded for the fast path, and the miss is
// counted in the zero-copy hit-ratio telemetry.

// Content digest for the candidate-name blob — the ticket's cache key.
// Word-wise (8 bytes per multiply) because the byte-serial FNV-1a it
// replaced ran at ~1 byte/cycle and dominated the whole decode at 10k
// names. Collision quality only affects cache efficiency, never
// correctness: every consumer verifies equality with a blob memcmp.
inline uint64_t mix64(uint64_t x) {
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ull;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebull;
  x ^= x >> 31;
  return x;
}

uint64_t blob_digest(const char* d, size_t n) {
  uint64_t h = 1469598103934665603ull ^ (n * 0x9e3779b97f4a7c15ull);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t w;
    memcpy(&w, d + i, 8);
    h = (h ^ w) * 1099511628211ull;
    h = (h << 27) | (h >> 37);
  }
  uint64_t tail = 0;
  for (size_t j = 0; i < n; ++i, j += 8) {
    tail |= static_cast<uint64_t>(static_cast<uint8_t>(d[i])) << j;
  }
  h = (h ^ tail) * 1099511628211ull;
  return mix64(h);
}

std::atomic<int64_t> g_live_slots{0};

struct PredicateSlot {
  std::vector<char> pod;      // the Pod value's exact JSON bytes ("{}" if absent)
  std::vector<char> blob;     // candidate node names, '\0' after each
  std::vector<int32_t> offs;  // name i starts at offs[i]; offs[count] = blob end
  uint64_t digest = 0;        // FNV-1a 64 over blob (names + separators)
  int64_t decode_ns = 0;

  void reset() {
    pod.clear();
    blob.clear();
    offs.clear();
    digest = 0;
  }
};

bool is_json_ws(char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

struct Cursor {
  const char* p;
  const char* end;
};

void skip_ws(Cursor& c) {
  while (c.p < c.end && is_json_ws(*c.p)) ++c.p;
}

// c.p at the opening quote; leaves c.p past the closing quote. memchr does
// the scanning (the libc SIMD path), backslash-parity decides whether a
// quote is real.
bool skip_string(Cursor& c) {
  const char* p = c.p + 1;
  while (p < c.end) {
    const char* q =
        static_cast<const char*>(memchr(p, '"', c.end - p));
    if (q == nullptr) break;
    const char* b = q;
    while (b > p && b[-1] == '\\') --b;
    if ((q - b) % 2 == 0) {
      c.p = q + 1;
      return true;
    }
    p = q + 1;
  }
  c.p = c.end;
  return false;
}

bool skip_container(Cursor& c, char open, char close) {
  int depth = 0;
  while (c.p < c.end) {
    char ch = *c.p;
    if (ch == '"') {
      if (!skip_string(c)) return false;
      continue;
    }
    if (ch == open) {
      ++depth;
    } else if (ch == close) {
      --depth;
      if (depth == 0) {
        ++c.p;
        return true;
      }
    }
    ++c.p;
  }
  return false;
}

bool skip_value(Cursor& c) {
  skip_ws(c);
  if (c.p >= c.end) return false;
  char ch = *c.p;
  if (ch == '"') return skip_string(c);
  if (ch == '{') return skip_container(c, '{', '}');
  if (ch == '[') return skip_container(c, '[', ']');
  const char* start = c.p;
  while (c.p < c.end) {
    ch = *c.p;
    if (ch == ',' || ch == '}' || ch == ']' || is_json_ws(ch)) break;
    ++c.p;
  }
  return c.p > start;  // bare literal/number; delimiter checks follow outside
}

// Valid UTF-8 and no raw control characters (< 0x20) — the two conditions
// under which Python's json.loads would have accepted the same name bytes.
// One pass over the final blob ('\0' separators are the one allowed < 0x20).
bool blob_is_clean_utf8(const std::vector<char>& blob) {
  const unsigned char* p =
      reinterpret_cast<const unsigned char*>(blob.data());
  const unsigned char* end = p + blob.size();
  while (p < end) {
    unsigned char c = *p;
    if (c < 0x80) {
      if (c < 0x20 && c != '\0') return false;
      ++p;
      continue;
    }
    int n;
    uint32_t cp;
    if ((c & 0xE0) == 0xC0) {
      n = 1;
      cp = c & 0x1F;
      if (cp < 2) return false;  // overlong 2-byte
    } else if ((c & 0xF0) == 0xE0) {
      n = 2;
      cp = c & 0x0F;
    } else if ((c & 0xF8) == 0xF0) {
      n = 3;
      cp = c & 0x07;
    } else {
      return false;
    }
    if (end - p <= n) return false;
    for (int i = 1; i <= n; ++i) {
      if ((p[i] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (p[i] & 0x3F);
    }
    if (n == 2 && (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
      return false;
    if (n == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    p += n + 1;
  }
  return true;
}

// Fast path for the existing JSON predicate schema:
//   {"Pod": {...}, "NodeNames": ["n1", "n2", ...]}
// Returns 1 with the slot filled, or 0 when the body deviates from the
// shape in ANY way the caller's Python parser might read differently
// (escapes, duplicate NodeNames keys, non-string entries, an empty or
// missing NodeNames — Python's `or` chain falls through to "Nodes" there —
// trailing bytes, invalid UTF-8). The caller falls back to json.loads.
int32_t decode_predicate_json_impl(PredicateSlot* s, const char* body,
                                   int64_t len) {
  s->reset();
  // One reservation covers the whole tokenized output (names are a strict
  // subset of the body): growth reallocations would otherwise memmove the
  // ~200 KB blob several times at 10k names.
  s->blob.reserve(static_cast<size_t>(len));
  s->offs.reserve(static_cast<size_t>(len / 16) + 8);
  Cursor c{body, body + len};
  skip_ws(c);
  if (c.p >= c.end || *c.p != '{') return 0;
  ++c.p;
  const char* pod_b = nullptr;
  const char* pod_e = nullptr;
  const char* podl_b = nullptr;
  const char* podl_e = nullptr;
  bool saw_names = false;
  skip_ws(c);
  if (c.p < c.end && *c.p == '}') {
    ++c.p;
  } else {
    while (true) {
      skip_ws(c);
      if (c.p >= c.end || *c.p != '"') return 0;
      const char* kb = c.p + 1;
      if (!skip_string(c)) return 0;
      const char* ke = c.p - 1;
      skip_ws(c);
      if (c.p >= c.end || *c.p != ':') return 0;
      ++c.p;
      skip_ws(c);
      size_t klen = static_cast<size_t>(ke - kb);
      // A key containing an escape could DECODE to "Pod"/"NodeNames"
      // (e.g. "\u0050od") while comparing unequal on raw bytes here —
      // only the Python parser may interpret it.
      if (memchr(kb, '\\', klen) != nullptr) return 0;
      bool is_pod = (klen == 3 && memcmp(kb, "Pod", 3) == 0);
      bool is_podl = (klen == 3 && memcmp(kb, "pod", 3) == 0);
      bool is_names = (klen == 9 && memcmp(kb, "NodeNames", 9) == 0);
      if (is_names) {
        if (saw_names) return 0;  // duplicate key: json.loads keeps the last
        saw_names = true;
        if (c.p >= c.end || *c.p != '[') return 0;  // null/other type
        ++c.p;
        skip_ws(c);
        if (c.p < c.end && *c.p == ']') {
          ++c.p;
        } else {
          while (true) {
            // Names are short (10-40 bytes): a fused byte loop beats two
            // memchr calls per name — the compiler vectorizes the triple
            // compare, and escapes/quotes resolve in the same pass.
            if (c.p >= c.end) return 0;
            char ch = *c.p;
            while (ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r') {
              if (++c.p >= c.end) return 0;
              ch = *c.p;
            }
            if (ch != '"') return 0;
            ++c.p;
            const char* nb = c.p;
            const char* e = c.end;
            while (c.p < e) {
              ch = *c.p;
              // Stop on the closing quote, an escape, or anything outside
              // printable ASCII: valid k8s node names are RFC 1123 DNS
              // labels, so a control byte / UTF-8 name is a legitimate
              // fast-path miss (the Python parser decides what it means).
              if (static_cast<unsigned char>(ch) - 0x20u >= 0x5Fu ||
                  ch == '"' || ch == '\\')
                break;
              ++c.p;
            }
            if (c.p >= e || ch != '"') return 0;  // EOF/escape/non-ASCII
            s->offs.push_back(static_cast<int32_t>(s->blob.size()));
            s->blob.insert(s->blob.end(), nb, c.p);
            s->blob.push_back('\0');
            ++c.p;
            if (c.p >= e) return 0;
            ch = *c.p;
            while (ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r') {
              if (++c.p >= e) return 0;
              ch = *c.p;
            }
            if (ch == ',') {
              ++c.p;
              continue;
            }
            if (ch == ']') {
              ++c.p;
              break;
            }
            return 0;
          }
        }
      } else if (is_pod || is_podl) {
        if (c.p < c.end && *c.p == '{') {
          const char* vb = c.p;
          if (!skip_container(c, '{', '}')) return 0;
          if (is_pod) {
            pod_b = vb;
            pod_e = c.p;
          } else {
            podl_b = vb;
            podl_e = c.p;
          }
        } else {
          // Only a JSON null reads as "absent" the way Python's
          // `raw.get(...) or ...` chain does; any other type falls back.
          const char* vb = c.p;
          if (!skip_value(c)) return 0;
          if (c.p - vb != 4 || memcmp(vb, "null", 4) != 0) return 0;
        }
      } else {
        if (!skip_value(c)) return 0;
      }
      skip_ws(c);
      if (c.p >= c.end) return 0;
      if (*c.p == ',') {
        ++c.p;
        continue;
      }
      if (*c.p == '}') {
        ++c.p;
        break;
      }
      return 0;
    }
  }
  skip_ws(c);
  if (c.p != c.end) return 0;  // trailing bytes: json.loads would raise
  // Empty/missing NodeNames: Python's `or` chain falls through to "Nodes".
  if (!saw_names || s->offs.empty()) return 0;
  auto nonempty_obj = [](const char* b, const char* e) {
    Cursor t{b + 1, e};
    skip_ws(t);
    return t.p < t.end && *t.p != '}';
  };
  // `raw.get("Pod") or raw.get("pod") or {}`: an empty {} is falsy too.
  const char* ub = nullptr;
  const char* ue = nullptr;
  if (pod_b != nullptr && nonempty_obj(pod_b, pod_e)) {
    ub = pod_b;
    ue = pod_e;
  } else if (podl_b != nullptr && nonempty_obj(podl_b, podl_e)) {
    ub = podl_b;
    ue = podl_e;
  } else if (pod_b != nullptr) {
    ub = pod_b;
    ue = pod_e;
  } else if (podl_b != nullptr) {
    ub = podl_b;
    ue = podl_e;
  }
  if (ub != nullptr) {
    s->pod.assign(ub, ue);
  } else {
    s->pod = {'{', '}'};
  }
  s->offs.push_back(static_cast<int32_t>(s->blob.size()));
  s->digest = blob_digest(s->blob.data(), s->blob.size());
  return 1;
}

inline uint32_t read_u32le(const unsigned char* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// Compact binary predicate protocol (content type
// application/x-spark-predicate), length-prefixed frames:
//   "SPRD" | version u8 (=1) | pod_json_len u32le | pod JSON bytes
//   | names_count u32le | names_count x (len u16le | name bytes)
// Exact-length bodies only. Returns 1/0 like the JSON fast path; a 0 sends
// the caller to the pure-Python decoder, which raises the protocol error.
int32_t decode_predicate_binary_impl(PredicateSlot* s, const char* body,
                                     int64_t len) {
  s->reset();
  const unsigned char* p = reinterpret_cast<const unsigned char*>(body);
  const unsigned char* end = p + len;
  if (end - p < 13) return 0;
  if (memcmp(p, "SPRD", 4) != 0 || p[4] != 1) return 0;
  uint32_t pod_len = read_u32le(p + 5);
  p += 9;
  if (static_cast<uint64_t>(end - p) < pod_len + 4ull) return 0;
  s->pod.assign(p, p + pod_len);
  p += pod_len;
  uint32_t count = read_u32le(p);
  p += 4;
  // The count is attacker-controlled: clamp the reservation by what the
  // remaining body could possibly hold (>= 2 bytes per name frame) BEFORE
  // trusting it — an oversized reserve would throw bad_alloc across the C
  // ABI and terminate the process on a 13-byte request.
  if (static_cast<uint64_t>(count) > static_cast<uint64_t>(end - p) / 2)
    return 0;
  s->offs.reserve(count + 1);
  s->blob.reserve(static_cast<size_t>(end - p) + count);
  for (uint32_t i = 0; i < count; ++i) {
    if (end - p < 2) return 0;
    uint32_t n = static_cast<uint32_t>(p[0]) |
                 (static_cast<uint32_t>(p[1]) << 8);
    p += 2;
    if (static_cast<uint64_t>(end - p) < n) return 0;
    // A NUL inside a name would alias the blob's separator format (digest
    // and materialization would see two names): defer to the Python
    // decoder, which represents 'a\0b' faithfully.
    if (memchr(p, '\0', n) != nullptr) return 0;
    s->offs.push_back(static_cast<int32_t>(s->blob.size()));
    s->blob.insert(s->blob.end(), p, p + n);
    s->blob.push_back('\0');
    p += n;
  }
  if (p != end) return 0;
  if (!blob_is_clean_utf8(s->blob)) return 0;
  if (s->pod.empty()) s->pod = {'{', '}'};
  s->offs.push_back(static_cast<int32_t>(s->blob.size()));
  s->digest = blob_digest(s->blob.data(), s->blob.size());
  return 1;
}

// ------------------------------------------------------ HTTP/1.1 framer

// Event kinds / body-error codes mirrored by the ctypes bindings.
constexpr int32_t kNeedMore = 0;
constexpr int32_t kRequest = 1;
constexpr int32_t kReject = 2;
constexpr int32_t kErrTransferEncoding = 1;
constexpr int32_t kErrContentLength = 2;
constexpr int32_t kErrBodyTooLarge = 3;
constexpr int32_t kRejectHeaderTooLarge = 1;
constexpr int32_t kRejectRequestLine = 2;
constexpr int32_t kRejectHeaderLine = 3;
constexpr int32_t kFlagKeepAlive = 1;
constexpr int32_t kFlagCloseAfter = 2;
constexpr int32_t kFlagPredicate = 4;

struct IngestEvent {
  int32_t kind;
  int32_t status;     // reject-only: HTTP status (400/431)
  int32_t flags;      // kFlag*
  int32_t body_error; // kErr* (deferred into the routing layer's Request)
  int32_t err_code;   // kReject* detail for reject events
  int32_t pad_;
  int64_t method_off, method_len;
  int64_t target_off, target_len;
  int64_t head_off, head_len;  // full head incl. request line
  int64_t body_off, body_len;
  int64_t declared_len;        // Content-Length for 413 messages
  int64_t parse_ns;
};

struct IngestConn {
  std::vector<char> buf;
  size_t consumed = 0;   // prefix to drop at the next next() call
  size_t scan = 0;       // \r\n\r\n scan progress
  int state = 0;         // 0 headers, 1 body, 2 drain, 3 closed
  int64_t max_body = -1; // -1 = unlimited
  int64_t max_header = 65536;
  IngestEvent pend{};    // request meta carried from headers into body/drain
  size_t body_start = 0;
  size_t body_need = 0;
  int64_t drain_left = 0;
  // Last emitted request's body span, for zero-copy in-place decode.
  size_t last_body_off = 0;
  size_t last_body_len = 0;
};

bool token_ws(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

void trim(const char*& b, const char*& e) {
  while (b < e && token_ws(*b)) ++b;
  while (e > b && token_ws(e[-1])) --e;
}

bool iequal(const char* b, const char* e, const char* lit) {
  size_t n = strlen(lit);
  if (static_cast<size_t>(e - b) != n) return false;
  for (size_t i = 0; i < n; ++i) {
    char c = b[i];
    if (c >= 'A' && c <= 'Z') c += 32;
    if (c != lit[i]) return false;
  }
  return true;
}

// Parse the head [hb, he) into the pending event. Returns kRequest on
// success or kReject (with status/err_code set) — the same decisions
// transport_async._begin_request makes, byte for byte on the wire.
int32_t parse_head(IngestConn* conn, const char* hb, const char* he) {
  IngestEvent& ev = conn->pend;
  const char* base = conn->buf.data();
  // Request line: Python's str.split() — any whitespace runs — must yield
  // exactly [method, target, version] with version starting "HTTP/1.".
  const char* line_end = static_cast<const char*>(
      memchr(hb, '\r', he - hb));
  const char* rl_end = line_end != nullptr ? line_end : he;
  const char* toks[4];
  const char* tok_ends[4];
  int ntok = 0;
  const char* p = hb;
  while (p < rl_end) {
    while (p < rl_end && token_ws(*p)) ++p;
    if (p >= rl_end) break;
    const char* tb = p;
    while (p < rl_end && !token_ws(*p)) ++p;
    if (ntok < 4) {
      toks[ntok] = tb;
      tok_ends[ntok] = p;
    }
    ++ntok;
  }
  if (ntok != 3 || tok_ends[2] - toks[2] < 7 ||
      memcmp(toks[2], "HTTP/1.", 7) != 0) {
    ev.kind = kReject;
    ev.status = 400;
    ev.err_code = kRejectRequestLine;
    return kReject;
  }
  bool http10 = iequal(toks[2], tok_ends[2], "http/1.0");
  ev.method_off = toks[0] - base;
  ev.method_len = tok_ends[0] - toks[0];
  ev.target_off = toks[1] - base;
  ev.target_len = tok_ends[1] - toks[1];
  // Header lines.
  bool te_present = false;
  bool te_seen = false;
  bool cl_seen = false;
  bool cl_conflict = false;
  bool cl_bad = false;
  int64_t cl_value = 0;
  const char* cl_b = nullptr;
  const char* cl_e = nullptr;
  std::string conn_tok;  // first Connection header, lowered
  bool conn_seen = false;
  p = line_end != nullptr ? line_end : he;
  while (p < he) {
    if (*p == '\r' || *p == '\n') {
      ++p;
      continue;
    }
    const char* lb = p;
    const char* le = static_cast<const char*>(memchr(p, '\r', he - p));
    if (le == nullptr) le = he;
    p = le;
    const char* colon =
        static_cast<const char*>(memchr(lb, ':', le - lb));
    if (colon == nullptr) {
      ev.kind = kReject;
      ev.status = 400;
      ev.err_code = kRejectHeaderLine;
      return kReject;
    }
    const char* nb = lb;
    const char* ne = colon;
    const char* vb = colon + 1;
    const char* ve = le;
    trim(nb, ne);
    trim(vb, ve);
    if (iequal(nb, ne, "transfer-encoding")) {
      // Match the Python framer's `headers.get(...)` truthiness gate:
      // only the FIRST Transfer-Encoding header counts, and an empty
      // value is ignored.
      if (!te_seen) {
        te_seen = true;
        te_present = vb < ve;
      }
    } else if (iequal(nb, ne, "content-length")) {
      if (cl_seen) {
        if (static_cast<size_t>(ve - vb) !=
                static_cast<size_t>(cl_e - cl_b) ||
            memcmp(vb, cl_b, ve - vb) != 0) {
          cl_conflict = true;  // RFC 7230 3.3.2: differing duplicates
        }
      } else {
        cl_seen = true;
        cl_b = vb;
        cl_e = ve;
        if (vb == ve) {
          cl_bad = true;
        } else {
          for (const char* d = vb; d < ve; ++d) {
            if (*d < '0' || *d > '9') {
              cl_bad = true;
              break;
            }
          }
          if (!cl_bad) {
            cl_value = 0;
            for (const char* d = vb; d < ve; ++d) {
              if (cl_value > (INT64_MAX - 9) / 10) {
                cl_bad = true;  // absurd length: unframeable
                break;
              }
              cl_value = cl_value * 10 + (*d - '0');
            }
          }
        }
      }
    } else if (!conn_seen && iequal(nb, ne, "connection")) {
      conn_seen = true;
      conn_tok.assign(vb, ve);
      for (auto& ch : conn_tok) {
        if (ch >= 'A' && ch <= 'Z') ch += 32;
      }
    }
  }
  bool keep_alive;
  if (http10) {
    keep_alive = conn_tok.find("keep-alive") != std::string::npos;
  } else {
    keep_alive = conn_tok.find("close") == std::string::npos;
  }
  ev.kind = kRequest;
  ev.status = 0;
  ev.err_code = 0;
  ev.flags = keep_alive ? kFlagKeepAlive : 0;
  ev.body_error = 0;
  ev.body_off = 0;
  ev.body_len = 0;
  ev.declared_len = 0;
  // POST /predicates (query-string allowed): the hot-path flag the Python
  // side uses to route the body straight into a predicate slot.
  if (ev.method_len == 4 && memcmp(base + ev.method_off, "POST", 4) == 0) {
    const char* tb = base + ev.target_off;
    size_t tl = static_cast<size_t>(ev.target_len);
    const char* qm = static_cast<const char*>(memchr(tb, '?', tl));
    size_t plen = qm != nullptr ? static_cast<size_t>(qm - tb) : tl;
    if (plen == 11 && memcmp(tb, "/predicates", 11) == 0) {
      ev.flags |= kFlagPredicate;
    }
  }
  if (te_present) {
    ev.body_error = kErrTransferEncoding;
    ev.flags |= kFlagCloseAfter;
    return kRequest;
  }
  if (cl_conflict || cl_bad) {
    ev.body_error = kErrContentLength;
    ev.flags |= kFlagCloseAfter;
    return kRequest;
  }
  ev.declared_len = cl_seen ? cl_value : 0;
  return kRequest;
}

int32_t conn_next(IngestConn* conn, IngestEvent* out) {
  auto t0 = std::chrono::steady_clock::now();
  auto finish = [&](int32_t kind) {
    conn->pend.kind = kind;
    conn->pend.parse_ns +=
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count();
    *out = conn->pend;
    return kind;
  };
  if (conn->consumed > 0) {
    conn->buf.erase(conn->buf.begin(),
                    conn->buf.begin() + conn->consumed);
    conn->consumed = 0;
  }
  if (conn->state == 3) return finish(kNeedMore);
  if (conn->state == 0) {
    conn->pend = IngestEvent{};
    const char* data = conn->buf.data();
    size_t size = conn->buf.size();
    size_t from = conn->scan > 3 ? conn->scan - 3 : 0;
    const char* hit = nullptr;
    while (from + 4 <= size) {
      const char* q = static_cast<const char*>(
          memchr(data + from, '\r', size - from));
      if (q == nullptr || static_cast<size_t>(q - data) + 4 > size) break;
      if (memcmp(q, "\r\n\r\n", 4) == 0) {
        hit = q;
        break;
      }
      from = q - data + 1;
    }
    if (hit == nullptr) {
      if (static_cast<int64_t>(size) > conn->max_header) {
        conn->state = 3;
        conn->pend.status = 431;
        conn->pend.err_code = kRejectHeaderTooLarge;
        return finish(kReject);
      }
      conn->scan = size;
      return finish(kNeedMore);
    }
    size_t idx = hit - data;
    conn->scan = 0;
    int32_t kind = parse_head(conn, data, data + idx);
    conn->pend.head_off = 0;
    conn->pend.head_len = idx;
    if (kind == kReject) {
      conn->state = 3;
      return finish(kReject);
    }
    conn->body_start = idx + 4;
    if (conn->pend.body_error != 0) {
      // TE / bad Content-Length: the body cannot be framed — emit the
      // request with the deferred error; nothing after it is parseable.
      conn->state = 3;
      conn->last_body_len = 0;
      return finish(kRequest);
    }
    int64_t length = conn->pend.declared_len;
    if (conn->max_body >= 0 && length > conn->max_body) {
      conn->pend.body_error = kErrBodyTooLarge;
      conn->state = 2;
      conn->drain_left = length;
      // fall through to drain below
    } else {
      conn->body_need = static_cast<size_t>(length);
      conn->state = 1;
      // fall through to body below
    }
  }
  if (conn->state == 1) {
    if (conn->buf.size() < conn->body_start + conn->body_need)
      return finish(kNeedMore);
    conn->pend.body_off = conn->body_start;
    conn->pend.body_len = conn->body_need;
    conn->last_body_off = conn->body_start;
    conn->last_body_len = conn->body_need;
    conn->consumed = conn->body_start + conn->body_need;
    conn->state = 0;
    return finish(kRequest);
  }
  // state 2: discard an oversized body in place, then emit the 413 request
  // with keep-alive framing intact.
  size_t have = conn->buf.size() > conn->body_start
                    ? conn->buf.size() - conn->body_start
                    : 0;
  size_t take = static_cast<size_t>(
      std::min<int64_t>(conn->drain_left, static_cast<int64_t>(have)));
  if (take > 0) {
    conn->buf.erase(conn->buf.begin() + conn->body_start,
                    conn->buf.begin() + conn->body_start + take);
    conn->drain_left -= static_cast<int64_t>(take);
  }
  if (conn->drain_left > 0) return finish(kNeedMore);
  conn->pend.body_off = 0;
  conn->pend.body_len = 0;
  conn->last_body_len = 0;
  conn->consumed = conn->body_start;
  conn->state = 0;
  return finish(kRequest);
}

}  // namespace

extern "C" {

// ---- arena ----------------------------------------------------------------

void* arena_create() { return new ClusterArena(); }

void arena_destroy(void* h) { delete static_cast<ClusterArena*>(h); }

void arena_upsert(void* h, int64_t idx, const int64_t* alloc3, int32_t zone,
                  int32_t unschedulable, int32_t ready, int32_t lr_driver,
                  int32_t lr_executor) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  a->ensure(idx);
  std::memcpy(&a->alloc[idx * kDims], alloc3, kDims * sizeof(int64_t));
  a->zone_id[idx] = zone;
  a->unschedulable[idx] = unschedulable ? 1 : 0;
  a->ready[idx] = ready ? 1 : 0;
  a->lr_driver[idx] = lr_driver;
  a->lr_executor[idx] = lr_executor;
  a->valid[idx] = 1;
}

void arena_remove(void* h, int64_t idx) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  if (idx < a->capacity) {
    a->valid[idx] = 0;
    a->name_rank[idx] = kInt32Inf;
  }
}

// ranks: [n_pairs] slot indices in name-sorted order. Slots not listed keep
// their previous rank only if still valid; callers pass the full live set.
void arena_set_name_ranks(void* h, const int64_t* sorted_idx, int64_t n) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  std::fill(a->name_rank.begin(), a->name_rank.end(), kInt32Inf);
  for (int64_t r = 0; r < n; ++r) {
    int64_t idx = sorted_idx[r];
    a->ensure(idx);
    a->name_rank[idx] = static_cast<int32_t>(r);
  }
}

// Scatter EXPLICIT rank values onto slots. The gapped (order-maintenance)
// name-rank scheme rides this: every kernel consumes rank ORDER only, so
// values need not be dense — a node ADD assigns a midpoint between its
// lexicographic neighbours' values and touches ONE slot, where the dense
// scheme (arena_set_name_ranks) renumbers every slot per add. Unlisted
// slots keep their previous ranks.
void arena_set_name_rank_values(void* h, const int64_t* idx,
                                const int32_t* ranks, int64_t n) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  for (int64_t i = 0; i < n; ++i) {
    a->ensure(idx[i]);
    a->name_rank[idx[i]] = ranks[i];
  }
}

// Materialize the solver inputs for slots [0, n) into caller buffers.
// usage/overhead are [n*3] int64 (sparse scatter done by the caller into a
// reusable buffer); outputs are the ClusterTensors fields.
void arena_snapshot(void* h, int64_t n, const int64_t* usage,
                    const int64_t* overhead, int32_t* available,
                    int32_t* schedulable, int32_t* zone_id, int32_t* name_rank,
                    int32_t* lr_driver, int32_t* lr_executor,
                    uint8_t* unschedulable, uint8_t* ready, uint8_t* valid) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  a->ensure(n > 0 ? n - 1 : 0);
  for (int64_t i = 0; i < n; ++i) {
    for (int d = 0; d < kDims; ++d) {
      int64_t al = a->alloc[i * kDims + d];
      int64_t ov = overhead[i * kDims + d];
      int64_t us = usage[i * kDims + d];
      available[i * kDims + d] = clip64(al - us - ov);
      schedulable[i * kDims + d] = clip64(al - ov);
    }
    zone_id[i] = a->zone_id[i];
    name_rank[i] = a->name_rank[i];
    lr_driver[i] = a->lr_driver[i];
    lr_executor[i] = a->lr_executor[i];
    unschedulable[i] = a->unschedulable[i];
    ready[i] = a->ready[i];
    valid[i] = a->valid[i];
  }
}

// Incremental twin of arena_snapshot: recompute ONLY `rows` into the
// caller's RESIDENT output buffers (each sized [n] rows). This is the
// C-speed half of the O(K + changed) tensor build — the per-window full
// materialization pass over all n slots was a measured ~35-50 ms at the
// million-node tier even when a handful of rows had changed. Rows at or
// past n are skipped defensively (the caller's buffers bound the write).
void arena_snapshot_rows(void* h, const int64_t* rows, int64_t k, int64_t n,
                         const int64_t* usage, const int64_t* overhead,
                         int32_t* available, int32_t* schedulable,
                         int32_t* zone_id, int32_t* name_rank,
                         int32_t* lr_driver, int32_t* lr_executor,
                         uint8_t* unschedulable, uint8_t* ready,
                         uint8_t* valid) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  a->ensure(n > 0 ? n - 1 : 0);
  for (int64_t r = 0; r < k; ++r) {
    int64_t i = rows[r];
    if (i < 0 || i >= n) continue;
    for (int d = 0; d < kDims; ++d) {
      int64_t al = a->alloc[i * kDims + d];
      int64_t ov = overhead[i * kDims + d];
      int64_t us = usage[i * kDims + d];
      available[i * kDims + d] = clip64(al - us - ov);
      schedulable[i * kDims + d] = clip64(al - ov);
    }
    zone_id[i] = a->zone_id[i];
    name_rank[i] = a->name_rank[i];
    lr_driver[i] = a->lr_driver[i];
    lr_executor[i] = a->lr_executor[i];
    unschedulable[i] = a->unschedulable[i];
    ready[i] = a->ready[i];
    valid[i] = a->valid[i];
  }
}

int64_t arena_capacity(void* h) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->capacity;
}

// ---- queue ----------------------------------------------------------------

void* queue_create(int64_t buckets, int64_t buffer_size) {
  return new ShardedQueue(static_cast<size_t>(buckets),
                          static_cast<size_t>(buffer_size));
}

void queue_destroy(void* h) { delete static_cast<ShardedQueue*>(h); }

int64_t queue_bucket(void* h, const char* key, int64_t key_len) {
  auto* q = static_cast<ShardedQueue*>(h);
  return fnv1a32(key, static_cast<size_t>(key_len)) % q->shards.size();
}

// Dedup semantics of queue.go:58-68: every request marks the key inflight
// if absent; a request whose key was already inflight is dropped (the
// consumer reads the latest object from the store anyway) UNLESS it is a
// delete — deletes always enqueue so created-then-deleted objects still
// reach the backend. Returns 0 when dropped, 1 when enqueued. Blocks while
// the shard buffer is full.
int32_t queue_add_if_absent(void* h, const char* key, int64_t key_len,
                            uint64_t ticket, int32_t is_delete) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::string k(key, static_cast<size_t>(key_len));
  bool added;
  {
    std::lock_guard<std::mutex> lock(q->inflight_mu);
    added = q->inflight.insert(k).second;
  }
  if (!added && !is_delete) return 0;
  Shard& s = q->shards[fnv1a32(key, key_len) % q->shards.size()];
  std::unique_lock<std::mutex> lock(s.mu);
  s.not_full.wait(lock, [&] { return s.tickets.size() < q->buffer_size; });
  s.tickets.push_back(ticket);
  s.not_empty.notify_one();
  return 1;
}

// Non-blocking variant (TryAddIfAbsent, queue.go:73-88): returns -1 if the
// shard buffer is full (caller handles overflow; the inflight mark this
// call added is rolled back), else as add_if_absent.
int32_t queue_try_add_if_absent(void* h, const char* key, int64_t key_len,
                                uint64_t ticket, int32_t is_delete) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::string k(key, static_cast<size_t>(key_len));
  bool added;
  {
    std::lock_guard<std::mutex> lock(q->inflight_mu);
    added = q->inflight.insert(k).second;
  }
  if (!added && !is_delete) return 0;
  Shard& s = q->shards[fnv1a32(key, key_len) % q->shards.size()];
  std::unique_lock<std::mutex> lock(s.mu);
  if (s.tickets.size() >= q->buffer_size) {
    lock.unlock();
    if (added) {
      std::lock_guard<std::mutex> ilock(q->inflight_mu);
      q->inflight.erase(k);
    }
    return -1;
  }
  s.tickets.push_back(ticket);
  s.not_empty.notify_one();
  return 1;
}

// Blocking pop with timeout; returns 1 and fills *ticket, or 0 on timeout.
int32_t queue_pop(void* h, int64_t bucket, int64_t timeout_ms,
                  uint64_t* ticket) {
  auto* q = static_cast<ShardedQueue*>(h);
  Shard& s = q->shards[static_cast<size_t>(bucket)];
  std::unique_lock<std::mutex> lock(s.mu);
  if (!s.not_empty.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                            [&] { return !s.tickets.empty(); })) {
    return 0;
  }
  *ticket = s.tickets.front();
  s.tickets.pop_front();
  s.not_full.notify_one();
  return 1;
}

// Consumers release the key from the inflight set when they start working
// on it, so later mutations re-enqueue (queue.go:90-104).
void queue_release(void* h, const char* key, int64_t key_len) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::lock_guard<std::mutex> lock(q->inflight_mu);
  q->inflight.erase(std::string(key, static_cast<size_t>(key_len)));
}

int64_t queue_len(void* h, int64_t bucket) {
  auto* q = static_cast<ShardedQueue*>(h);
  Shard& s = q->shards[static_cast<size_t>(bucket)];
  std::lock_guard<std::mutex> lock(s.mu);
  return static_cast<int64_t>(s.tickets.size());
}

int64_t queue_num_buckets(void* h) {
  auto* q = static_cast<ShardedQueue*>(h);
  return static_cast<int64_t>(q->shards.size());
}

// ---- ingest: predicate slots ----------------------------------------------

void* pslot_create() {
  g_live_slots.fetch_add(1, std::memory_order_relaxed);
  return new PredicateSlot();
}

void pslot_destroy(void* h) {
  g_live_slots.fetch_sub(1, std::memory_order_relaxed);
  delete static_cast<PredicateSlot*>(h);
}

int64_t ingest_live_slots() {
  return g_live_slots.load(std::memory_order_relaxed);
}

int32_t predicate_decode_json(void* h, const char* body, int64_t len) {
  auto* s = static_cast<PredicateSlot*>(h);
  auto t0 = std::chrono::steady_clock::now();
  int32_t rc = decode_predicate_json_impl(s, body, len);
  s->decode_ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  return rc;
}

int32_t predicate_decode_binary(void* h, const char* body, int64_t len) {
  auto* s = static_cast<PredicateSlot*>(h);
  auto t0 = std::chrono::steady_clock::now();
  int32_t rc = decode_predicate_binary_impl(s, body, len);
  s->decode_ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  return rc;
}

const char* pslot_pod_ptr(void* h) {
  return static_cast<PredicateSlot*>(h)->pod.data();
}

int64_t pslot_pod_len(void* h) {
  return static_cast<int64_t>(static_cast<PredicateSlot*>(h)->pod.size());
}

const char* pslot_blob_ptr(void* h) {
  return static_cast<PredicateSlot*>(h)->blob.data();
}

int64_t pslot_blob_len(void* h) {
  return static_cast<int64_t>(static_cast<PredicateSlot*>(h)->blob.size());
}

const int32_t* pslot_offs_ptr(void* h) {
  return static_cast<PredicateSlot*>(h)->offs.data();
}

int64_t pslot_names_count(void* h) {
  auto* s = static_cast<PredicateSlot*>(h);
  return s->offs.empty() ? 0
                         : static_cast<int64_t>(s->offs.size()) - 1;
}

uint64_t pslot_digest(void* h) {
  return static_cast<PredicateSlot*>(h)->digest;
}

int64_t pslot_decode_ns(void* h) {
  return static_cast<PredicateSlot*>(h)->decode_ns;
}

int32_t pslot_blob_equal(void* ha, void* hb) {
  auto* a = static_cast<PredicateSlot*>(ha);
  auto* b = static_cast<PredicateSlot*>(hb);
  return a->blob.size() == b->blob.size() &&
                 memcmp(a->blob.data(), b->blob.data(), a->blob.size()) == 0
             ? 1
             : 0;
}

// ---- ingest: HTTP framer --------------------------------------------------

void* ingest_conn_create(int64_t max_body_bytes, int64_t max_header_bytes) {
  auto* c = new IngestConn();
  c->max_body = max_body_bytes;
  if (max_header_bytes > 0) c->max_header = max_header_bytes;
  return c;
}

void ingest_conn_destroy(void* h) { delete static_cast<IngestConn*>(h); }

void ingest_conn_feed(void* h, const char* data, int64_t len) {
  auto* c = static_cast<IngestConn*>(h);
  if (c->state == 3) return;  // closed: discard (drain-before-close)
  c->buf.insert(c->buf.end(), data, data + len);
}

int32_t ingest_conn_next(void* h, IngestEvent* out) {
  return conn_next(static_cast<IngestConn*>(h), out);
}

const char* ingest_conn_ptr(void* h) {
  return static_cast<IngestConn*>(h)->buf.data();
}

// Decode the LAST emitted request's body straight out of the connection
// buffer into a slot — the zero-copy hand-off (socket -> conn buffer ->
// arena slot; the body bytes never become a Python object). Valid only
// until the next ingest_conn_next call.
int32_t ingest_conn_decode_json(void* h, void* slot) {
  auto* c = static_cast<IngestConn*>(h);
  return predicate_decode_json(
      slot, c->buf.data() + c->last_body_off,
      static_cast<int64_t>(c->last_body_len));
}

int32_t ingest_conn_decode_binary(void* h, void* slot) {
  auto* c = static_cast<IngestConn*>(h);
  return predicate_decode_binary(
      slot, c->buf.data() + c->last_body_off,
      static_cast<int64_t>(c->last_body_len));
}

}  // extern "C"
