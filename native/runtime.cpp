// Native runtime for the TPU scheduler framework.
//
// Two host-side components that sit on the request path around the XLA
// solver (the runtime slots of SURVEY.md §2d):
//
//   ClusterArena  — dense per-slot cluster state (allocatable, zone, flags,
//                   priority ranks) with O(1) upsert/remove and a single-call
//                   snapshot that materializes the ClusterTensors inputs
//                   (available = clip(alloc - usage - overhead),
//                   schedulable = clip(alloc - overhead)) into caller
//                   buffers. Replaces the per-request Python walk over all
//                   nodes (the reference rebuilds string-keyed maps per
//                   request, resources.go:61-100; we rebuild nothing).
//
//   ShardedQueue  — the async write-back queue (store/queue.go:22-144
//                   semantics): per-key dedup via an inflight set, FNV-1a
//                   sharding so one key always lands on the same consumer,
//                   bounded per-shard buffers, blocking/non-blocking add,
//                   blocking pop with timeout. Payloads stay in Python;
//                   the queue moves opaque u64 ticket ids.
//
// Exposed as a C ABI for ctypes. No Python.h dependency so it builds with
// a bare g++ -shared -fPIC.

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

constexpr int kDims = 3;
constexpr int32_t kInt32Inf = 2147483647 / 2;  // models/resources.INT32_INF

inline int32_t clip64(int64_t v) {
  if (v > kInt32Inf) return kInt32Inf;
  if (v < -kInt32Inf) return -kInt32Inf;
  return static_cast<int32_t>(v);
}

// ----------------------------------------------------------- ClusterArena

struct ClusterArena {
  std::mutex mu;
  // Slot-indexed, grown on demand; slot indices are owned by the Python
  // NodeRegistry (stable across churn, recycled+masked like cluster.py).
  std::vector<int64_t> alloc;        // [cap * 3]
  std::vector<int32_t> zone_id;      // [cap]
  std::vector<int32_t> name_rank;    // [cap]
  std::vector<int32_t> lr_driver;    // [cap]
  std::vector<int32_t> lr_executor;  // [cap]
  std::vector<uint8_t> unschedulable;
  std::vector<uint8_t> ready;
  std::vector<uint8_t> valid;
  int64_t capacity = 0;

  void ensure(int64_t idx) {
    if (idx < capacity) return;
    int64_t cap = std::max<int64_t>(8, capacity);
    while (cap <= idx) cap *= 2;
    alloc.resize(cap * kDims, 0);
    zone_id.resize(cap, 0);
    name_rank.resize(cap, kInt32Inf);
    lr_driver.resize(cap, kInt32Inf);
    lr_executor.resize(cap, kInt32Inf);
    unschedulable.resize(cap, 0);
    ready.resize(cap, 0);
    valid.resize(cap, 0);
    capacity = cap;
  }
};

// ----------------------------------------------------------- ShardedQueue

struct Shard {
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<uint64_t> tickets;
};

struct ShardedQueue {
  std::vector<Shard> shards;
  size_t buffer_size;
  std::mutex inflight_mu;
  std::unordered_set<std::string> inflight;

  ShardedQueue(size_t buckets, size_t buffer)
      : shards(buckets), buffer_size(buffer) {}
};

uint32_t fnv1a32(const char* data, size_t len) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < len; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= 16777619u;
  }
  return h;
}

}  // namespace

extern "C" {

// ---- arena ----------------------------------------------------------------

void* arena_create() { return new ClusterArena(); }

void arena_destroy(void* h) { delete static_cast<ClusterArena*>(h); }

void arena_upsert(void* h, int64_t idx, const int64_t* alloc3, int32_t zone,
                  int32_t unschedulable, int32_t ready, int32_t lr_driver,
                  int32_t lr_executor) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  a->ensure(idx);
  std::memcpy(&a->alloc[idx * kDims], alloc3, kDims * sizeof(int64_t));
  a->zone_id[idx] = zone;
  a->unschedulable[idx] = unschedulable ? 1 : 0;
  a->ready[idx] = ready ? 1 : 0;
  a->lr_driver[idx] = lr_driver;
  a->lr_executor[idx] = lr_executor;
  a->valid[idx] = 1;
}

void arena_remove(void* h, int64_t idx) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  if (idx < a->capacity) {
    a->valid[idx] = 0;
    a->name_rank[idx] = kInt32Inf;
  }
}

// ranks: [n_pairs] slot indices in name-sorted order. Slots not listed keep
// their previous rank only if still valid; callers pass the full live set.
void arena_set_name_ranks(void* h, const int64_t* sorted_idx, int64_t n) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  std::fill(a->name_rank.begin(), a->name_rank.end(), kInt32Inf);
  for (int64_t r = 0; r < n; ++r) {
    int64_t idx = sorted_idx[r];
    a->ensure(idx);
    a->name_rank[idx] = static_cast<int32_t>(r);
  }
}

// Materialize the solver inputs for slots [0, n) into caller buffers.
// usage/overhead are [n*3] int64 (sparse scatter done by the caller into a
// reusable buffer); outputs are the ClusterTensors fields.
void arena_snapshot(void* h, int64_t n, const int64_t* usage,
                    const int64_t* overhead, int32_t* available,
                    int32_t* schedulable, int32_t* zone_id, int32_t* name_rank,
                    int32_t* lr_driver, int32_t* lr_executor,
                    uint8_t* unschedulable, uint8_t* ready, uint8_t* valid) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  a->ensure(n > 0 ? n - 1 : 0);
  for (int64_t i = 0; i < n; ++i) {
    for (int d = 0; d < kDims; ++d) {
      int64_t al = a->alloc[i * kDims + d];
      int64_t ov = overhead[i * kDims + d];
      int64_t us = usage[i * kDims + d];
      available[i * kDims + d] = clip64(al - us - ov);
      schedulable[i * kDims + d] = clip64(al - ov);
    }
    zone_id[i] = a->zone_id[i];
    name_rank[i] = a->name_rank[i];
    lr_driver[i] = a->lr_driver[i];
    lr_executor[i] = a->lr_executor[i];
    unschedulable[i] = a->unschedulable[i];
    ready[i] = a->ready[i];
    valid[i] = a->valid[i];
  }
}

int64_t arena_capacity(void* h) {
  auto* a = static_cast<ClusterArena*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->capacity;
}

// ---- queue ----------------------------------------------------------------

void* queue_create(int64_t buckets, int64_t buffer_size) {
  return new ShardedQueue(static_cast<size_t>(buckets),
                          static_cast<size_t>(buffer_size));
}

void queue_destroy(void* h) { delete static_cast<ShardedQueue*>(h); }

int64_t queue_bucket(void* h, const char* key, int64_t key_len) {
  auto* q = static_cast<ShardedQueue*>(h);
  return fnv1a32(key, static_cast<size_t>(key_len)) % q->shards.size();
}

// Dedup semantics of queue.go:58-68: every request marks the key inflight
// if absent; a request whose key was already inflight is dropped (the
// consumer reads the latest object from the store anyway) UNLESS it is a
// delete — deletes always enqueue so created-then-deleted objects still
// reach the backend. Returns 0 when dropped, 1 when enqueued. Blocks while
// the shard buffer is full.
int32_t queue_add_if_absent(void* h, const char* key, int64_t key_len,
                            uint64_t ticket, int32_t is_delete) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::string k(key, static_cast<size_t>(key_len));
  bool added;
  {
    std::lock_guard<std::mutex> lock(q->inflight_mu);
    added = q->inflight.insert(k).second;
  }
  if (!added && !is_delete) return 0;
  Shard& s = q->shards[fnv1a32(key, key_len) % q->shards.size()];
  std::unique_lock<std::mutex> lock(s.mu);
  s.not_full.wait(lock, [&] { return s.tickets.size() < q->buffer_size; });
  s.tickets.push_back(ticket);
  s.not_empty.notify_one();
  return 1;
}

// Non-blocking variant (TryAddIfAbsent, queue.go:73-88): returns -1 if the
// shard buffer is full (caller handles overflow; the inflight mark this
// call added is rolled back), else as add_if_absent.
int32_t queue_try_add_if_absent(void* h, const char* key, int64_t key_len,
                                uint64_t ticket, int32_t is_delete) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::string k(key, static_cast<size_t>(key_len));
  bool added;
  {
    std::lock_guard<std::mutex> lock(q->inflight_mu);
    added = q->inflight.insert(k).second;
  }
  if (!added && !is_delete) return 0;
  Shard& s = q->shards[fnv1a32(key, key_len) % q->shards.size()];
  std::unique_lock<std::mutex> lock(s.mu);
  if (s.tickets.size() >= q->buffer_size) {
    lock.unlock();
    if (added) {
      std::lock_guard<std::mutex> ilock(q->inflight_mu);
      q->inflight.erase(k);
    }
    return -1;
  }
  s.tickets.push_back(ticket);
  s.not_empty.notify_one();
  return 1;
}

// Blocking pop with timeout; returns 1 and fills *ticket, or 0 on timeout.
int32_t queue_pop(void* h, int64_t bucket, int64_t timeout_ms,
                  uint64_t* ticket) {
  auto* q = static_cast<ShardedQueue*>(h);
  Shard& s = q->shards[static_cast<size_t>(bucket)];
  std::unique_lock<std::mutex> lock(s.mu);
  if (!s.not_empty.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                            [&] { return !s.tickets.empty(); })) {
    return 0;
  }
  *ticket = s.tickets.front();
  s.tickets.pop_front();
  s.not_full.notify_one();
  return 1;
}

// Consumers release the key from the inflight set when they start working
// on it, so later mutations re-enqueue (queue.go:90-104).
void queue_release(void* h, const char* key, int64_t key_len) {
  auto* q = static_cast<ShardedQueue*>(h);
  std::lock_guard<std::mutex> lock(q->inflight_mu);
  q->inflight.erase(std::string(key, static_cast<size_t>(key_len)));
}

int64_t queue_len(void* h, int64_t bucket) {
  auto* q = static_cast<ShardedQueue*>(h);
  Shard& s = q->shards[static_cast<size_t>(bucket)];
  std::lock_guard<std::mutex> lock(s.mu);
  return static_cast<int64_t>(s.tickets.size());
}

int64_t queue_num_buckets(void* h) {
  auto* q = static_cast<ShardedQueue*>(h);
  return static_cast<int64_t>(q->shards.size());
}

}  // extern "C"
