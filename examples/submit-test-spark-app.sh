#!/usr/bin/env bash
# Mock a Spark application launch: create a fully-annotated driver pod and
# its executor pods directly with kubectl (no spark-submit needed) — the
# reference's examples/submit-test-spark-app.sh flow.
#
#   examples/submit-test-spark-app.sh <app-id> [num-executors]
set -euo pipefail

APP_ID="${1:?usage: submit-test-spark-app.sh <app-id> [num-executors]}"
NUM_EXECUTORS="${2:-2}"
DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

render() { # file name app_id [executor-count]
  sed -e "s/name: NAME/name: $2/" -e "s/APP_ID/$3/" \
      ${4:+-e "s/spark-executor-count: \"8\"/spark-executor-count: \"$4\"/"} \
      "$1"
}

render "$DIR/driver.template.yml" "$APP_ID-driver" "$APP_ID" "$NUM_EXECUTORS" \
  | kubectl apply -f -

# Executors normally launch after the driver runs; creating them up front
# exercises the same reservation-binding path.
for i in $(seq 1 "$NUM_EXECUTORS"); do
  render "$DIR/executor.template.yml" "$APP_ID-exec-$i" "$APP_ID" \
    | kubectl apply -f -
done

echo "submitted $APP_ID: 1 driver + $NUM_EXECUTORS executors"
echo "watch: kubectl -n spark get pods -l spark-app-id=$APP_ID -o wide"
echo "reservation: kubectl -n spark get resourcereservations $APP_ID -o yaml"
