"""Business events (internal/events/events.go:27-83).

Structured JSON-line events with the reference's event names, so downstream
event pipelines keyed on `foundry.spark.scheduler.*` carry over. The sink is
pluggable: any callable taking the event dict (default: a JSON line to the
given stream). Tests pass a list-appending sink.
"""

from __future__ import annotations

import json
import sys
import time

APPLICATION_SCHEDULED = "foundry.spark.scheduler.application_scheduled"
DEMAND_CREATED = "foundry.spark.scheduler.demand_created"
DEMAND_DELETED = "foundry.spark.scheduler.demand_deleted"


class EventEmitter:
    def __init__(self, sink=None, instance_group_label: str = "instance-group", clock=time.time):
        if sink is None:
            # Resolve sys.stderr at EMIT time, not construction: capturing
            # the stream object here silently ignores any later stderr
            # redirection (capsys, contextlib.redirect_stderr, a daemon
            # re-pointing fd 2) for an emitter built before it.
            def sink(event):
                sys.stderr.write(json.dumps(event) + "\n")

        self._sink = sink
        self._label = instance_group_label
        self._clock = clock

    def _emit(self, name: str, values: dict) -> None:
        self._sink({"event": name, "time": self._clock(), **values})

    def emit_application_scheduled(self, pod, app_resources) -> None:
        """events.go:35-58: emitted once the driver and all min executors
        have reservations."""
        from spark_scheduler_tpu.core.sparkpods import (
            SPARK_APP_ID_LABEL,
            find_instance_group,
        )

        d = app_resources.driver_resources
        e = app_resources.executor_resources
        self._emit(
            APPLICATION_SCHEDULED,
            {
                "instanceGroup": find_instance_group(pod, self._label) or "",
                "sparkAppID": pod.labels.get(SPARK_APP_ID_LABEL, ""),
                "driverCpuMilli": d.cpu_milli,
                "driverMemoryKib": d.mem_kib,
                "driverNvidiaGpuMilli": d.gpu_milli,
                "executorCpuMilli": e.cpu_milli,
                "executorMemoryKib": e.mem_kib,
                "executorNvidiaGpuMilli": e.gpu_milli,
                "minExecutorCount": app_resources.min_executor_count,
                "maxExecutorCount": app_resources.max_executor_count,
            },
        )

    def emit_demand_created(self, demand) -> None:
        self._emit(
            DEMAND_CREATED,
            {
                "instanceGroup": demand.spec.instance_group,
                "demandNamespace": demand.namespace,
                "demandName": demand.name,
            },
        )

    def emit_demand_deleted(self, demand, source: str) -> None:
        self._emit(
            DEMAND_DELETED,
            {
                "instanceGroup": demand.spec.instance_group,
                "demandNamespace": demand.namespace,
                "demandName": demand.name,
                "source": source,
            },
        )
