"""Simulated-RTT device shim.

The serving ceiling this repo is attacking is the tunneled-TPU device
round trip (`device_rtt_floor_ms`, ~70-104 ms per window in every
BENCH_r05 serving section) — but CI and the dev box run on local CPU,
where every device boundary is microseconds and the fused dispatch's
amortization property (K windows per round trip) is invisible. This shim
makes it measurable WITHOUT hardware: installed into the solver's device
hook (core/solver.set_device_shim), it sleeps a configurable share of the
round trip at each boundary, on the thread that would pay it over a real
tunnel:

  "h2d"      the dispatcher thread, once per device DISPATCH (window-batch
             upload + program launch RPC). This is the serialized cost a
             fused K-window batch pays once where K sequential dispatches
             pay it K times.
  "dispatch" a pool worker thread, once per pooled slot program launch
             (overlaps across slots, like the real per-device RPCs).
  "d2h"      the fetch-pool thread, once per decision-blob pull
             (concurrent pulls overlap, like the tunnel's concurrent
             device_get RPCs).

Default split: h2d and d2h each take rtt_ms/2, dispatch takes 0 — one
unfused window costs one full round trip; a fused K-window dispatch costs
one round trip for all K. Event counts are recorded per kind, so tests
assert the amortization structurally (fused serving of K windows fires
ONE h2d and ONE d2h) rather than by wall clock.

`tunnel_serialized=True` models a SHARED device link: every boundary's
sleep holds one tunnel lock, so concurrent transfers from different
threads queue behind each other instead of overlapping. That is the
regime where the fleet's per-cluster round trips pile up (F windows = F
serialized RTTs) and the fused fleet dispatch's single launch pays once
— the stacked-vs-unstacked fleet bench runs BOTH arms under this mode so
the A/B measures launch fusion, not sleep overlap. Default False keeps
PR 19's overlapping-transfer semantics (independent per-device RPCs).
"""

from __future__ import annotations

import threading
import time


class SimulatedRTT:
    """Context-manager shim: `with SimulatedRTT(50.0) as rtt: ...` serves
    every window inside the block against a simulated 50 ms device round
    trip; `rtt.counts` holds the per-boundary event counts."""

    def __init__(
        self,
        rtt_ms: float = 50.0,
        *,
        h2d_ms: float | None = None,
        dispatch_ms: float = 0.0,
        d2h_ms: float | None = None,
        tunnel_serialized: bool = False,
    ):
        half = rtt_ms / 2.0
        self.rtt_ms = rtt_ms
        self.h2d_ms = half if h2d_ms is None else h2d_ms
        self.dispatch_ms = dispatch_ms
        self.d2h_ms = half if d2h_ms is None else d2h_ms
        self.tunnel_serialized = tunnel_serialized
        self.counts = {"h2d": 0, "dispatch": 0, "d2h": 0}
        self._lock = threading.Lock()
        self._tunnel = threading.Lock()
        self._prior = None
        self._installed = False

    def __call__(self, kind: str) -> None:
        with self._lock:
            if kind in self.counts:
                self.counts[kind] += 1
        ms = {
            "h2d": self.h2d_ms,
            "dispatch": self.dispatch_ms,
            "d2h": self.d2h_ms,
        }.get(kind, 0.0)
        if ms > 0:
            if self.tunnel_serialized:
                # One shared link: this transfer occupies the tunnel for
                # its full duration, queueing concurrent boundaries.
                with self._tunnel:
                    time.sleep(ms / 1e3)
            else:
                time.sleep(ms / 1e3)

    def reset_counts(self) -> None:
        with self._lock:
            for k in self.counts:
                self.counts[k] = 0

    def install(self) -> "SimulatedRTT":
        from spark_scheduler_tpu.core import solver as _solver

        if self._installed:
            return self
        self._prior = _solver._DEVICE_SHIM
        _solver.set_device_shim(self)
        self._installed = True
        return self

    def uninstall(self) -> None:
        from spark_scheduler_tpu.core import solver as _solver

        if not self._installed:
            return
        _solver.set_device_shim(self._prior)
        self._prior = None
        self._installed = False

    def __enter__(self) -> "SimulatedRTT":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
