"""Randomized invariant-soak ENGINE (VERDICT r4 #4, SURVEY §4 property
tests): a seeded random sequence of driver/executor arrivals, executor
deaths, app teardowns, topology churn (node add/cordon/delete), forced
reconciles, write faults, and idempotent retries through PIPELINED
serving windows (dispatch-before-fetch, depth 2 — the PredicateBatcher's
loop shape), asserting global scheduling invariants as it goes:

  1. No node over-committed: hard+soft reservations + overhead <=
     allocatable, per node, at every checkpoint.
  2. Every admitted gang has exactly its reservation: driver slot + min
     executor slots, all on nodes that exist.
  3. Pipeline-drained device mirror == host truth: after completing every
     in-flight window, the availability mirror the device base embodies
     equals the host view (a lost or double-counted gang diverges it).
  4. Idempotent retries never double-book: resubmitting an admitted driver
     returns its reserved node and changes no reservation.

Lives in the package (not tests/) so both the CPU test matrix
(tests/test_invariant_soak.py) and the ON-SILICON soak the bench runs
(bench.py bench_tpu_soak — Pallas window path under churn) drive one
engine. Anchor: extendertest harness pattern
(/root/reference/internal/extender/extendertest/extender_test_utils.go:51-397).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.core.solver import PipelineDrainRequired
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    overcommit_violations,
    static_allocation_spark_pods,
)

CHECK_EVERY = 50  # full invariant sweep cadence (every step would be O(n^2))


class SoakClock:
    """Monotonic wall clock with a manual offset. Real elapsed time flows
    through (so demand-to-fulfilled latencies the bench reports are real),
    while elastic ops advance the offset to cross the drainer's idle TTL
    deterministically without sleeping."""

    def __init__(self):
        self._offset = 0.0

    def __call__(self) -> float:
        return time.monotonic() + self._offset

    def advance(self, dt: float) -> None:
        self._offset += dt


class Soak:
    def __init__(
        self, rng, strategy, n_nodes: int = 12, elastic: bool = False,
        backend=None, trace_path=None,
    ):
        self.rng = rng
        self.elastic = elastic
        self.clock = SoakClock() if elastic else None
        # Decision-trace capture (ISSUE 17): route the whole run through
        # the live TraceWriter wiring so CI can replay it bit-identically.
        trace_kw = {"trace_path": trace_path} if trace_path else {}
        # same_az under single-az strategies: without it the extender's
        # zone-restriction gate (is_single_az AND same-az-dynalloc config)
        # stays False and the zone-restricted executor-reschedule ladder —
        # the very path the single-az matrix slot exists to soak — never
        # executes (verified by instrumentation in review).
        elastic_kw = (
            dict(
                autoscaler_enabled=True,
                # Low enough that autoscaler_tick ops cross it; real drains
                # happen mid-soak and provisioned capacity recycles.
                autoscaler_idle_ttl_s=30.0,
                # Headroom for several bursts, low enough that a busy run
                # exercises the cannot-fulfill cap path too.
                autoscaler_max_cluster_size=n_nodes + 48,
                autoscaler_zones=["zone0", "zone1", "zone2"],
                clock=self.clock,
            )
            if elastic
            else {}
        )
        self.h = Harness(
            binpack_algo=strategy, fifo=True,
            same_az_dynamic_allocation="single-az" in strategy,
            # Injected backend (e.g. a DurableBackend so the chaos matrix
            # can fault the WAL surface); default in-memory.
            backend=backend,
            **trace_kw,
            **elastic_kw,
        )
        self.trace = self.h.app.trace_writer
        self.node_seq = 0
        self.nodes: dict[str, object] = {}
        for _ in range(n_nodes):
            self._add_node()
        self.app_seq = 0
        # app_id -> {"driver": Pod, "execs": [Pod], "node": str,
        #            "min": int, "bound": {pod_name: node}}
        self.admitted: dict[str, dict] = {}
        self.pending_tickets = []  # pipelined windows in flight (max 2)
        self.ext = self.h.extender
        self.steps = 0
        self.op_counts: dict[str, int] = {}

    # ---------------------------------------------------------------- ops

    def _add_node(self):
        name = f"sn{self.node_seq}"
        self.node_seq += 1
        node = new_node(name, zone=f"zone{self.node_seq % 3}")
        self.h.add_nodes(node)
        self.nodes[name] = node

    def node_names(self):
        if self.elastic:
            # Elastic topology is backend truth: autoscaled nodes join the
            # candidate set, drained ones leave it.
            return [n.name for n in self.h.backend.list_nodes()]
        return list(self.nodes)

    def _dispatch(self, args_list):
        """Dispatch a window, draining the pipeline on topology changes the
        way the serving loop does (PipelineDrainRequired contract)."""
        for _ in range(3):
            try:
                t = self.ext.predicate_window_dispatch(args_list)
                self.pending_tickets.append(t)
                return
            except PipelineDrainRequired:
                self.drain()
        raise AssertionError("dispatch kept raising PipelineDrainRequired")

    def _complete_oldest(self):
        t = self.pending_tickets.pop(0)
        results = self.ext.predicate_window_complete(t)
        for args, res in zip(t.args_list, results):
            pod = args.pod
            role = pod.labels.get("spark-role", "")
            app_id = pod.labels.get("spark-app-id", "")
            if not res.ok:
                continue
            node = res.node_names[0]
            if role == "driver":
                entry = self.admitted.get(app_id)
                if entry is None:
                    # tracked by the op that submitted it
                    continue
                entry["node"] = node
                if self.h.backend.get("pods", pod.namespace, pod.name) is not None:
                    self.h.backend.bind_pod(pod, node)
            elif role == "executor":
                entry = self.admitted.get(app_id)
                if entry is not None:
                    entry["bound"][pod.name] = node
                # The app may have been torn down while this window was in
                # flight (its pods deleted) — a dead pod can't bind.
                if self.h.backend.get("pods", pod.namespace, pod.name) is not None:
                    self.h.backend.bind_pod(pod, node)
        return results

    def drain(self):
        while self.pending_tickets:
            self._complete_oldest()

    def op_submit_drivers(self):
        if len(self.admitted) > 24:
            # Bound the pending-driver population: unbounded FIFO prefixes
            # grow every later request's hypothetical rows (and the row
            # buckets) without adding coverage.
            self.op_teardown_app()
            return
        k = int(self.rng.integers(1, 4))
        args = []
        for _ in range(k):
            app_id = f"app-{self.app_seq}"
            self.app_seq += 1
            execs = int(self.rng.integers(1, 5))
            if self.rng.random() < 0.3:
                pods = dynamic_allocation_spark_pods(
                    app_id, execs, execs + int(self.rng.integers(1, 3))
                )
            else:
                pods = static_allocation_spark_pods(app_id, execs)
            self.h.add_pods(pods[0])
            self.admitted[app_id] = {
                "driver": pods[0], "execs": pods[1:], "node": None,
                "min": execs, "bound": {},
            }
            args.append(
                ExtenderArgs(pod=pods[0], node_names=self.node_names())
            )
        self._dispatch(args)
        if len(self.pending_tickets) > 2 or self.rng.random() < 0.6:
            self._complete_oldest()

    def op_submit_executors(self):
        ready = [
            (a, e) for a, e in self.admitted.items() if e["node"] is not None
        ]
        if not ready:
            return
        args = []
        for _ in range(int(self.rng.integers(1, 5))):
            app_id, entry = ready[int(self.rng.integers(0, len(ready)))]
            unsubmitted = [
                p for p in entry["execs"] if p.name not in entry["bound"]
            ]
            if not unsubmitted:
                continue
            pod = unsubmitted[int(self.rng.integers(0, len(unsubmitted)))]
            self.h.add_pods(pod)
            names = self.node_names()
            if self.rng.random() < 0.2:  # restricted candidates: reschedule
                self.rng.shuffle(names)
                names = names[: max(3, len(names) // 2)]
            args.append(ExtenderArgs(pod=pod, node_names=names))
        if not args:
            return
        self._dispatch(args)
        self._complete_oldest()

    def op_kill_executor(self):
        apps = [e for e in self.admitted.values() if e["bound"]]
        if not apps:
            return
        entry = apps[int(self.rng.integers(0, len(apps)))]
        name = list(entry["bound"])[0]
        pod = next(p for p in entry["execs"] if p.name == name)
        cur = self.h.backend.get("pods", pod.namespace, pod.name)
        if cur is not None:
            self.h.terminate_pod(cur)
        del entry["bound"][name]

    def op_teardown_app(self):
        if not self.admitted:
            return
        app_id = list(self.admitted)[int(self.rng.integers(0, len(self.admitted)))]
        entry = self.admitted.pop(app_id)
        for p in [entry["driver"]] + entry["execs"]:
            cur = self.h.backend.get("pods", p.namespace, p.name)
            if cur is not None:
                self.h.backend.delete_pod(cur)
        rr = self.h.get_reservation("namespace", app_id)
        if rr is not None:
            self.h.app.rr_cache.delete(rr.namespace, rr.name)
            if self.trace is not None:
                # Operator-initiated RR deletion is an INPUT: the trace
                # writer's backend hooks only watch nodes/pods (scheduler-
                # originated RR writes are outputs), so journal it here.
                self.trace.emit_rr_delete(rr.namespace, rr.name)

    def op_node_churn(self):
        self.drain()  # topology changes force a drain in the serving loop
        r = self.rng.random()
        if r < 0.5 or len(self.nodes) < 8:
            self._add_node()
        elif r < 0.8:
            # cordon/uncordon with a REPLACEMENT object, like the real
            # watch path — an in-place mutation would defeat the solver's
            # identity-based arena sync and test nothing.
            import dataclasses as _dc

            name = list(self.nodes)[int(self.rng.integers(0, len(self.nodes)))]
            node = _dc.replace(
                self.nodes[name],
                unschedulable=not self.nodes[name].unschedulable,
            )
            self.nodes[name] = node
            self.h.backend.update("nodes", node)
        else:
            # delete a node with no reservations on it (hard OR soft)
            used = set()
            for rr in self.h.app.rr_cache.list():
                for res in rr.spec.reservations.values():
                    used.add(res.node)
            for _app_id, sr in self.h.app.soft_store.get_all_copy().items():
                for r in sr.reservations.values():
                    used.add(r.node)
            free = [n for n in self.nodes if n not in used]
            if free:
                name = free[int(self.rng.integers(0, len(free)))]
                self.h.backend.delete("nodes", "", name)
                del self.nodes[name]

    def op_reconcile(self):
        self.drain()
        if self.ext._reconciler is not None:
            self.ext._reconciler.sync_resource_reservations_and_demands()
            if self.trace is not None:
                self.trace.emit_reconcile()

    def op_write_fault(self):
        """One faulted reservation write: the request fails internal and
        nothing may double-book afterwards. Runs through the unified
        FaultInjector (ISSUE 9) — a one-shot error spec on the
        reservation-write surface, the exact schedule the ad-hoc lambda
        used to hand-roll."""
        from spark_scheduler_tpu.faults import FaultInjector, FaultPlan, FaultSpec

        plan = FaultPlan(
            seed=int(self.rng.integers(0, 2**31)),
            name="soak-write-fault",
            specs=[
                FaultSpec(
                    surface="backend.resourcereservations.*",
                    mode="error",
                    limit=1,
                    error=lambda: RuntimeError("soak-injected write fault"),
                )
            ],
        )
        with FaultInjector(plan) as inj:
            inj.install_backend(self.h.backend)
            self.op_submit_drivers()
            self.drain()
        # The faulted app (if any) got failure-internal; forget our intent
        # for apps that have no reservation so invariant #2 stays exact.
        for app_id in list(self.admitted):
            e = self.admitted[app_id]
            if e["node"] is None and self.h.get_reservation(
                "namespace", app_id
            ) is None:
                del self.admitted[app_id]

    def op_idempotent_retry(self):
        ready = [
            (a, e) for a, e in self.admitted.items() if e["node"] is not None
        ]
        if not ready:
            return
        app_id, entry = ready[int(self.rng.integers(0, len(ready)))]
        before = {
            k: (v.node)
            for k, v in self.h.get_reservation(
                "namespace", app_id
            ).spec.reservations.items()
        }
        res = self.ext.predicate(
            ExtenderArgs(pod=entry["driver"], node_names=self.node_names())
        )
        assert res.ok and res.node_names[0] == entry["node"], (
            "idempotent retry moved the driver",
            app_id, res, entry["node"],
        )
        after = {
            k: (v.node)
            for k, v in self.h.get_reservation(
                "namespace", app_id
            ).spec.reservations.items()
        }
        assert before == after, ("retry changed reservations", app_id)

    # ------------------------------------------------------- elastic ops

    def _assert_no_reserved_drained(self):
        """THE drain-safety invariant: after any autoscaler pass, every node
        a hard or soft reservation names must still exist."""
        known = {n.name for n in self.h.backend.list_nodes()}
        reserved = self.h.autoscaler.drainer.reserved_node_names()
        missing = reserved - known
        assert not missing, ("reserved node drained", missing, self.steps)

    def op_elastic_burst(self):
        """A gang too large for current free capacity: the failed admission
        creates a Demand, the autoscaler provisions nodes for it, and the
        retried driver should land on them. Each burst moves the node count
        across the solver's padding buckets (_bucket(capacity, 8)) under
        load — the recompile-boundary churn this soak mode exists for."""
        self.drain()
        execs = int(self.rng.integers(8, 17))
        app_id = f"burst-{self.app_seq}"
        self.app_seq += 1
        pods = static_allocation_spark_pods(app_id, execs)
        self.h.add_pods(pods[0])
        self.admitted[app_id] = {
            "driver": pods[0], "execs": pods[1:], "node": None,
            "min": execs, "bound": {},
        }
        for attempt in range(3):
            res = self.ext.predicate(
                ExtenderArgs(pod=pods[0], node_names=self.node_names())
            )
            if res.ok:
                self.admitted[app_id]["node"] = res.node_names[0]
                self.h.backend.bind_pod(pods[0], res.node_names[0])
                return
            # Demand emitted for the failed fit -> provision -> retry. The
            # retry may still fail (FIFO earlier drivers, or the cap) —
            # the global invariants cover both outcomes.
            self.h.autoscaler.run_once()
            self._assert_no_reserved_drained()

    def op_autoscaler_tick(self):
        """One autoscaler control-loop pass after a clock jump: sub-TTL
        jumps exercise idle tracking and cordons-in-progress, super-TTL
        jumps complete drains. Reserved nodes must survive every pass."""
        self.drain()  # topology may change: serving loop would drain too
        ttl = self.h.autoscaler.drainer.idle_ttl_s
        self.clock.advance(ttl * (0.6 if self.rng.random() < 0.5 else 1.1))
        self.h.autoscaler.run_once()
        self._assert_no_reserved_drained()

    # --------------------------------------------------------- invariants

    def check_invariants(self):
        # 1. no node over-committed (reservations + overhead <= allocatable)
        #    — the ONE shared definition (testing/harness.py).
        violations = overcommit_violations(self.h.app, self.h.backend)
        assert not violations, ("over-commit", violations, self.steps)
        # 2. every admitted gang has exactly its reservation
        for app_id, entry in self.admitted.items():
            if entry["node"] is None:
                continue
            rr = self.h.get_reservation("namespace", app_id)
            assert rr is not None, ("admitted app lost its RR", app_id)
            assert rr.spec.reservations["driver"].node == entry["node"], (
                "driver slot moved", app_id)
            exec_slots = [k for k in rr.spec.reservations if k != "driver"]
            assert len(exec_slots) == entry["min"], (
                "executor slot count", app_id)
        # 5. flight-recorder cross-check: recorded verdicts match actual
        #    placements (every checkpoint pass, observability contract).
        self.check_recorder()

    def check_recorder(self):
        """Recorded verdict == actual placement: the newest driver record
        of every admitted app is a success naming the reserved node, and
        every denied record carries its per-node failure-reason map. The
        soak is the one place windowed, solo, retried, and faulted
        admissions all flow through the recorder under churn."""
        rec = self.h.app.recorder
        if rec is None:
            return
        for app_id, entry in self.admitted.items():
            if entry["node"] is None:
                continue
            r = rec.latest_for_app("namespace", app_id, role="driver")
            if r is None:
                # The ring is bounded: a very long soak can evict an early
                # admission's record while the app stays admitted. Only a
                # missing record with ZERO evictions is a real failure —
                # once the ring has dropped records, absence is expected.
                assert rec.stats()["dropped"] > 0, (
                    "admitted app has no decision record",
                    app_id, self.steps,
                )
                continue
            assert r.verdict == "success" and r.node == entry["node"], (
                "recorded verdict diverges from placement",
                app_id, r.verdict, r.node, entry["node"], self.steps,
            )
        for d in rec.query(verdict="failure-*", limit=25):
            assert d["node"] is None and d["failed_nodes"], (
                "denied record lacks its failure map", d, self.steps)

    def check_drained_mirror(self):
        """Invariant 3: with the pipeline drained, the device-embodied
        availability mirror equals the host truth."""
        self.drain()
        solver = self.h.app.solver
        if solver._pipe is None:
            return
        backend = self.h.backend
        all_nodes = backend.list_nodes()
        usage = self.h.app.reservation_manager.reserved_usage()
        overhead = self.h.app.overhead_computer.get_overhead(all_nodes)
        tensors = solver.build_tensors_pipelined(
            all_nodes, usage, overhead,
            topo_version=getattr(backend, "nodes_version", None),
        )
        host = getattr(tensors, "host", tensors)
        mirror = solver._pipe["mirror"]
        assert np.array_equal(
            np.asarray(host.available, dtype=np.int64), mirror
        ), ("drained mirror diverges from host truth", self.steps)

    # -------------------------------------------------------------- drive

    OPS = (
        ("submit_drivers", 30, op_submit_drivers),
        ("submit_executors", 30, op_submit_executors),
        ("kill_executor", 10, op_kill_executor),
        ("teardown_app", 8, op_teardown_app),
        ("node_churn", 6, op_node_churn),
        ("reconcile", 4, op_reconcile),
        ("write_fault", 4, op_write_fault),
        ("idempotent_retry", 8, op_idempotent_retry),
    )
    ELASTIC_OPS = (
        ("elastic_burst", 8, op_elastic_burst),
        ("autoscaler_tick", 10, op_autoscaler_tick),
    )

    def run(self, steps):
        ops = self.OPS + (self.ELASTIC_OPS if self.elastic else ())
        if self.trace is not None:
            # Injected faults are not part of the replayable input surface
            # (replay has no FaultInjector schedule), so a recorded soak
            # drives every op EXCEPT write faults.
            ops = tuple(o for o in ops if o[0] != "write_fault")
        names = [name for name, w, _ in ops for _ in range(w)]
        fns = {name: fn for name, _, fn in ops}
        while self.steps < steps:
            self.steps += 1
            name = names[int(self.rng.integers(0, len(names)))]
            self.op_counts[name] = self.op_counts.get(name, 0) + 1
            fns[name](self)
            if self.steps % CHECK_EVERY == 0:
                self.drain()
                self.check_invariants()
            if self.steps % (CHECK_EVERY * 4) == 0:
                self.check_drained_mirror()
        self.drain()
        self.check_invariants()
        self.check_drained_mirror()


# ------------------------------------------------------------ chaos matrix


class ChaosMatrixSoak:
    """ISSUE 9 chaos matrix: the randomized Soak workload run under ONE
    seeded FaultPlan per surface family — {backend, kube, wal, device,
    lease} — through the unified FaultInjector. Per run it asserts the
    engine's scheduling invariants (zero double placements, zero
    reservation over-commits), that faulted work was RETRIED or FENCED
    rather than silently dropped (write-back `dropped == 0`; the WAL leg
    additionally replays the log into a fresh backend and requires it to
    equal live reservation truth), and that per-step latency stays under
    `step_budget_s` (bounded spikes, not stalls). The verdict dict holds
    only DETERMINISTIC fields — tests/test_chaos_matrix.py pins that the
    same seed yields the same fault schedule and the same verdict.

    Surface families:
      backend  reservation/demand mutations error under the apiserver's
               lock (the write-back retry ladder absorbs them)
      kube     the async write-back client's drained requests error
               (p-faults AND a contiguous partition window shorter than
               the retry budget)
      wal      DurableBackend appends/fsyncs fail; parked records must
               reach the log anyway (durable._wal_pending)
      device   a device h2d dies mid-soak; the window is served by the
               degraded greedy fallback and the device path recovers
      lease    a LeaseManager's store blips under the soak; the retry
               ladder must absorb the faults without a spurious deposition
    """

    SURFACES = ("backend", "kube", "wal", "device", "lease")

    @staticmethod
    def plan_for(surface: str, seed: int):
        """The shipped chaos-matrix plan for one surface family. Bounded
        (`limit`) so every plan also tests RECOVERY: the workload must
        return to steady state after the last scheduled fault."""
        from spark_scheduler_tpu.faults import FaultPlan, FaultSpec

        specs = {
            "backend": [
                FaultSpec(surface="backend.resourcereservations.*",
                          mode="error", p=0.15, limit=10),
                FaultSpec(surface="backend.demands.*",
                          mode="error", p=0.2, limit=6),
            ],
            "kube": [
                FaultSpec(surface="kube.write.*", mode="error",
                          p=0.1, limit=8),
                # A dead-apiserver window: 3 consecutive drained writes
                # fail — shorter than the retry budget, so every one is
                # absorbed by requeues, never dropped.
                FaultSpec(surface="kube.write.*", mode="partition",
                          start=20, length=3, limit=3),
            ],
            "wal": [
                # Reservation/demand appends only: the soak's DIRECT pod
                # and node fixture writes are scaffolding with no retry
                # ladder in front of them — the serving paths are what
                # the leg probes.
                FaultSpec(surface="wal.append.resourcereservations",
                          mode="error", every=7, limit=5),
                FaultSpec(surface="wal.append.demands",
                          mode="error", p=0.3, limit=3),
                FaultSpec(surface="wal.fsync.resourcereservations",
                          mode="error", at=[3], limit=1),
            ],
            "device": [
                # The 3rd h2d dies (tunnel drop mid-soak): that window is
                # served by the host greedy fallback; the next dispatch
                # recovers the device path.
                FaultSpec(surface="device.h2d", mode="error",
                          at=[2], limit=1),
            ],
            "lease": [
                FaultSpec(surface="lease.read", mode="error",
                          p=0.2, limit=8),
                FaultSpec(surface="lease.write", mode="error",
                          p=0.2, limit=6),
            ],
        }[surface]
        return FaultPlan(seed=seed, name=f"matrix-{surface}", specs=specs)

    def __init__(
        self,
        surface: str,
        seed: int = 0,
        strategy: str = "tightly-pack",
        n_nodes: int = 12,
        wal_path: str | None = None,
        step_budget_s: float = 60.0,
        plan=None,
    ):
        import numpy as _np

        from spark_scheduler_tpu.faults import FaultInjector

        assert surface in self.SURFACES, surface
        self.surface = surface
        self.seed = seed
        self.plan = plan if plan is not None else self.plan_for(surface, seed)
        self.injector = FaultInjector(self.plan)
        self.step_budget_s = step_budget_s
        self.wal_path = wal_path
        backend = None
        if surface == "wal":
            assert wal_path, "the wal leg needs a log path"
            from spark_scheduler_tpu.store.durable import DurableBackend

            backend = DurableBackend(wal_path)
        self.soak = Soak(
            _np.random.default_rng(seed), strategy, n_nodes=n_nodes,
            backend=backend,
        )
        self.step_times: list[float] = []
        self.lease_mgr = None
        self.lease_io_errors = 0
        self.lease_renews_ok = 0

    # -- per-surface wiring -------------------------------------------------

    def _install(self) -> None:
        inj, h = self.injector, self.soak.h
        if self.surface == "backend":
            inj.install_backend(h.backend)
        elif self.surface == "kube":
            inj.install_async_client(h.app.rr_cache.client)
        elif self.surface == "wal":
            inj.install_wal(h.backend)
        elif self.surface == "device":
            inj.install_device()
        elif self.surface == "lease":
            from spark_scheduler_tpu.ha.lease import (
                BackendLeaseStore,
                LeaseManager,
            )

            self.lease_mgr = LeaseManager(
                inj.lease_store(BackendLeaseStore(h.backend)),
                "matrix-holder",
                ttl_s=3600.0,  # nothing may depose it but a real failure
            )
            assert self.lease_mgr.try_acquire()

    def _lease_tick(self) -> None:
        try:
            if self.lease_mgr.renew():
                self.lease_renews_ok += 1
        except Exception:
            # Retry-exhausted store IO. The lease itself is NOT lost — the
            # epoch is only moved by a successful takeover.
            self.lease_io_errors += 1

    # -- drive --------------------------------------------------------------

    def run(self, steps: int) -> dict:
        s = self.soak
        names = [name for name, w, _ in s.OPS for _ in range(w)]
        fns = {name: fn for name, _, fn in s.OPS}
        with self.injector:
            self._install()
            while s.steps < steps:
                s.steps += 1
                name = names[int(s.rng.integers(0, len(names)))]
                s.op_counts[name] = s.op_counts.get(name, 0) + 1
                t0 = time.perf_counter()
                fns[name](s)
                if self.lease_mgr is not None:
                    self._lease_tick()
                self.step_times.append(time.perf_counter() - t0)
                if s.steps % CHECK_EVERY == 0:
                    s.drain()
                    s.check_invariants()
            s.drain()
            s.check_invariants()
            s.check_drained_mirror()
        return self._verdict(steps)

    # -- verdict ------------------------------------------------------------

    def _verdict(self, steps: int) -> dict:
        s = self.soak
        client = s.h.app.rr_cache.client
        # Never silently dropped: every faulted write-back was absorbed by
        # its bounded requeue (the plans stay under the retry budget by
        # construction — a plan that can exhaust it must pair with an
        # on_error consumer, not silence).
        assert client.metrics.dropped == 0, (
            "chaos matrix dropped write-back work",
            self.surface, client.metrics.dropped,
        )
        # Bounded spikes: no single step may stall the serving loop.
        worst = max(self.step_times) if self.step_times else 0.0
        assert worst < self.step_budget_s, (
            "chaos-matrix step exceeded the latency budget",
            self.surface, worst, self.step_budget_s,
        )
        verdict = {
            "surface": self.surface,
            "seed": self.seed,
            "plan": self.plan.name,
            "steps": steps,
            "op_counts": dict(s.op_counts),
            "apps": s.app_seq,
            "fired": dict(self.injector.fired),
            "schedule": self.injector.schedule(),
            "write_back": {
                "retries": client.metrics.retries,
                "dropped": client.metrics.dropped,
            },
        }
        if self.surface == "device":
            solver = s.h.app.solver
            deg = solver.degraded
            snap = deg.snapshot() if deg is not None else {}
            # The faulted window was served (fallback), and the device
            # path recovered once the plan's faults exhausted.
            assert snap.get("fallback_decisions", 0) > 0, snap
            assert not (deg is not None and deg.active), (
                "device path never recovered", snap
            )
            verdict["device"] = {
                "fallback_decisions": snap.get("fallback_decisions"),
                "engagements": snap.get("engagements"),
            }
        if self.surface == "wal":
            verdict["wal"] = self._check_wal_durability()
        if self.surface == "lease":
            mgr = self.lease_mgr
            # Transient store blips never depose a healthy holder: the
            # epoch this manager acquired is still the live record's.
            assert mgr.acquired_epoch == 1, mgr.state()
            assert self.lease_renews_ok > 0
            verdict["lease"] = {
                "renews_ok": self.lease_renews_ok,
                "io_errors": self.lease_io_errors,
            }
        return verdict

    def _check_wal_durability(self) -> dict:
        """Append-faulted records must still reach the log: flush parked
        records, replay the log into a FRESH backend, and require its
        reservation truth to equal the live backend's."""
        from spark_scheduler_tpu.store.durable import DurableBackend

        live = self.soak.h.backend
        flushed = live.wal_flush()
        assert not live._wal_pending
        replayed = DurableBackend(self.wal_path, compact_on_load=False)
        def rr_truth(b):
            return {
                (rr.namespace, rr.name): {
                    k: v.node for k, v in rr.spec.reservations.items()
                }
                for rr in b.list("resourcereservations")
            }
        assert rr_truth(replayed) == rr_truth(live), (
            "WAL replay diverges from live truth after append faults"
        )
        replayed.close()
        return {
            "append_failures": live.wal_append_failures,
            "flushed_at_end": flushed,
        }


# ---------------------------------------------------------------- HA chaos


class HAChaosSoak:
    """Leader-kill chaos engine (ISSUE 8): N replicas (ha/replica.py) over
    ONE shared backend; driver bursts hit the current leader; mid-burst
    the leader is KILLED with a window in flight; after the lease TTL a
    warm standby promotes (reconcile-before-serve) and the burst
    continues; the dead leader's in-flight commit is then completed and
    must be FENCED (epoch moved at takeover) instead of double-placing.

    Asserted per cycle:
      - zero double placements: every admitted app has exactly ONE
        reservation whose driver slot names the node the SURVIVING
        leader answered (the dead leader's conflicting commit was
        rejected at the durability layer);
      - zero reservation-invariant violations (the shared
        overcommit_violations definition);
      - bounded placement-latency spike: the first post-failover decision
        completes within `spike_budget_s` wall seconds of the kill
        (promotion + retry, the TTL itself is crossed on the virtual
        clock).

    Driven fast by tests/test_ha_chaos_soak.py and on real clusters by
    bench.py's ha_failover section.

    The kill itself rides the unified FaultInjector (ISSUE 9): the
    `replica.kill` surface is fired once per cycle and the PLAN decides
    whether the leader dies — the default plan kills every cycle (the
    original hardcoded behavior); a seeded plan with `p`/`at` makes the
    kill schedule stochastic-but-replayable, and cycles the plan spares
    run the same staged windows to completion on the live leader (steady
    control arm). Plans carrying `lease.*` specs additionally wrap every
    replica's lease store in FaultyLeaseStore, so store blips ride the
    takeover itself.
    """

    def __init__(
        self,
        strategy: str = "tightly-pack",
        n_nodes: int = 16,
        ttl_s: float = 3.0,
        spike_budget_s: float = 30.0,
        backend=None,
        max_live_apps: int = 18,
        fault_plan=None,
    ):
        from spark_scheduler_tpu.faults import FaultInjector, FaultPlan, FaultSpec
        from spark_scheduler_tpu.ha.replica import build_replica
        from spark_scheduler_tpu.server.config import InstallConfig
        from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend
        from spark_scheduler_tpu.testing.harness import (
            INSTANCE_GROUP_LABEL,
            new_node,
        )

        if fault_plan is None:
            # The legacy contract: every cycle kills its leader.
            fault_plan = FaultPlan(
                seed=0, name="ha-kill-every-cycle",
                specs=[FaultSpec(surface="replica.kill", mode="error")],
            )
        self.injector = FaultInjector(fault_plan)
        self._fault_leases = any(
            s.surface.startswith("lease") for s in fault_plan.specs
        )
        self.kills = 0
        self.spared_cycles = 0
        self.backend = backend if backend is not None else InMemoryBackend()
        self.backend.register_crd(DEMAND_CRD)
        self.clock = SoakClock()
        self.ttl_s = ttl_s
        self.spike_budget_s = spike_budget_s
        self._config = lambda: InstallConfig(
            fifo=True,
            binpack_algo=strategy,
            instance_group_label=INSTANCE_GROUP_LABEL,
            sync_writes=True,
            ha_enabled=True,
            ha_lease_ttl_s=ttl_s,
        )
        def _build(rid):
            r = build_replica(
                self.backend, rid, config=self._config(), clock=self.clock
            )
            if self._fault_leases and r.lease is not None:
                r.lease._store = self.injector.lease_store(r.lease._store)
            return r

        self._build = _build
        for i in range(n_nodes):
            self.backend.add_node(new_node(f"hn{i}", zone=f"zone{i % 3}"))
        self.node_names = [f"hn{i}" for i in range(n_nodes)]
        self._replica_seq = 2
        self.replicas = [self._build("replica-0"), self._build("replica-1")]
        assert self.replicas[0].lease.try_acquire()
        self.replicas[0].promote()
        self.app_seq = 0
        # app_id -> node the SURVIVING leader answered (live apps only —
        # completed apps retire so an arbitrary-cycle soak runs at bounded
        # state instead of exhausting the fixed fleet)
        self.placed: dict[str, str] = {}
        self.max_live_apps = max_live_apps
        self.total_placed = 0
        self.retired = 0
        self.driver_pods: dict[str, object] = {}
        self.steady_latencies: list[float] = []
        self.failover_spikes: list[float] = []
        self.fenced_drops = 0
        self.promotions = 0

    # -- plumbing ----------------------------------------------------------

    @property
    def leader(self):
        for r in self.replicas:
            if r.is_serving():
                return r
        raise AssertionError("no serving replica")

    @property
    def standby(self):
        for r in self.replicas:
            if not r._dead and not r.is_serving():
                return r
        raise AssertionError("no standby replica")

    def _new_app(self, execs: int = 2):
        from spark_scheduler_tpu.testing.harness import (
            static_allocation_spark_pods,
        )

        app_id = f"chaos-{self.app_seq}"
        self.app_seq += 1
        pods = static_allocation_spark_pods(app_id, execs)
        self.backend.add_pod(pods[0])
        self.driver_pods[app_id] = pods[0]
        return app_id, pods[0]

    def _serve_driver(self, runtime, pod, record=None) -> str:
        from spark_scheduler_tpu.core.extender import ExtenderArgs

        t0 = time.perf_counter()
        res = runtime.app.extender.predicate(
            ExtenderArgs(pod=pod, node_names=list(self.node_names))
        )
        if record is not None:
            record.append(time.perf_counter() - t0)
        assert res.ok, (pod.name, res.outcome, res.failed_nodes and next(iter(res.failed_nodes.values())))
        node = res.node_names[0]
        self.backend.bind_pod(pod, node)
        return node

    # -- one chaos cycle ---------------------------------------------------

    def run_cycle(self, burst: int = 4, inflight: int = 2) -> None:
        from spark_scheduler_tpu.core.extender import ExtenderArgs

        leader = self.leader
        # Steady phase: admit a burst on the live leader.
        for _ in range(burst):
            app_id, driver = self._new_app()
            self.placed[app_id] = self._serve_driver(
                leader, driver, self.steady_latencies
            )
            self.total_placed += 1
        # Stage the kill: dispatch (but do not complete) a window of fresh
        # gangs on the soon-dead leader — the async fire-and-forget commit
        # the fencing epoch exists for. Half are RETRIED by their client on
        # the new leader (the tailer makes the dead commit an idempotent
        # no-op); the rest are ORPHANS only the dead leader ever saw —
        # their commit is a brand-new reservation write and MUST be fenced
        # at the durability layer.
        staged = [self._new_app() for _ in range(inflight)]
        orphans = [self._new_app() for _ in range(max(1, inflight // 2))]
        ticket = leader.app.extender.predicate_window_dispatch(
            [
                ExtenderArgs(pod=p, node_names=list(self.node_names))
                for _aid, p in staged + orphans
            ]
        )
        # The kill decision is the fault plan's (replica.kill surface):
        # an InjectedFault IS the crash; a spared cycle completes the
        # same staged window on the live leader (steady control arm).
        from spark_scheduler_tpu.faults import InjectedFault

        try:
            self.injector.fire("replica.kill")
            kill = False
        except InjectedFault:
            kill = True
        if not kill:
            self.spared_cycles += 1
            results = leader.app.extender.predicate_window_complete(ticket)
            for (app_id, driver), res in zip(staged + orphans, results):
                assert res.ok, (app_id, res.outcome)
                node = res.node_names[0]
                self.backend.bind_pod(driver, node)
                self.placed[app_id] = node
                self.total_placed += 1
            self._retire_oldest()
            self.check_invariants()
            return
        self.kills += 1
        kill_t0 = time.perf_counter()
        leader.kill()
        drops_before = leader.app.rr_cache.client.metrics.dropped
        # The lease must EXPIRE (no clean release on a crash).
        self.clock.advance(self.ttl_s * 1.5)
        survivor = self.standby
        assert survivor.run_election_once() == "leader", survivor.state()
        self.promotions += 1
        # Clients retry the in-flight gangs against the new leader; the
        # first retried decision's wall time since the kill is the spike.
        for i, (app_id, driver) in enumerate(staged):
            node = self._serve_driver(survivor, driver)
            self.placed[app_id] = node
            self.total_placed += 1
            if i == 0:
                self.failover_spikes.append(time.perf_counter() - kill_t0)
        # The dead leader's window now lands. Retried apps: the tailer
        # already delivered the new leader's reservation, so the commit is
        # an idempotent no-op. Orphans: a fresh reservation write carrying
        # the stale epoch — rejected by the fence, counted dropped.
        try:
            leader.app.extender.predicate_window_complete(ticket)
        except Exception:
            pass  # a fenced demand/reservation write surfacing is fine
        drops = leader.app.rr_cache.client.metrics.dropped - drops_before
        self.fenced_drops += drops
        assert leader.lease.fenced_rejects > 0 and drops >= len(orphans), (
            "the dead leader's orphan commit was never fenced",
            leader.lease.fenced_rejects, drops,
        )
        for app_id, driver in orphans:
            assert (
                self.backend.get(
                    "resourcereservations", driver.namespace, app_id
                )
                is None
            ), ("fenced orphan reservation reached the durable store", app_id)
            # The orphan's client went away with its leader: remove the
            # pending driver pod so FIFO doesn't track a ghost forever.
            self.backend.delete_pod(driver)
            del self.driver_pods[app_id]
        # Fresh standby replaces the corpse (built AFTER the new state
        # exists: its caches fill warm, the tailer keeps them warm).
        self.replicas = [r for r in self.replicas if not r._dead]
        self.replicas.append(self._build(f"replica-{self._replica_seq}"))
        self._replica_seq += 1
        self._retire_oldest()
        self.check_invariants()

    def _retire_oldest(self) -> None:
        """Completed apps leave the cluster: delete the driver pod and its
        reservation through the NEW leader's fenced write path (tailers
        propagate the deletes to every replica's cache and usage tracker),
        so an arbitrary-cycle soak recycles capacity instead of hitting
        legitimate does-not-fit on the fixed fleet — which would starve the
        orphan-fencing assertion of its reservation write."""
        leader = self.leader
        while len(self.placed) > self.max_live_apps:
            app_id = next(iter(self.placed))
            driver = self.driver_pods.pop(app_id)
            # Pod first: a bound driver with no reservation is exactly what
            # reconcile calls stale and would re-place.
            self.backend.delete_pod(driver)
            leader.app.rr_cache.delete(driver.namespace, app_id)
            del self.placed[app_id]
            self.retired += 1

    # -- invariants --------------------------------------------------------

    def check_invariants(self) -> None:
        from spark_scheduler_tpu.testing.harness import overcommit_violations

        leader = self.leader
        # Reservation invariant over DURABLE truth.
        violations = overcommit_violations(leader.app, self.backend)
        assert not violations, ("over-commit", violations)
        # Zero double placements: one RR per admitted app, driver slot on
        # the surviving answer's node.
        rrs = {rr.name: rr for rr in self.backend.list("resourcereservations")}
        for app_id, node in self.placed.items():
            rr = rrs.get(app_id)
            assert rr is not None, ("admitted app lost its reservation", app_id)
            assert rr.spec.reservations["driver"].node == node, (
                "double placement: durable driver slot diverges from the "
                "surviving leader's answer",
                app_id, rr.spec.reservations["driver"].node, node,
            )
        # Latency spike bounded.
        for spike in self.failover_spikes:
            assert spike < self.spike_budget_s, (
                "failover spike exceeds budget", spike, self.spike_budget_s
            )

    def run(self, cycles: int = 3, burst: int = 4) -> dict:
        for _ in range(cycles):
            self.run_cycle(burst=burst)
        mid = sorted(self.steady_latencies)
        return {
            "cycles": cycles,
            "kills": self.kills,
            "spared_cycles": self.spared_cycles,
            "fault_stats": self.injector.stats(),
            "apps_placed": self.total_placed,
            "live_apps": len(self.placed),
            "retired": self.retired,
            "steady_p50_ms": round(mid[len(mid) // 2] * 1e3, 3) if mid else None,
            "failover_spike_ms": [
                round(s * 1e3, 1) for s in self.failover_spikes
            ],
            "fenced_drops": self.fenced_drops,
            "promotions": self.promotions,
        }


class PolicySoak:
    """Priority/preemption soak (ISSUE 16 satellite): sustained
    high-priority pressure against a fixed set of low-priority gangs plus
    one protected "system" gang, through the REAL policy-enabled extender
    (priority ordering + vectorized preemption search + age promotion).

    Deterministic manual clock: pods are stamped with the soak clock so
    age promotion is driven by `advance()`, not wall time. Each step:

      submit 1 fresh high-priority gang (evicts low gangs while they are
      young; denied once they age into the promotion cap), retire the
      oldest high gang past a small working-set bound (so capacity keeps
      turning over), retry every pending/evicted low gang, advance the
      clock one `step_s`.

    Invariants collected for the test layer (`verdict()`):
      * no starvation — every low gang holds a reservation at the end,
        and every admission happened within `starvation_bound_s` of its
        original submission (the age-promotion bound: once promoted to
        the cap a low gang is neither blocked behind fresh high gangs
        nor evictable by them);
      * the system gang's hard reservation survives every step;
      * zero over-commit at every step.
    """

    def __init__(
        self,
        n_low: int = 3,
        n_nodes: int = 3,
        promote_after_s: float = 120.0,
        step_s: float = 30.0,
    ):
        class _Clock:
            def __init__(self):
                self.t = 1_000.0

            def __call__(self):
                return self.t

            def advance(self, dt):
                self.t += dt

        self.clock = _Clock()
        self.promote_after_s = promote_after_s
        self.step_s = step_s
        self.h = Harness(
            binpack_algo="tightly-pack",
            fifo=True,
            clock=self.clock,
            policy_enabled=True,
            policy_ordering="priority",
            policy_preemption=True,
            policy_promote_after_s=promote_after_s,
            # The manual clock jumps step_s per step — without this every
            # request would cross the leader-gap heuristic and run a full
            # failover reconcile mid-soak (resurrecting evicted gangs
            # from their leftover pending pods).
            resync_gap_seconds=1e12,
        )
        for i in range(n_nodes):
            self.h.add_nodes(new_node(f"pn{i}", zone=f"zone{i % 3}"))
        self.names = [f"pn{i}" for i in range(n_nodes)]
        self.seq = 0
        self.highs: list[tuple[str, list]] = []  # (app_id, pods) admitted
        self.evictions = 0
        self.denied_high = 0
        self.system_rr_lost = False
        self.overcommit: list = []
        # app_id -> {"pods", "submitted", "admitted"(clock time or None)}
        self.lows: dict[str, dict] = {}

        from spark_scheduler_tpu.models.reservations import (
            PRIORITY_CLASS_ANNOTATION,
        )

        self._ann = PRIORITY_CLASS_ANNOTATION

        # One protected gang: its reservation must survive the whole soak.
        sys_pods = self._gang("system-app", 2, "system")
        assert self._admit_gang(sys_pods), "system gang must admit first"

        for i in range(n_low):
            app_id = f"low-{i}"
            pods = self._gang(app_id, 2, "low")
            self.lows[app_id] = {
                "pods": pods,
                "submitted": self.clock(),
                "admitted": None,
            }

    def _gang(self, app_id: str, execs: int, pclass: str):
        pods = static_allocation_spark_pods(app_id, execs)
        pods[0].annotations[self._ann] = pclass
        for p in pods:  # stamp with the SOAK clock, not the global counter
            p.creation_timestamp = self.clock()
        return pods

    def _admit_gang(self, pods) -> bool:
        r = self.h.schedule(pods[0], self.names)
        if not r.ok:
            return False
        for p in pods[1:]:
            self.h.schedule(p, self.names)
        return True

    def _teardown(self, app_id: str, pods) -> None:
        for p in pods:
            cur = self.h.backend.get("pods", p.namespace, p.name)
            if cur is not None:
                self.h.backend.delete_pod(cur)
        rr = self.h.get_reservation("namespace", app_id)
        if rr is not None:
            self.h.app.rr_cache.delete(rr.namespace, rr.name)

    def step(self) -> None:
        # Sustained pressure: one fresh high gang per step.
        app_id = f"high-{self.seq}"
        self.seq += 1
        pods = self._gang(app_id, 2, "high")
        if self._admit_gang(pods):
            self.highs.append((app_id, pods))
        else:
            self.denied_high += 1
        if len(self.highs) > 4:
            old_id, old_pods = self.highs.pop(0)
            self._teardown(old_id, old_pods)

        # Low gangs retry every step (the kube retry loop). Resubmission
        # uses FRESH pod objects carrying the ORIGINAL creation stamp:
        # binding mutates the stored pod's node_name in place, so reusing
        # the old objects would re-add pods that look already-bound (a
        # phantom the availability mirror would count as usage) — while a
        # fresh stamp would reset the gang's promotion clock.
        import dataclasses as _dc

        for low_id, entry in self.lows.items():
            rr = self.h.get_reservation("namespace", low_id)
            if rr is not None:
                continue
            if entry["admitted"] is not None:
                self.evictions += 1
                entry["admitted"] = None
            entry["pods"] = [
                _dc.replace(p, node_name=None, phase="Pending")
                for p in entry["pods"]
            ]
            if self._admit_gang(entry["pods"]):
                entry["admitted"] = self.clock()

        if self.h.get_reservation("namespace", "system-app") is None:
            self.system_rr_lost = True
        self.overcommit.extend(overcommit_violations(self.h.app, self.h.backend))
        self.clock.advance(self.step_s)

    def run(self, steps: int) -> dict:
        for _ in range(steps):
            self.step()
        return self.verdict()

    def verdict(self) -> dict:
        waits = {}
        for low_id, entry in self.lows.items():
            waits[low_id] = (
                entry["admitted"] - entry["submitted"]
                if entry["admitted"] is not None
                else None
            )
        return {
            "steps": self.seq,
            "low_waits_s": waits,
            "evictions": self.evictions,
            "denied_high": self.denied_high,
            "system_rr_lost": self.system_rr_lost,
            "overcommit": self.overcommit,
            "preemptions": [
                rec["preemption"]
                for rec in self.h.app.recorder.query(limit=10_000)
                if rec.get("preemption")
            ],
        }


class FleetSoak:
    """Fleet chaos soak (ISSUE 19): randomized gang traffic across F
    per-cluster stacks behind one FleetFacade, with cluster kill/rejoin
    chaos riding StableMembership. Groups are multi-homed (each instance
    group hosted by two clusters) so routing has real choices and denied
    drivers have a live spillover sibling.

    Each step: submit a fresh gang on a random group, retry a few pending
    (denied) gangs, occasionally tear one placed app down. At `kill_at`
    one cluster is removed from serving (its pending gangs become orphans
    and MUST re-route to survivors); at `rejoin_at` it returns.

    Invariants (verdict()):
      * zero double placements — every app's reservation exists in at
        most ONE cluster's backend at every checkpoint;
      * zero over-commits — per-cluster overcommit_violations() empty at
        every checkpoint;
      * orphaned gangs re-routed — every pre-kill PENDING gang bound to
        the dead cluster ends up placed on (or routed to) a survivor;
      * aggregates == walk-oracle per cluster at every checkpoint;
      * per-cluster decisions byte-identical to a standalone replay of
        the cluster's op stream (checked once at the end — the oplog
        covers the entire soak).

    STACKING MODE (`stack_window_ms` > 0, ISSUE 20): the facade runs the
    FleetDispatchCoordinator, and each step's fresh gangs are submitted
    CONCURRENTLY — one per group from its own thread — so per-cluster
    windows actually meet inside the gather and flush as stacked
    launches. The kill lands while a concurrent burst is in flight
    (kill-mid-gather: the victim's parked window must resolve via the
    forced fallback and the survivors' stack must flush clean), and
    every invariant above — byte-identity included — holds unchanged.
    """

    def __init__(
        self,
        n_clusters: int = 3,
        nodes_per_cluster: int = 2,
        seed: int = 0,
        max_spillover_hops: int = 1,
        stack_window_ms: float = 0.0,
    ):
        from spark_scheduler_tpu.fleet import FleetFacade
        from spark_scheduler_tpu.server.config import InstallConfig
        from spark_scheduler_tpu.testing.harness import (
            INSTANCE_GROUP_LABEL,
        )

        self.rng = np.random.default_rng(seed)
        self.F = n_clusters
        self.stack_window_ms = stack_window_ms
        self._traffic_lock = threading.Lock()
        cfg = InstallConfig(
            fifo=True,
            sync_writes=True,
            instance_group_label=INSTANCE_GROUP_LABEL,
        )
        self.facade = FleetFacade(
            n_clusters,
            cfg,
            record_ops=True,
            max_spillover_hops=max_spillover_hops,
            stack_window_ms=stack_window_ms,
        )
        # Group g is hosted by clusters g and (g+1) % F — multi-homed.
        self.groups = [f"ig-{g}" for g in range(n_clusters)]
        for g in range(n_clusters):
            for c in (g, (g + 1) % n_clusters):
                for i in range(nodes_per_cluster):
                    self.facade.add_node(
                        c, new_node(f"c{c}-g{g}-n{i}", instance_group=f"ig-{g}")
                    )
        self.seq = 0
        self.placed: dict[str, dict] = {}   # app_id -> {pods, cluster}
        self.pending: dict[str, dict] = {}  # app_id -> {pods, group}
        self.dead: int | None = None
        self.double_placements: list = []
        self.overcommit: list = []
        self.oracle_mismatches: list = []
        self.orphans_at_kill: set[str] = set()
        self.orphans_rerouted = 0
        self.unavailable_denials = 0
        self.steps_run = 0

    # -- traffic -------------------------------------------------------------

    def _submit(self, app_id: str, group: str) -> None:
        pods = static_allocation_spark_pods(
            app_id, int(self.rng.integers(1, 4)), instance_group=group
        )
        self._try_place(app_id, group, pods)

    def _try_place(self, app_id: str, group: str, pods) -> None:
        # schedule() runs OUTSIDE the traffic lock so concurrent burst
        # threads (stacking mode) can meet inside the gather window;
        # only the soak's own bookkeeping is lock-guarded.
        d = self.facade.schedule(pods[0])
        if d.unavailable:
            with self._traffic_lock:
                self.unavailable_denials += 1
                self.pending[app_id] = {"pods": pods, "group": group}
            return
        if not d.ok:
            with self._traffic_lock:
                self.pending[app_id] = {"pods": pods, "group": group}
            return
        for p in pods[1:]:
            self.facade.schedule(p)
        with self._traffic_lock:
            self.pending.pop(app_id, None)
            self.placed[app_id] = {"pods": pods, "cluster": d.cluster}
            if app_id in self.orphans_at_kill:
                self.orphans_rerouted += 1

    def _start_burst(self) -> list[threading.Thread]:
        """Stacking mode: one fresh gang per group, each submitted from
        its own thread so per-cluster windows can stack. Pods and RNG
        draws happen on the caller's thread to keep the soak
        deterministic; only the facade calls run concurrently."""
        jobs = []
        for group in self.groups:
            self.seq += 1
            app_id = f"fleet-soak-{self.seq}"
            pods = static_allocation_spark_pods(
                app_id, int(self.rng.integers(1, 4)), instance_group=group
            )
            jobs.append((app_id, group, pods))
        threads = [
            threading.Thread(
                target=self._try_place, args=job, name=f"soak-burst-{job[0]}"
            )
            for job in jobs
        ]
        for t in threads:
            t.start()
        return threads

    def _teardown(self, app_id: str) -> None:
        info = self.placed.pop(app_id)
        stack = self.facade.stacks[info["cluster"]]
        if not self.facade.router.members.is_live(info["cluster"]):
            self.placed[app_id] = info  # cluster down: cannot tear down
            return
        for p in info["pods"]:
            stack.delete_pod(p)
        self.facade.router.unbind(app_id)

    # -- invariants ----------------------------------------------------------

    def _reservation_holders(self, app_id: str) -> list[int]:
        out = []
        for s in self.facade.stacks:
            if any(
                rr.name == app_id
                for rr in s.backend.list("resourcereservations")
            ):
                out.append(s.index)
        return out

    def _check(self) -> None:
        for app_id in list(self.placed) + list(self.pending):
            holders = self._reservation_holders(app_id)
            if len(holders) > 1:
                self.double_placements.append((self.steps_run, app_id, holders))
        for s in self.facade.stacks:
            v = overcommit_violations(s.app, s.backend)
            if v:
                self.overcommit.append((self.steps_run, s.index, v))
            if not s.aggregates.oracle_equals():
                self.oracle_mismatches.append((self.steps_run, s.index))

    # -- the soak loop -------------------------------------------------------

    def run(
        self,
        steps: int = 45,
        kill_at: int = 15,
        rejoin_at: int = 30,
        check_every: int = 5,
    ) -> "FleetSoak":
        stacking = self.stack_window_ms > 0
        for step in range(steps):
            self.steps_run = step
            kill_now = step == kill_at and self.dead is None
            if kill_now and not stacking:
                self._kill()
            if step == rejoin_at and self.dead is not None:
                self.facade.rejoin_cluster(self.dead)
                self.dead = None
            # Fresh gang(s). Stacking mode submits one per group
            # concurrently so the coordinator actually gathers; the kill
            # then lands while the burst is in flight (kill-mid-gather).
            if stacking:
                burst = self._start_burst()
                if kill_now:
                    time.sleep(min(self.stack_window_ms, 50.0) / 2e3)
                    self._kill()
                for t in burst:
                    t.join()
            else:
                self.seq += 1
                group = self.groups[
                    int(self.rng.integers(0, len(self.groups)))
                ]
                self._submit(f"fleet-soak-{self.seq}", group)
            # Retry up to two pending gangs (oldest first).
            for app_id in list(self.pending)[:2]:
                info = self.pending.pop(app_id)
                self._try_place(app_id, info["group"], info["pods"])
            # Occasionally retire a placed app.
            if self.placed and self.rng.random() < 0.25:
                ids = sorted(self.placed)
                self._teardown(ids[int(self.rng.integers(0, len(ids)))])
            if step % check_every == 0:
                self._check()
        self._check()
        return self

    def _kill(self) -> None:
        victim = int(self.rng.integers(0, self.F))
        # Pending gangs routed to the victim are the orphans the
        # re-route invariant tracks.
        with self._traffic_lock:
            self.orphans_at_kill = {
                a
                for a in self.pending
                if self.facade.router.affinity_of(a) == victim
            }
        self.facade.kill_cluster(victim)
        self.dead = victim

    def verdict(self) -> dict:
        from spark_scheduler_tpu.fleet import verify_cluster_equivalence

        equivalence = verify_cluster_equivalence(self.facade)
        st = self.facade.state()
        # Every orphan must have left the dead cluster: either re-placed
        # on a survivor (orphans_rerouted) or re-routed and still pending
        # with a LIVE affinity (or none yet).
        unrouted = []
        for a in self.orphans_at_kill:
            aff = self.facade.router.affinity_of(a)
            if aff is not None and not self.facade.router.members.is_live(aff):
                unrouted.append(a)
        return {
            "steps": self.steps_run + 1,
            "double_placements": self.double_placements,
            "overcommit": self.overcommit,
            "oracle_mismatches": self.oracle_mismatches,
            "orphans_at_kill": len(self.orphans_at_kill),
            "orphans_rerouted": self.orphans_rerouted,
            "orphans_unrouted": unrouted,
            "unavailable_denials": self.unavailable_denials,
            "placed": len(self.placed),
            "pending": len(self.pending),
            "spillovers": st["spillover"]["spilled"],
            "stacking": st.get("stacking", {"enabled": False}),
            "equivalence": equivalence,
        }

    def stop(self) -> None:
        self.facade.stop()
