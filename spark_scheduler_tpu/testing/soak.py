"""Randomized invariant-soak ENGINE (VERDICT r4 #4, SURVEY §4 property
tests): a seeded random sequence of driver/executor arrivals, executor
deaths, app teardowns, topology churn (node add/cordon/delete), forced
reconciles, write faults, and idempotent retries through PIPELINED
serving windows (dispatch-before-fetch, depth 2 — the PredicateBatcher's
loop shape), asserting global scheduling invariants as it goes:

  1. No node over-committed: hard+soft reservations + overhead <=
     allocatable, per node, at every checkpoint.
  2. Every admitted gang has exactly its reservation: driver slot + min
     executor slots, all on nodes that exist.
  3. Pipeline-drained device mirror == host truth: after completing every
     in-flight window, the availability mirror the device base embodies
     equals the host view (a lost or double-counted gang diverges it).
  4. Idempotent retries never double-book: resubmitting an admitted driver
     returns its reserved node and changes no reservation.

Lives in the package (not tests/) so both the CPU test matrix
(tests/test_invariant_soak.py) and the ON-SILICON soak the bench runs
(bench.py bench_tpu_soak — Pallas window path under churn) drive one
engine. Anchor: extendertest harness pattern
(/root/reference/internal/extender/extendertest/extender_test_utils.go:51-397).
"""

from __future__ import annotations

import time

import numpy as np

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.core.solver import PipelineDrainRequired
from spark_scheduler_tpu.testing.harness import (
    Harness,
    dynamic_allocation_spark_pods,
    new_node,
    overcommit_violations,
    static_allocation_spark_pods,
)

CHECK_EVERY = 50  # full invariant sweep cadence (every step would be O(n^2))


class SoakClock:
    """Monotonic wall clock with a manual offset. Real elapsed time flows
    through (so demand-to-fulfilled latencies the bench reports are real),
    while elastic ops advance the offset to cross the drainer's idle TTL
    deterministically without sleeping."""

    def __init__(self):
        self._offset = 0.0

    def __call__(self) -> float:
        return time.monotonic() + self._offset

    def advance(self, dt: float) -> None:
        self._offset += dt


class Soak:
    def __init__(self, rng, strategy, n_nodes: int = 12, elastic: bool = False):
        self.rng = rng
        self.elastic = elastic
        self.clock = SoakClock() if elastic else None
        # same_az under single-az strategies: without it the extender's
        # zone-restriction gate (is_single_az AND same-az-dynalloc config)
        # stays False and the zone-restricted executor-reschedule ladder —
        # the very path the single-az matrix slot exists to soak — never
        # executes (verified by instrumentation in review).
        elastic_kw = (
            dict(
                autoscaler_enabled=True,
                # Low enough that autoscaler_tick ops cross it; real drains
                # happen mid-soak and provisioned capacity recycles.
                autoscaler_idle_ttl_s=30.0,
                # Headroom for several bursts, low enough that a busy run
                # exercises the cannot-fulfill cap path too.
                autoscaler_max_cluster_size=n_nodes + 48,
                autoscaler_zones=["zone0", "zone1", "zone2"],
                clock=self.clock,
            )
            if elastic
            else {}
        )
        self.h = Harness(
            binpack_algo=strategy, fifo=True,
            same_az_dynamic_allocation="single-az" in strategy,
            **elastic_kw,
        )
        self.node_seq = 0
        self.nodes: dict[str, object] = {}
        for _ in range(n_nodes):
            self._add_node()
        self.app_seq = 0
        # app_id -> {"driver": Pod, "execs": [Pod], "node": str,
        #            "min": int, "bound": {pod_name: node}}
        self.admitted: dict[str, dict] = {}
        self.pending_tickets = []  # pipelined windows in flight (max 2)
        self.ext = self.h.extender
        self.steps = 0
        self.op_counts: dict[str, int] = {}

    # ---------------------------------------------------------------- ops

    def _add_node(self):
        name = f"sn{self.node_seq}"
        self.node_seq += 1
        node = new_node(name, zone=f"zone{self.node_seq % 3}")
        self.h.add_nodes(node)
        self.nodes[name] = node

    def node_names(self):
        if self.elastic:
            # Elastic topology is backend truth: autoscaled nodes join the
            # candidate set, drained ones leave it.
            return [n.name for n in self.h.backend.list_nodes()]
        return list(self.nodes)

    def _dispatch(self, args_list):
        """Dispatch a window, draining the pipeline on topology changes the
        way the serving loop does (PipelineDrainRequired contract)."""
        for _ in range(3):
            try:
                t = self.ext.predicate_window_dispatch(args_list)
                self.pending_tickets.append(t)
                return
            except PipelineDrainRequired:
                self.drain()
        raise AssertionError("dispatch kept raising PipelineDrainRequired")

    def _complete_oldest(self):
        t = self.pending_tickets.pop(0)
        results = self.ext.predicate_window_complete(t)
        for args, res in zip(t.args_list, results):
            pod = args.pod
            role = pod.labels.get("spark-role", "")
            app_id = pod.labels.get("spark-app-id", "")
            if not res.ok:
                continue
            node = res.node_names[0]
            if role == "driver":
                entry = self.admitted.get(app_id)
                if entry is None:
                    # tracked by the op that submitted it
                    continue
                entry["node"] = node
                if self.h.backend.get("pods", pod.namespace, pod.name) is not None:
                    self.h.backend.bind_pod(pod, node)
            elif role == "executor":
                entry = self.admitted.get(app_id)
                if entry is not None:
                    entry["bound"][pod.name] = node
                # The app may have been torn down while this window was in
                # flight (its pods deleted) — a dead pod can't bind.
                if self.h.backend.get("pods", pod.namespace, pod.name) is not None:
                    self.h.backend.bind_pod(pod, node)
        return results

    def drain(self):
        while self.pending_tickets:
            self._complete_oldest()

    def op_submit_drivers(self):
        if len(self.admitted) > 24:
            # Bound the pending-driver population: unbounded FIFO prefixes
            # grow every later request's hypothetical rows (and the row
            # buckets) without adding coverage.
            self.op_teardown_app()
            return
        k = int(self.rng.integers(1, 4))
        args = []
        for _ in range(k):
            app_id = f"app-{self.app_seq}"
            self.app_seq += 1
            execs = int(self.rng.integers(1, 5))
            if self.rng.random() < 0.3:
                pods = dynamic_allocation_spark_pods(
                    app_id, execs, execs + int(self.rng.integers(1, 3))
                )
            else:
                pods = static_allocation_spark_pods(app_id, execs)
            self.h.add_pods(pods[0])
            self.admitted[app_id] = {
                "driver": pods[0], "execs": pods[1:], "node": None,
                "min": execs, "bound": {},
            }
            args.append(
                ExtenderArgs(pod=pods[0], node_names=self.node_names())
            )
        self._dispatch(args)
        if len(self.pending_tickets) > 2 or self.rng.random() < 0.6:
            self._complete_oldest()

    def op_submit_executors(self):
        ready = [
            (a, e) for a, e in self.admitted.items() if e["node"] is not None
        ]
        if not ready:
            return
        args = []
        for _ in range(int(self.rng.integers(1, 5))):
            app_id, entry = ready[int(self.rng.integers(0, len(ready)))]
            unsubmitted = [
                p for p in entry["execs"] if p.name not in entry["bound"]
            ]
            if not unsubmitted:
                continue
            pod = unsubmitted[int(self.rng.integers(0, len(unsubmitted)))]
            self.h.add_pods(pod)
            names = self.node_names()
            if self.rng.random() < 0.2:  # restricted candidates: reschedule
                self.rng.shuffle(names)
                names = names[: max(3, len(names) // 2)]
            args.append(ExtenderArgs(pod=pod, node_names=names))
        if not args:
            return
        self._dispatch(args)
        self._complete_oldest()

    def op_kill_executor(self):
        apps = [e for e in self.admitted.values() if e["bound"]]
        if not apps:
            return
        entry = apps[int(self.rng.integers(0, len(apps)))]
        name = list(entry["bound"])[0]
        pod = next(p for p in entry["execs"] if p.name == name)
        cur = self.h.backend.get("pods", pod.namespace, pod.name)
        if cur is not None:
            self.h.terminate_pod(cur)
        del entry["bound"][name]

    def op_teardown_app(self):
        if not self.admitted:
            return
        app_id = list(self.admitted)[int(self.rng.integers(0, len(self.admitted)))]
        entry = self.admitted.pop(app_id)
        for p in [entry["driver"]] + entry["execs"]:
            cur = self.h.backend.get("pods", p.namespace, p.name)
            if cur is not None:
                self.h.backend.delete_pod(cur)
        rr = self.h.get_reservation("namespace", app_id)
        if rr is not None:
            self.h.app.rr_cache.delete(rr.namespace, rr.name)

    def op_node_churn(self):
        self.drain()  # topology changes force a drain in the serving loop
        r = self.rng.random()
        if r < 0.5 or len(self.nodes) < 8:
            self._add_node()
        elif r < 0.8:
            # cordon/uncordon with a REPLACEMENT object, like the real
            # watch path — an in-place mutation would defeat the solver's
            # identity-based arena sync and test nothing.
            import dataclasses as _dc

            name = list(self.nodes)[int(self.rng.integers(0, len(self.nodes)))]
            node = _dc.replace(
                self.nodes[name],
                unschedulable=not self.nodes[name].unschedulable,
            )
            self.nodes[name] = node
            self.h.backend.update("nodes", node)
        else:
            # delete a node with no reservations on it (hard OR soft)
            used = set()
            for rr in self.h.app.rr_cache.list():
                for res in rr.spec.reservations.values():
                    used.add(res.node)
            for _app_id, sr in self.h.app.soft_store.get_all_copy().items():
                for r in sr.reservations.values():
                    used.add(r.node)
            free = [n for n in self.nodes if n not in used]
            if free:
                name = free[int(self.rng.integers(0, len(free)))]
                self.h.backend.delete("nodes", "", name)
                del self.nodes[name]

    def op_reconcile(self):
        self.drain()
        if self.ext._reconciler is not None:
            self.ext._reconciler.sync_resource_reservations_and_demands()

    def op_write_fault(self):
        """One faulted reservation write: the request fails internal and
        nothing may double-book afterwards."""
        fired = {"n": 0}

        def inject(kind, verb, obj):
            if kind == "resourcereservations" and fired["n"] == 0:
                fired["n"] = 1
                return RuntimeError("soak-injected write fault")
            return None

        self.h.backend.fault_injector = inject
        try:
            self.op_submit_drivers()
            self.drain()
        finally:
            self.h.backend.fault_injector = None
        # The faulted app (if any) got failure-internal; forget our intent
        # for apps that have no reservation so invariant #2 stays exact.
        for app_id in list(self.admitted):
            e = self.admitted[app_id]
            if e["node"] is None and self.h.get_reservation(
                "namespace", app_id
            ) is None:
                del self.admitted[app_id]

    def op_idempotent_retry(self):
        ready = [
            (a, e) for a, e in self.admitted.items() if e["node"] is not None
        ]
        if not ready:
            return
        app_id, entry = ready[int(self.rng.integers(0, len(ready)))]
        before = {
            k: (v.node)
            for k, v in self.h.get_reservation(
                "namespace", app_id
            ).spec.reservations.items()
        }
        res = self.ext.predicate(
            ExtenderArgs(pod=entry["driver"], node_names=self.node_names())
        )
        assert res.ok and res.node_names[0] == entry["node"], (
            "idempotent retry moved the driver",
            app_id, res, entry["node"],
        )
        after = {
            k: (v.node)
            for k, v in self.h.get_reservation(
                "namespace", app_id
            ).spec.reservations.items()
        }
        assert before == after, ("retry changed reservations", app_id)

    # ------------------------------------------------------- elastic ops

    def _assert_no_reserved_drained(self):
        """THE drain-safety invariant: after any autoscaler pass, every node
        a hard or soft reservation names must still exist."""
        known = {n.name for n in self.h.backend.list_nodes()}
        reserved = self.h.autoscaler.drainer.reserved_node_names()
        missing = reserved - known
        assert not missing, ("reserved node drained", missing, self.steps)

    def op_elastic_burst(self):
        """A gang too large for current free capacity: the failed admission
        creates a Demand, the autoscaler provisions nodes for it, and the
        retried driver should land on them. Each burst moves the node count
        across the solver's padding buckets (_bucket(capacity, 8)) under
        load — the recompile-boundary churn this soak mode exists for."""
        self.drain()
        execs = int(self.rng.integers(8, 17))
        app_id = f"burst-{self.app_seq}"
        self.app_seq += 1
        pods = static_allocation_spark_pods(app_id, execs)
        self.h.add_pods(pods[0])
        self.admitted[app_id] = {
            "driver": pods[0], "execs": pods[1:], "node": None,
            "min": execs, "bound": {},
        }
        for attempt in range(3):
            res = self.ext.predicate(
                ExtenderArgs(pod=pods[0], node_names=self.node_names())
            )
            if res.ok:
                self.admitted[app_id]["node"] = res.node_names[0]
                self.h.backend.bind_pod(pods[0], res.node_names[0])
                return
            # Demand emitted for the failed fit -> provision -> retry. The
            # retry may still fail (FIFO earlier drivers, or the cap) —
            # the global invariants cover both outcomes.
            self.h.autoscaler.run_once()
            self._assert_no_reserved_drained()

    def op_autoscaler_tick(self):
        """One autoscaler control-loop pass after a clock jump: sub-TTL
        jumps exercise idle tracking and cordons-in-progress, super-TTL
        jumps complete drains. Reserved nodes must survive every pass."""
        self.drain()  # topology may change: serving loop would drain too
        ttl = self.h.autoscaler.drainer.idle_ttl_s
        self.clock.advance(ttl * (0.6 if self.rng.random() < 0.5 else 1.1))
        self.h.autoscaler.run_once()
        self._assert_no_reserved_drained()

    # --------------------------------------------------------- invariants

    def check_invariants(self):
        # 1. no node over-committed (reservations + overhead <= allocatable)
        #    — the ONE shared definition (testing/harness.py).
        violations = overcommit_violations(self.h.app, self.h.backend)
        assert not violations, ("over-commit", violations, self.steps)
        # 2. every admitted gang has exactly its reservation
        for app_id, entry in self.admitted.items():
            if entry["node"] is None:
                continue
            rr = self.h.get_reservation("namespace", app_id)
            assert rr is not None, ("admitted app lost its RR", app_id)
            assert rr.spec.reservations["driver"].node == entry["node"], (
                "driver slot moved", app_id)
            exec_slots = [k for k in rr.spec.reservations if k != "driver"]
            assert len(exec_slots) == entry["min"], (
                "executor slot count", app_id)
        # 5. flight-recorder cross-check: recorded verdicts match actual
        #    placements (every checkpoint pass, observability contract).
        self.check_recorder()

    def check_recorder(self):
        """Recorded verdict == actual placement: the newest driver record
        of every admitted app is a success naming the reserved node, and
        every denied record carries its per-node failure-reason map. The
        soak is the one place windowed, solo, retried, and faulted
        admissions all flow through the recorder under churn."""
        rec = self.h.app.recorder
        if rec is None:
            return
        for app_id, entry in self.admitted.items():
            if entry["node"] is None:
                continue
            r = rec.latest_for_app("namespace", app_id, role="driver")
            if r is None:
                # The ring is bounded: a very long soak can evict an early
                # admission's record while the app stays admitted. Only a
                # missing record with ZERO evictions is a real failure —
                # once the ring has dropped records, absence is expected.
                assert rec.stats()["dropped"] > 0, (
                    "admitted app has no decision record",
                    app_id, self.steps,
                )
                continue
            assert r.verdict == "success" and r.node == entry["node"], (
                "recorded verdict diverges from placement",
                app_id, r.verdict, r.node, entry["node"], self.steps,
            )
        for d in rec.query(verdict="failure-*", limit=25):
            assert d["node"] is None and d["failed_nodes"], (
                "denied record lacks its failure map", d, self.steps)

    def check_drained_mirror(self):
        """Invariant 3: with the pipeline drained, the device-embodied
        availability mirror equals the host truth."""
        self.drain()
        solver = self.h.app.solver
        if solver._pipe is None:
            return
        backend = self.h.backend
        all_nodes = backend.list_nodes()
        usage = self.h.app.reservation_manager.reserved_usage()
        overhead = self.h.app.overhead_computer.get_overhead(all_nodes)
        tensors = solver.build_tensors_pipelined(
            all_nodes, usage, overhead,
            topo_version=getattr(backend, "nodes_version", None),
        )
        host = getattr(tensors, "host", tensors)
        mirror = solver._pipe["mirror"]
        assert np.array_equal(
            np.asarray(host.available, dtype=np.int64), mirror
        ), ("drained mirror diverges from host truth", self.steps)

    # -------------------------------------------------------------- drive

    OPS = (
        ("submit_drivers", 30, op_submit_drivers),
        ("submit_executors", 30, op_submit_executors),
        ("kill_executor", 10, op_kill_executor),
        ("teardown_app", 8, op_teardown_app),
        ("node_churn", 6, op_node_churn),
        ("reconcile", 4, op_reconcile),
        ("write_fault", 4, op_write_fault),
        ("idempotent_retry", 8, op_idempotent_retry),
    )
    ELASTIC_OPS = (
        ("elastic_burst", 8, op_elastic_burst),
        ("autoscaler_tick", 10, op_autoscaler_tick),
    )

    def run(self, steps):
        ops = self.OPS + (self.ELASTIC_OPS if self.elastic else ())
        names = [name for name, w, _ in ops for _ in range(w)]
        fns = {name: fn for name, _, fn in ops}
        while self.steps < steps:
            self.steps += 1
            name = names[int(self.rng.integers(0, len(names)))]
            self.op_counts[name] = self.op_counts.get(name, 0) + 1
            fns[name](self)
            if self.steps % CHECK_EVERY == 0:
                self.drain()
                self.check_invariants()
            if self.steps % (CHECK_EVERY * 4) == 0:
                self.check_drained_mirror()
        self.drain()
        self.check_invariants()
        self.check_drained_mirror()
