"""In-memory component-test harness.

Rebuilds internal/extender/extendertest/extender_test_utils.go:51-397: a
COMPLETE real scheduler (real caches, reservation manager, packing kernels,
FIFO) wired to the in-memory backend with synchronous write-back, plus
fixture factories matching the reference's (8 CPU / 8 GiB / 1 GPU nodes,
fully-annotated driver+executor pod sets). `schedule` invokes the real
predicate and then simulates kube-scheduler binding; `terminate_pod`
simulates executor death via terminated container statuses.
"""

from __future__ import annotations

import itertools

from spark_scheduler_tpu.core.extender import ExtenderArgs, ExtenderFilterResult
from spark_scheduler_tpu.core.sparkpods import (
    DA_MAX_EXECUTOR_COUNT,
    DA_MIN_EXECUTOR_COUNT,
    DRIVER_CPU,
    DRIVER_MEMORY,
    DYNAMIC_ALLOCATION_ENABLED,
    EXECUTOR_COUNT,
    EXECUTOR_CPU,
    EXECUTOR_MEMORY,
    ROLE_DRIVER,
    ROLE_EXECUTOR,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    SPARK_SCHEDULER_NAME,
)
from spark_scheduler_tpu.models.kube import Container, Node, Pod, ZONE_LABEL
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.server.app import SchedulerApp, build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

INSTANCE_GROUP_LABEL = "resource_channel"
DEFAULT_INSTANCE_GROUP = "batch-medium-priority"

_ts = itertools.count(1)


def new_node(name: str, zone: str = "zone1", instance_group: str = DEFAULT_INSTANCE_GROUP) -> Node:
    """8 CPU / 8 GiB / 1 GPU node (extender_test_utils.go:225-257)."""
    return Node(
        name=name,
        allocatable=Resources.from_quantities("8", "8Gi", "1", round_up=False),
        labels={
            ZONE_LABEL: zone,
            INSTANCE_GROUP_LABEL: instance_group,
        },
    )


def _spark_pods(
    app_id: str,
    num_executors: int,
    annotations: dict[str, str],
    instance_group: str = DEFAULT_INSTANCE_GROUP,
) -> list[Pod]:
    ts = float(next(_ts))
    driver = Pod(
        name=f"{app_id}-driver",
        namespace="namespace",
        labels={SPARK_ROLE_LABEL: ROLE_DRIVER, SPARK_APP_ID_LABEL: app_id},
        annotations=dict(annotations),
        creation_timestamp=ts,
        scheduler_name=SPARK_SCHEDULER_NAME,
        node_selector={INSTANCE_GROUP_LABEL: instance_group},
        containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
    )
    pods = [driver]
    for i in range(num_executors):
        pods.append(
            Pod(
                name=f"{app_id}-exec-{i + 1}",
                namespace="namespace",
                labels={SPARK_ROLE_LABEL: ROLE_EXECUTOR, SPARK_APP_ID_LABEL: app_id},
                creation_timestamp=ts,
                scheduler_name=SPARK_SCHEDULER_NAME,
                node_selector={INSTANCE_GROUP_LABEL: instance_group},
                containers=[Container(requests=Resources.from_quantities("1", "1Gi"))],
            )
        )
    return pods


def static_allocation_spark_pods(
    app_id: str,
    num_executors: int,
    instance_group: str = DEFAULT_INSTANCE_GROUP,
) -> list[Pod]:
    """Driver + executors, 1 CPU / 1 GiB each (extender_test_utils.go:261-277).
    `instance_group` pins the pods' node selector to that group's nodes —
    the multi-group topology the multi-device serving tests drive."""
    return _spark_pods(
        app_id,
        num_executors,
        {
            DRIVER_CPU: "1",
            DRIVER_MEMORY: "1Gi",
            EXECUTOR_CPU: "1",
            EXECUTOR_MEMORY: "1Gi",
            EXECUTOR_COUNT: str(num_executors),
        },
        instance_group=instance_group,
    )


def dynamic_allocation_spark_pods(
    app_id: str, min_executors: int, max_executors: int
) -> list[Pod]:
    """(extender_test_utils.go:280-302): pod list sized max, annotations
    min/max with dynamic allocation on."""
    return _spark_pods(
        app_id,
        max_executors,
        {
            DRIVER_CPU: "1",
            DRIVER_MEMORY: "1Gi",
            EXECUTOR_CPU: "1",
            EXECUTOR_MEMORY: "1Gi",
            DYNAMIC_ALLOCATION_ENABLED: "true",
            DA_MIN_EXECUTOR_COUNT: str(min_executors),
            DA_MAX_EXECUTOR_COUNT: str(max_executors),
        },
    )


class Harness:
    def __init__(
        self,
        binpack_algo: str = "single-az-tightly-pack",
        fifo: bool = True,
        same_az_dynamic_allocation: bool = False,
        metrics=None,
        events=None,
        waste=None,
        backend=None,
        clock=None,
        **config_kw,
    ):
        # An injected backend (e.g. DurableBackend for restart tests) is
        # used as-is; default is a fresh in-memory cluster.
        self.backend = backend if backend is not None else InMemoryBackend()
        self.backend.register_crd(DEMAND_CRD)
        config_kw.setdefault("sync_writes", True)
        self.app: SchedulerApp = build_scheduler_app(
            self.backend,
            InstallConfig(
                fifo=fifo,
                binpack_algo=binpack_algo,
                instance_group_label=INSTANCE_GROUP_LABEL,
                should_schedule_dynamically_allocated_executors_in_same_az=(
                    same_az_dynamic_allocation
                ),
                **config_kw,
            ),
            metrics=metrics,
            events=events,
            waste=waste,
            clock=clock,
        )
        self.extender = self.app.extender
        # suppress time-gap reconciliation in deterministic tests
        self.extender._last_request = float("inf")
        # ... and record that suppression in the trace (when one is being
        # written) so replay reproduces it instead of re-enabling the
        # clock-driven resync heuristic.
        if self.app.trace_writer is not None:
            self.app.trace_writer.emit_meta(resync_suppressed=True)

    # -- cluster fixtures ---------------------------------------------------

    def add_nodes(self, *nodes: Node) -> None:
        for n in nodes:
            self.backend.add_node(n)

    def add_pods(self, *pods: Pod) -> None:
        for p in pods:
            if self.backend.get("pods", p.namespace, p.name) is None:
                self.backend.add_pod(p)

    # -- scheduling ---------------------------------------------------------

    def schedule(self, pod: Pod, node_names: list[str]) -> ExtenderFilterResult:
        """Run the real predicate; on success simulate kube-scheduler binding
        + kubelet running (extender_test_utils.go:176-190)."""
        self.add_pods(pod)
        result = self.extender.predicate(ExtenderArgs(pod=pod, node_names=node_names))
        if result.ok:
            self.backend.bind_pod(pod, result.node_names[0])
        return result

    def schedule_app(self, pods: list[Pod], node_names: list[str]) -> list[ExtenderFilterResult]:
        return [self.schedule(p, node_names) for p in pods]

    def terminate_pod(self, pod: Pod) -> None:
        """Executor death via terminated containers (extender_test_utils.go:193-206)."""
        cur = self.backend.get("pods", pod.namespace, pod.name)
        for c in cur.containers:
            c.terminated = True
        self.backend.update_pod(cur)

    def delete_pod(self, pod: Pod) -> None:
        self.backend.delete_pod(pod)

    # -- inspection ---------------------------------------------------------

    def get_reservation(self, namespace: str, app_id: str):
        return self.app.rr_cache.get(namespace, app_id)

    def soft_reservations(self):
        return self.app.soft_store.get_all_copy()

    def demands(self):
        return self.app.demand_cache.list()

    @property
    def autoscaler(self):
        """The ElasticAutoscaler when built with autoscaler_enabled=True."""
        return self.app.autoscaler


def overcommit_violations(app, backend) -> list[tuple[str, str]]:
    """[(node_name, dimension)] wherever hard+soft reservations + overhead
    exceed allocatable — THE over-commit invariant, shared by bench.py's
    10k serving bench and tests/test_invariant_soak.py so the definition
    cannot drift. A reservation on a node the backend no longer knows is
    reported as ("<name>", "missing-node")."""
    from spark_scheduler_tpu.models.resources import Resources

    all_nodes = backend.list_nodes()
    known = {n.name for n in all_nodes}
    overhead = app.overhead_computer.get_overhead(all_nodes)
    assert isinstance(overhead, dict), type(overhead)  # the one provider
    reserved = app.reservation_manager.get_reserved_resources()
    out: list[tuple[str, str]] = []
    for node in all_nodes:
        res = reserved.get(node.name)
        if res is None:
            continue
        ov = overhead.get(node.name, Resources.zero()).as_array()
        alloc = node.allocatable
        if res.cpu_milli + int(ov[0]) > alloc.cpu_milli:
            out.append((node.name, "cpu"))
        if res.mem_kib + int(ov[1]) > alloc.mem_kib:
            out.append((node.name, "memory"))
        if res.gpu_milli + int(ov[2]) > alloc.gpu_milli:
            out.append((node.name, "gpu"))
    for name in reserved:
        if name not in known:
            out.append((name, "missing-node"))
    return out
