"""Test harness utilities (parity with the reference's exported
extendertest package)."""

from spark_scheduler_tpu.testing.harness import (  # noqa: F401
    Harness,
    new_node,
    static_allocation_spark_pods,
    dynamic_allocation_spark_pods,
)
from spark_scheduler_tpu.testing.rtt_shim import SimulatedRTT  # noqa: F401
