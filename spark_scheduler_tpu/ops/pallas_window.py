"""Segmented serving windows on the Pallas queue kernel (VERDICT r3 #3).

`core/solver.pack_window` expresses a serving window as SEGMENTS — each
/predicates request is its FIFO-earlier hypothetical rows followed by its
own committing row, availability rewinding to the committed base between
segments and the node priority orders re-sorted per segment from the
segment-start availability (the sort at resource.go:299). The r3 Pallas
queue kernel (ops/pallas_fifo.py) could not serve these windows: it bakes
ONE priority order into its node layout (positions pre-permuted into
executor-priority order), and Mosaic has no in-kernel sort.

The TPU-native factoring here splits the work by what each engine is good
at:

  - XLA, per segment: the eligibility masks and the priority SORTS from the
    committed base (fused device sorts — recomputing them per segment is
    exactly what the reference does per request);
  - Mosaic, per segment: the sequential row walk (hypothetical earlier
    drivers + the committing row) with availability resident in VMEM
    scratch across rows — the part the XLA scan pays loop-trip overhead
    for. Instead of pre-permuting the node axis, the kernel takes the
    priority orders as per-position RANK tensors and every "first in
    priority order" reduction is an argmin over the rank key — the same
    VPU cost, but layout-independent, so ONE kernel serves every segment's
    (fresh) orders.

A `lax.scan` over segments threads the committed base: the commit row's
placement (the kernel reports per-row driver/executor picks) is
scatter-subtracted in XLA between segments. Decisions are bit-identical to
the segmented XLA scan (`ops/batched.batched_fifo_pack` window mode) — the
parity suite (tests/test_pallas_window.py) compares the two paths
decision-for-decision, and the serving integration reuses the solver's
existing blob/fetch contract unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops.packing import _rank_of_position
from spark_scheduler_tpu.ops.sorting import priority_order, zone_ranks
from spark_scheduler_tpu.ops.pallas_fifo import (
    PALLAS_FILLS,
    PALLAS_SINGLE_AZ,
    _LANES,
    _layout_rows,
    _round_up,
    make_gang_solver,
    pallas_available,
)

try:  # pragma: no cover - import guard (mirrors pallas_fifo)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


class SegmentedWindow(NamedTuple):
    """A serving window re-shaped segment-major for the Pallas path.

    S segments (one per /predicates request), each padded to R rows; row
    [s, r] is the r-th FIFO row of request s (its pending earlier drivers,
    then — at index row_count[s]-1 — the request's own application).
    Padding rows carry valid=False."""

    driver_req: jnp.ndarray  # [S, R, 3] i32
    exec_req: jnp.ndarray  # [S, R, 3] i32
    exec_count: jnp.ndarray  # [S, R] i32
    valid: jnp.ndarray  # [S, R] bool
    skippable: jnp.ndarray  # [S, R] bool
    row_count: jnp.ndarray  # [S] i32 — real rows per segment
    driver_cand: jnp.ndarray  # [S, N] bool — the request's kube candidates
    domain: jnp.ndarray  # [S, N] bool — the request's affinity domain


def _make_window_kernel(
    fill: str, emax: int, n_pad: int, rows: int, *, num_zones: int = 0
):
    """Per-SEGMENT row walk in NODE order with rank-key argmins.

    Mirrors ops/pallas_fifo._make_kernel's math (capacities, driver
    feasibility identity, the three executor fills, the single-AZ zone
    loop — all through the shared make_gang_solver — and strict-FIFO
    blocking) with two deltas: positions are node indices (no
    pre-permutation), and every priority walk keys on the segment's rank
    tensors (drank/erank) instead of position order."""

    INF = INT32_INF
    cols = n_pad // rows

    def kernel(
        dreq_ref,  # SMEM [R, 3] i32
        ereq_ref,  # SMEM [R, 3] i32
        cnt_ref,  # SMEM [R] i32
        valid_ref,  # SMEM [R] i32
        skip_ref,  # SMEM [R] i32
        avail_ref,  # VMEM [3, rows, cols] i32 — segment-start availability
        elig_e_ref,  # VMEM [rows, cols] i32
        elig_d_ref,  # VMEM [rows, cols] i32
        drank_ref,  # VMEM [rows, cols] i32 — driver priority rank per node
        erank_ref,  # VMEM [rows, cols] i32 — executor priority rank per node
        zone_ref,  # VMEM [rows, cols] i32 — zone id per node (single-AZ)
        sched_ref,  # VMEM [3, rows, cols] i32 — schedulable (single-AZ)
        meta_out,  # VMEM [R, 4] i32
        execs_out,  # VMEM [R, emax] i32 (node ids)
        avail_scr,  # VMEM [3, rows, cols] i32 scratch
        blocked_scr,  # SMEM [1] i32 scratch
    ):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            avail_scr[:] = avail_ref[:]
            blocked_scr[0] = 0

        iota = (
            jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
            + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        )
        elig_e = elig_e_ref[:] != 0
        elig_d = elig_d_ref[:] != 0
        drank = drank_ref[:]
        erank = erank_ref[:]

        raw_count = cnt_ref[b]
        too_big = raw_count > emax
        count = jnp.minimum(raw_count, emax)
        valid = valid_ref[b] != 0
        skippable = skip_ref[b] != 0
        blocked_in = blocked_scr[0] != 0

        # --- node capacities (ops/capacity.py node_capacities, identical
        # math to the queue kernel)
        shape = (rows, cols)
        cap_e = jnp.full(shape, INF, jnp.int32)
        cap_wd = jnp.full(shape, INF, jnp.int32)
        fit_d = jnp.ones(shape, jnp.bool_)
        for d in range(3):
            a = avail_scr[d]
            er = ereq_ref[b, d]
            dr = dreq_ref[b, d]
            safe = jnp.maximum(er, 1)
            per_e = jnp.where(
                0 > a, 0, jnp.where(er == 0, INF, jnp.floor_divide(a, safe))
            )
            per_wd = jnp.where(
                dr > a,
                0,
                jnp.where(er == 0, INF, jnp.floor_divide(a - dr, safe)),
            )
            cap_e = jnp.minimum(cap_e, per_e)
            cap_wd = jnp.minimum(cap_wd, per_wd)
            fit_d = fit_d & (dr <= a)
        cap_e = jnp.where(elig_e, jnp.maximum(cap_e, 0), 0)
        cap_wd = jnp.where(elig_e, jnp.maximum(cap_wd, 0), 0)

        # Shared gang math (ops/pallas_fifo.make_gang_solver): the ONE
        # driver-selection / executor-fill / single-AZ-zone-pick
        # implementation, keyed here on the segment's rank tensors instead
        # of the queue kernel's pre-permuted positions.
        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (1, emax), 1)
        solve = make_gang_solver(
            fill,
            num_zones=num_zones, emax=emax, n_pad=n_pad, shape=shape,
            count=count, cap_e=cap_e, cap_wd=cap_wd, fit_d=fit_d,
            elig_e=elig_e, elig_d=elig_d, drank=drank,
            key=erank, node_val=iota, slot_iota=slot_iota,
            zone=zone_ref[:],
            sched3=[sched_ref[0], sched_ref[1], sched_ref[2]],
            avail3=[avail_scr[0], avail_scr[1], avail_scr[2]],
            dreq3=[dreq_ref[b, 0], dreq_ref[b, 1], dreq_ref[b, 2]],
            ereq3=[ereq_ref[b, 0], ereq_ref[b, 1], ereq_ref[b, 2]],
        )
        ok, is_drv, execs_row, exec_counts, driver_node = solve()

        packed = ok & valid & ~too_big
        admitted = packed & ~blocked_in

        for d in range(3):
            delta = exec_counts * ereq_ref[b, d] + jnp.where(
                is_drv, dreq_ref[b, d], 0
            )
            a = avail_scr[d]
            avail_scr[d] = jnp.where(admitted, a - delta, a)

        blocked_scr[0] = jnp.where(
            blocked_in | (valid & ~packed & ~skippable), 1, 0
        ).astype(jnp.int32)

        m_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 4), 1)
        out_driver = jnp.where(admitted, driver_node, -1)
        meta = jnp.where(
            m_iota == 0,
            out_driver,
            jnp.where(
                m_iota == 1,
                admitted.astype(jnp.int32),
                jnp.where(m_iota == 2, packed.astype(jnp.int32), 0),
            ),
        )
        meta_out[pl.ds(b, 1), :] = meta
        execs_out[pl.ds(b, 1), :] = jnp.where(admitted, execs_row, -1)

    return kernel


@partial(
    jax.jit,
    static_argnames=("fill", "emax", "num_zones", "interpret"),
)
def window_pack_pallas(
    cluster: ClusterTensors,
    win: SegmentedWindow,
    *,
    fill: str,
    emax: int,
    num_zones: int,
    interpret: bool = False,
):
    """Serve a segmented window: scan over segments, XLA sorts per segment
    from the committed base, Mosaic row walk per segment.

    Returns (meta [S,R,4] i32, execs [S,R,emax] i32, base_after [N,3]) —
    meta rows are (driver_node, admitted, packed, 0), exactly the queue
    kernel's contract, in node indices."""
    if fill not in PALLAS_FILLS and fill not in PALLAS_SINGLE_AZ:
        raise ValueError(
            f"pallas window path supports "
            f"{PALLAS_FILLS + tuple(PALLAS_SINGLE_AZ)}"
        )
    n = cluster.available.shape[0]
    s, r = win.exec_count.shape
    rows = _layout_rows(n)
    tile = rows * _LANES
    n_pad = _round_up(max(n, tile), tile)
    cols = n_pad // rows
    pad = n_pad - n

    kernel = _make_window_kernel(fill, emax, n_pad, rows, num_zones=num_zones)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(r,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 7,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((3, rows, cols), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
    )

    def fold(x, fill_value):
        """[N] -> [rows, cols] node-order tile."""
        return jnp.pad(x, (0, pad), constant_values=fill_value).reshape(
            rows, cols
        )

    def step(base, seg):
        dreq, ereq, cnt, valid, skip, row_count, cand, domain = seg

        def live_segment():
            # Per-segment eligibility + priority sorts from the committed
            # base (ops/batched.py masked mode, resource.go:299 semantics).
            dom = domain & cluster.valid
            driver_elig = dom & cand
            exec_elig = dom & ~cluster.unschedulable & cluster.ready
            zrank = zone_ranks(cluster, dom, num_zones, available=base)
            d_order, _ = priority_order(
                cluster, driver_elig, zrank, cluster.label_rank_driver,
                available=base,
            )
            e_order, _ = priority_order(
                cluster, exec_elig, zrank, cluster.label_rank_executor,
                available=base,
            )
            drank = _rank_of_position(d_order)
            erank = _rank_of_position(e_order)

            avail_tile = (
                jnp.pad(base.T.astype(jnp.int32), ((0, 0), (0, pad)))
                .reshape(3, rows, cols)
            )
            # Zone ids padded with an out-of-range id (padding matches no
            # zone); schedulable feeds the single-AZ zone-efficiency
            # scoring — node order, same fold as every other tile.
            zone_tile = fold(cluster.zone_id.astype(jnp.int32), num_zones)
            sched_tile = (
                jnp.pad(
                    jnp.asarray(cluster.schedulable).T.astype(jnp.int32),
                    ((0, 0), (0, pad)),
                ).reshape(3, rows, cols)
            )
            return pl.pallas_call(
                kernel,
                out_shape=[
                    jax.ShapeDtypeStruct((r, 4), jnp.int32),
                    jax.ShapeDtypeStruct((r, emax), jnp.int32),
                ],
                grid_spec=grid_spec,
                interpret=interpret,
            )(
                dreq.astype(jnp.int32),
                ereq.astype(jnp.int32),
                cnt.astype(jnp.int32),
                valid.astype(jnp.int32),
                skip.astype(jnp.int32),
                avail_tile,
                fold(exec_elig.astype(jnp.int32), 0),
                fold(driver_elig.astype(jnp.int32), 0),
                fold(drank, INT32_INF),
                fold(erank, INT32_INF),
                zone_tile,
                sched_tile,
            )

        def dead_segment():
            # S is BUCKETED: padding segments skip the sorts and the kernel
            # outright, so a small window's device cost tracks its real
            # request count, not the bucket.
            return (
                jnp.zeros((r, 4), jnp.int32),
                jnp.full((r, emax), -1, jnp.int32),
            )

        meta, execs = jax.lax.cond(row_count > 0, live_segment, dead_segment)
        # Commit the REQUEST row's placement (the last real row) into the
        # base for the next segment (ops/batched.py window mode).
        ci = jnp.maximum(row_count - 1, 0)
        c_admit = (meta[ci, 1] != 0) & (row_count > 0)
        c_driver = meta[ci, 0]
        c_execs = execs[ci]
        exec_counts = (
            jnp.zeros(n, jnp.int32)
            .at[jnp.clip(c_execs, 0, n - 1)]
            .add(jnp.where(c_execs >= 0, 1, 0))
        )
        delta = exec_counts[:, None] * ereq[ci][None, :] + jnp.where(
            (jnp.arange(n) == c_driver)[:, None] & (c_driver >= 0),
            dreq[ci][None, :],
            0,
        )
        base = jnp.where(c_admit, base - delta.astype(base.dtype), base)
        return base, (meta, execs)

    base_after, (meta, execs) = jax.lax.scan(
        step,
        jnp.asarray(cluster.available),
        (
            win.driver_req, win.exec_req, win.exec_count,
            win.valid, win.skippable, win.row_count,
            win.driver_cand, win.domain,
        ),
    )
    return meta, execs, base_after


def segmented_window_from_flat(
    drv_arr,  # [B, 3] int — flat rows, segment-major
    exc_arr,  # [B, 3] int
    counts,  # [B] int
    skip_arr,  # [B] bool
    row_counts,  # [S] int — rows per segment (sum == B)
    cand_masks,  # list/array of [N] bool — per segment
    domain_masks,  # list/array of [N] bool — per segment
    *,
    pad_segments: int,
    pad_rows: int,
):
    """THE SegmentedWindow layout builder (single owner): scatter flat
    segment-major row arrays into the padded [S, R] shape in a handful of
    vectorized assignments (per-row Python here would sit on the serving
    hot path). Returns (SegmentedWindow, seg_idx, row_idx) — the flat->
    [S, R] index map the fetch side uses to flatten the device blob."""
    s = len(row_counts)
    rc = np.asarray(row_counts, np.int64)
    seg_idx = np.repeat(np.arange(s, dtype=np.int64), rc)
    row_idx = np.concatenate(
        [np.arange(k, dtype=np.int64) for k in rc]
    ) if s else np.zeros(0, np.int64)
    n = len(cand_masks[0])
    dreq = np.zeros((pad_segments, pad_rows, 3), np.int32)
    ereq = np.zeros((pad_segments, pad_rows, 3), np.int32)
    cnt = np.zeros((pad_segments, pad_rows), np.int32)
    valid = np.zeros((pad_segments, pad_rows), bool)
    skip = np.zeros((pad_segments, pad_rows), bool)
    row_count = np.zeros(pad_segments, np.int32)
    cand = np.zeros((pad_segments, n), bool)
    dom = np.zeros((pad_segments, n), bool)
    dreq[seg_idx, row_idx] = drv_arr
    ereq[seg_idx, row_idx] = exc_arr
    cnt[seg_idx, row_idx] = counts
    valid[seg_idx, row_idx] = True
    skip[seg_idx, row_idx] = skip_arr
    row_count[:s] = rc
    cand[:s] = np.stack(cand_masks)
    dom[:s] = np.stack(domain_masks)
    win = SegmentedWindow(
        driver_req=dreq, exec_req=ereq, exec_count=cnt, valid=valid,
        skippable=skip, row_count=row_count, driver_cand=cand, domain=dom,
    )
    return win, seg_idx, row_idx


def make_segmented_window(
    requests_rows,  # list of list[(driver_req[3], exec_req[3], count, skip)]
    cand_masks,  # list of [N] bool — per request
    domain_masks,  # list of [N] bool — per request
    *,
    row_bucket: int = 16,
    pad_segments: int | None = None,
    pad_rows: int | None = None,
) -> SegmentedWindow:
    """List-of-rows convenience front-end over `segmented_window_from_flat`
    (tests, smoke). `pad_segments`/`pad_rows` override the defaults for
    callers with their own bucketing policy; padding segments have
    row_count 0 and are skipped at runtime."""
    s = len(requests_rows)
    r = 1
    for rws in requests_rows:
        r = max(r, len(rws))
    r = pad_rows if pad_rows is not None else _round_up(r, row_bucket)
    s_pad = pad_segments if pad_segments is not None else s
    rc = [len(rws) for rws in requests_rows]
    flat = [row for rws in requests_rows for row in rws]
    win, _, _ = segmented_window_from_flat(
        np.asarray([row[0] for row in flat], np.int32).reshape(-1, 3),
        np.asarray([row[1] for row in flat], np.int32).reshape(-1, 3),
        np.asarray([row[2] for row in flat], np.int32),
        np.asarray([bool(row[3]) for row in flat]),
        rc,
        cand_masks,
        domain_masks,
        pad_segments=s_pad,
        pad_rows=r,
    )
    return win


def window_pallas_eligible(fill: str) -> bool:
    """Whether the segmented serving-window Pallas path can serve this
    strategy on this backend — all six (the plain fills, and since r5 the
    single-AZ wrappers: per-zone fill + efficiency-scored zone pick through
    the shared make_gang_solver)."""
    return (
        fill in PALLAS_FILLS or fill in PALLAS_SINGLE_AZ
    ) and pallas_available()
