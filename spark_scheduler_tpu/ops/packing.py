"""Vectorized bin-packing strategies with slot-exact reference semantics.

The reference's five strategies (internal/extender/binpack.go:39-54) are
order-dependent greedy loops; here each becomes a closed-form tensor program
built on one observation: for all three executor-distribution kernels, the
per-node capacity vector `cap[i] = floor((avail-reserved)/req)` fully
determines the greedy outcome, so placement = prefix-sums / sorts /
searchsorted over `cap`, and gang feasibility = `sum(cap) >= count`.

  tightly-pack       (binpack/pack_tightly.go:34-63): fill nodes to capacity
      in priority order -> executor slot j lands on the first node whose
      cumulative capacity exceeds j: `searchsorted(cumsum(cap), j, 'right')`.

  distribute-evenly  (binpack/distribute_evenly.go:34-73): round-robin one
      executor per open node per round -> slot j's (round r_j, intra-round
      index k_j) come from searchsorted over the cumulative round sizes
      M[r] = #{i: cap_i > r}; the node is the (k_j+1)-th position with
      cap > r_j.

  minimal-fragmentation (binpack/minimal_fragmentation.go:49-205): if one
      node fits the whole gang, the smallest such node (earliest priority on
      ties) takes it; otherwise consume nodes in (capacity desc, priority
      asc) order while the running total stays <= count, then place the
      remainder on the smallest not-yet-consumed node that fits it.

  single-az-* (binpack/single_az.go:23-97): run the inner packer per zone
      (zones in driver-priority first-appearance order), keep feasible zone
      results, pick the highest average packing efficiency (strictly-greater
      replacement => earliest zone wins ties).

  az-aware-tightly-pack (binpack/az_aware_pack_tightly.go:27-38): single-AZ
      tightly-pack, falling back to plain tightly-pack.

Driver selection (binpack/binpack.go:60-87 SparkBinPack) — "first driver
candidate, in priority order, on which the driver fits and the executors
still pack" — uses the feasibility identity: placing the driver on node d
only changes node d's executor capacity, so total capacity with the driver on
d is `total - cap[d] + cap_with_driver[d]`, an O(N) vectorized check over ALL
driver candidates at once instead of a re-pack per candidate.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops.capacity import node_capacities, fits
from spark_scheduler_tpu.ops.sorting import priority_order, zone_ranks
from spark_scheduler_tpu.ops import efficiency as eff_ops


class Packing(NamedTuple):
    """Device-side PackingResult (binpack/binpack.go:25-31): node indices
    instead of names, -1 for "no node" / padding."""

    driver_node: jnp.ndarray  # i32 scalar
    executor_nodes: jnp.ndarray  # [Emax] i32
    has_capacity: jnp.ndarray  # bool scalar

    @staticmethod
    def empty(emax: int) -> "Packing":
        return Packing(
            driver_node=jnp.int32(-1),
            executor_nodes=jnp.full((emax,), -1, jnp.int32),
            has_capacity=jnp.bool_(False),
        )


def _rank_of_position(order: jnp.ndarray) -> jnp.ndarray:
    """rank[node] = position of node in `order`."""
    n = order.shape[0]
    return jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))


# ---------------------------------------------------------------------------
# Executor-distribution kernels.
# Each takes capacities *arranged by executor-priority position* plus the
# position->node map, and returns ([Emax] node indices, feasible).
# ---------------------------------------------------------------------------


def _check_cumsum_bound(n: int, emax: int) -> None:
    """Clamping caps to `count` bounds every cumsum at n*emax; guard the int32
    accumulator explicitly rather than overflowing silently. Clusters beyond
    this bound must shard the node axis (parallel/) — which also keeps each
    shard's prefix sums within range."""
    if n * emax >= 2**31:
        raise ValueError(
            f"n_nodes*emax = {n}*{emax} >= 2^31: int32 prefix sums would "
            "overflow; shard the node axis across devices instead of packing "
            "a single flat tensor"
        )


def _fill_tightly(caps_pos, order, count, emax):
    n = caps_pos.shape[0]
    _check_cumsum_bound(n, emax)
    caps = jnp.minimum(caps_pos, count)  # bounds cumsum at n*count
    cum = jnp.cumsum(caps)
    ok = cum[-1] >= count
    j = jnp.arange(emax, dtype=jnp.int32)
    pos = jnp.clip(jnp.searchsorted(cum, j, side="right"), 0, n - 1)
    nodes = jnp.where(j < count, order[pos], -1)
    return nodes.astype(jnp.int32), ok


def _fill_distribute_evenly(caps_pos, order, count, emax):
    n = caps_pos.shape[0]
    _check_cumsum_bound(n, emax)
    caps = jnp.minimum(caps_pos, count)
    ok = jnp.sum(caps) >= count
    # m[r] = number of nodes still open in round r = #{i: cap_i > r}.
    sorted_caps = jnp.sort(caps)
    r = jnp.arange(emax, dtype=jnp.int32)
    m = (n - jnp.searchsorted(sorted_caps, r, side="right")).astype(jnp.int32)
    M = jnp.cumsum(m)  # slots placed through round r
    j = jnp.arange(emax, dtype=jnp.int32)
    r_j = jnp.clip(jnp.searchsorted(M, j, side="right"), 0, emax - 1)
    prev = jnp.where(r_j > 0, M[jnp.maximum(r_j - 1, 0)], 0)
    k_j = j - prev  # index within round r_j (0-based, in priority order)
    open_ = caps[None, :] > r_j[:, None]  # [Emax, N]
    rank = jnp.cumsum(open_, axis=1)
    hit = open_ & (rank == (k_j + 1)[:, None])
    pos_j = jnp.argmax(hit, axis=1)
    nodes = jnp.where(j < count, order[pos_j], -1)
    return nodes.astype(jnp.int32), ok


def _fill_minimal_fragmentation(caps_pos, order, count, emax):
    n = caps_pos.shape[0]
    _check_cumsum_bound(n, emax)
    pos = jnp.arange(n, dtype=jnp.int32)
    cap_ok = caps_pos > 0
    caps_c = jnp.minimum(caps_pos, count)
    ok = jnp.sum(caps_c) >= count

    # Branch A: some node fits the whole gang -> smallest such (cap, pos).
    mask_a = cap_ok & (caps_pos >= count)
    exists_a = jnp.any(mask_a)
    min_cap_a = jnp.min(jnp.where(mask_a, caps_pos, INT32_INF))
    pos_a = jnp.min(jnp.where(mask_a & (caps_pos == min_cap_a), pos, INT32_INF))
    pos_a = jnp.clip(pos_a, 0, n - 1)

    # Branch B: consume (cap desc, pos asc) while cumulative <= count.
    desc = jnp.lexsort((pos, -caps_c, jnp.where(cap_ok, 0, 1)))
    caps_desc = jnp.where(cap_ok[desc], caps_c[desc], 0)
    cum = jnp.cumsum(caps_desc)
    consumed = cum <= count
    total = jnp.sum(jnp.where(consumed, caps_desc, 0))
    remainder = count - total
    consumed_pos = jnp.zeros(n, jnp.bool_).at[desc].set(consumed)
    mask_fin = cap_ok & ~consumed_pos & (caps_pos >= remainder)
    min_cap_f = jnp.min(jnp.where(mask_fin, caps_pos, INT32_INF))
    pos_f = jnp.min(jnp.where(mask_fin & (caps_pos == min_cap_f), pos, INT32_INF))
    pos_f = jnp.clip(pos_f, 0, n - 1)

    j = jnp.arange(emax, dtype=jnp.int32)
    idx = jnp.clip(jnp.searchsorted(cum, j, side="right"), 0, n - 1)
    pos_b = jnp.where(j < total, desc[idx], pos_f)

    chosen_pos = jnp.where(exists_a, pos_a, pos_b)
    nodes = jnp.where(j < count, order[chosen_pos], -1)
    return nodes.astype(jnp.int32), ok


_FILLS = {
    "tightly-pack": _fill_tightly,
    "distribute-evenly": _fill_distribute_evenly,
    "minimal-fragmentation": _fill_minimal_fragmentation,
}


# ---------------------------------------------------------------------------
# SparkBinPack: driver selection + executor distribution.
# ---------------------------------------------------------------------------


from functools import partial


def pack_one_app(
    avail: jnp.ndarray,  # [N,3] i32 — current availability
    exec_elig: jnp.ndarray,  # [N] bool
    driver_elig: jnp.ndarray,  # [N] bool
    d_order: jnp.ndarray,  # [N] i32 driver priority order
    d_rank: jnp.ndarray,  # [N] i32 rank of each node in d_order
    e_order: jnp.ndarray,  # [N] i32 executor priority order
    driver_req: jnp.ndarray,  # [3] i32
    exec_req: jnp.ndarray,  # [3] i32
    count: jnp.ndarray,  # i32 scalar
    fill_fn,
    emax: int,
):
    """Core gang pack against a given availability (binpack.go:60-87):
    driver selection via the feasibility identity (module docstring) + one
    executor fill with the chosen driver tentatively reserved. Shared by the
    single-app path (`spark_bin_pack`) and the batched FIFO scan body
    (ops/batched.py) so their semantics cannot diverge.

    Returns (driver_node, driver_one_hot[N,1], exec_nodes[Emax], ok).
    """
    n = avail.shape[0]
    zero = jnp.zeros_like(avail)
    cap_base = jnp.where(exec_elig, node_capacities(avail, zero, exec_req), 0)
    cap_base_c = jnp.minimum(cap_base, count)
    total_base = jnp.sum(cap_base_c)

    # Capacity of node i for executors if the driver were reserved on i.
    driver_reserved = jnp.broadcast_to(driver_req[None, :], avail.shape)
    cap_with_driver = jnp.where(
        exec_elig, node_capacities(avail, driver_reserved, exec_req), 0
    )
    total_if_driver = total_base - cap_base_c + jnp.minimum(cap_with_driver, count)

    driver_fit = driver_elig & fits(avail, driver_req)
    feasible = driver_fit & (total_if_driver >= count)
    best_rank = jnp.min(jnp.where(feasible, d_rank, INT32_INF))
    found = best_rank < INT32_INF
    driver_node = jnp.where(found, d_order[jnp.clip(best_rank, 0, n - 1)], -1).astype(
        jnp.int32
    )

    one_hot = (jnp.arange(n) == driver_node)[:, None]
    reserved = jnp.where(one_hot, driver_req[None, :], 0).astype(avail.dtype)
    caps = jnp.where(exec_elig, node_capacities(avail, reserved, exec_req), 0)
    exec_nodes, fill_ok = fill_fn(caps[e_order], e_order, count, emax)
    return driver_node, one_hot, exec_nodes, found & fill_ok


@partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def spark_bin_pack(
    cluster: ClusterTensors,
    driver_req: jnp.ndarray,  # [3] i32
    exec_req: jnp.ndarray,  # [3] i32
    count: jnp.ndarray,  # i32 scalar — number of executors
    driver_candidate_mask: jnp.ndarray,  # [N] bool (kube-scheduler candidates)
    domain_mask: jnp.ndarray,  # [N] bool (instance-group metadata domain)
    *,
    fill: str,
    emax: int,
    num_zones: int,
    zrank: jnp.ndarray | None = None,
) -> Packing:
    """Gang-pack one app (binpack/binpack.go:60-87).

    Driver candidates are `domain & driver_candidate_mask` in driver priority
    order; executor-eligible nodes are `domain & schedulable & ready`
    (sort/nodesorting.go:51-58). Feasibility identity (see module docstring)
    finds the first driver node for which the executors still pack without
    re-running the fill per candidate.
    """
    fill_fn = _FILLS[fill]
    avail = cluster.available
    n = avail.shape[0]

    domain = domain_mask & cluster.valid
    driver_elig = domain & driver_candidate_mask
    exec_elig = domain & ~cluster.unschedulable & cluster.ready

    if zrank is None:
        zrank = zone_ranks(cluster, domain, num_zones)
    d_order, _ = priority_order(cluster, driver_elig, zrank, cluster.label_rank_driver)
    e_order, _ = priority_order(cluster, exec_elig, zrank, cluster.label_rank_executor)
    d_rank = _rank_of_position(d_order)

    driver_node, _, exec_nodes, has_cap = pack_one_app(
        avail, exec_elig, driver_elig, d_order, d_rank, e_order,
        driver_req, exec_req, count, fill_fn, emax,
    )
    return Packing(
        driver_node=jnp.where(has_cap, driver_node, -1).astype(jnp.int32),
        executor_nodes=jnp.where(has_cap, exec_nodes, -1).astype(jnp.int32),
        has_capacity=has_cap,
    )


def single_az_orders(
    cluster,
    driver_elig: jnp.ndarray,  # [N] bool
    exec_elig: jnp.ndarray,  # [N] bool
    zrank: jnp.ndarray,  # [num_zones] i32
    num_zones: int,
    available: jnp.ndarray | None = None,
):
    """Per-zone priority orders for the single-AZ packers: restrict each
    eligibility vector to one zone and sort (what spark_bin_pack does
    internally when called with zone-masked masks, single_az.go:44-56).
    Returns ([Z,N] driver orders, [Z,N] driver ranks, [Z,N] exec orders)."""
    zmask_all = cluster.zone_id[None, :] == jnp.arange(num_zones, dtype=jnp.int32)[:, None]
    d_elig_z = driver_elig[None, :] & zmask_all
    e_elig_z = exec_elig[None, :] & zmask_all
    d_order_z = jax.vmap(
        lambda e: priority_order(
            cluster, e, zrank, cluster.label_rank_driver, available=available
        )[0]
    )(d_elig_z)
    e_order_z = jax.vmap(
        lambda e: priority_order(
            cluster, e, zrank, cluster.label_rank_executor, available=available
        )[0]
    )(e_elig_z)
    d_rank_z = jax.vmap(_rank_of_position)(d_order_z)
    return d_elig_z, e_elig_z, d_order_z, d_rank_z, e_order_z


def pack_one_app_single_az(
    zone_id: jnp.ndarray,  # [N] i32
    schedulable: jnp.ndarray,  # [N,3] i32
    avail: jnp.ndarray,  # [N,3] i32 — CURRENT availability
    driver_elig: jnp.ndarray,  # [N] bool (domain & candidates & valid)
    exec_elig: jnp.ndarray,  # [N] bool
    d_rank_global: jnp.ndarray,  # [N] i32 — rank in the FULL driver order
    d_elig_z,  # [Z,N] bool
    e_elig_z,  # [Z,N] bool
    d_order_z,  # [Z,N] i32
    d_rank_z,  # [Z,N] i32
    e_order_z,  # [Z,N] i32
    driver_req: jnp.ndarray,  # [3] i32
    exec_req: jnp.ndarray,  # [3] i32
    count: jnp.ndarray,  # i32 scalar
    fill_fn,
    emax: int,
    num_zones: int,
    include_executors_in_reserved: bool,
):
    """Single-AZ gang pack against a given availability (single_az.go:23-97):
    pack every zone (vmapped pack_one_app over zone-restricted orders), keep
    feasible zones, pick the best average packing efficiency — strictly-
    greater replacement, so the earliest zone (by first appearance in driver
    priority order) wins ties. Shared by the standalone `_single_az_pack`
    and the batched FIFO scan body (ops/batched.py) so their semantics
    cannot diverge.

    Returns (driver_node, driver_one_hot[N,1], exec_nodes[Emax], ok)."""
    # Zone first-appearance rank in driver priority order (single_az.go:58-73).
    zone_first = jnp.full(num_zones, INT32_INF, jnp.int32).at[zone_id].min(
        jnp.where(driver_elig, d_rank_global, INT32_INF)
    )
    # Zones with no executor-order nodes are skipped (single_az.go:40-43).
    zone_has_exec = jnp.zeros(num_zones, jnp.bool_).at[zone_id].max(exec_elig)

    def one(d_e, e_e, d_o, d_r, e_o):
        return pack_one_app(
            avail, e_e, d_e, d_o, d_r, e_o, driver_req, exec_req, count,
            fill_fn, emax,
        )

    drivers, one_hots, exec_nodes, oks = jax.vmap(one)(
        d_elig_z, e_elig_z, d_order_z, d_rank_z, e_order_z
    )

    effs = jax.vmap(
        lambda dn, en: eff_ops.avg_packing_efficiency_arrays(
            schedulable,
            avail,
            dn,
            en,
            driver_req,
            exec_req,
            # minimalFragmentation never adds executors to reservedResources
            # in the reference, so its zone scores are driver-only (see
            # efficiency.avg_packing_efficiency docstring).
            include_executors_in_reserved=include_executors_in_reserved,
        ).max
    )(drivers, exec_nodes)
    valid_zone = oks & (zone_first < INT32_INF) & zone_has_exec
    effs = jnp.where(valid_zone, effs, -jnp.inf)
    best_eff = jnp.max(effs)
    # chooseBestResult starts from WorstAvgPackingEfficiency (Max=0.0) and
    # replaces only on strictly-greater, so a zone whose best efficiency is
    # exactly 0.0 is rejected entirely (single_az.go:84-97).
    any_valid = jnp.any(valid_zone) & (best_eff > 0.0)
    tie = valid_zone & (effs == best_eff)
    best_zone = jnp.argmin(jnp.where(tie, zone_first, INT32_INF))

    driver_node = jnp.where(any_valid, drivers[best_zone], -1).astype(jnp.int32)
    execs = jnp.where(any_valid, exec_nodes[best_zone], -1).astype(jnp.int32)
    one_hot = one_hots[best_zone] & any_valid
    return driver_node, one_hot, execs, any_valid


@partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def _single_az_pack(
    cluster,
    driver_req,
    exec_req,
    count,
    driver_candidate_mask,
    domain_mask,
    *,
    fill,
    emax,
    num_zones,
):
    """Single-AZ wrapper (binpack/single_az.go:23-97): per-zone SparkBinPack,
    best feasible zone by average packing efficiency."""
    domain = domain_mask & cluster.valid
    driver_elig = domain & driver_candidate_mask
    exec_elig = domain & ~cluster.unschedulable & cluster.ready
    zrank = zone_ranks(cluster, domain, num_zones)
    d_order, _ = priority_order(cluster, driver_elig, zrank, cluster.label_rank_driver)
    d_rank = _rank_of_position(d_order)

    d_elig_z, e_elig_z, d_order_z, d_rank_z, e_order_z = single_az_orders(
        cluster, driver_elig, exec_elig, zrank, num_zones
    )
    driver_node, _, execs, ok = pack_one_app_single_az(
        cluster.zone_id,
        cluster.schedulable,
        cluster.available,
        driver_elig,
        exec_elig,
        d_rank,
        d_elig_z,
        e_elig_z,
        d_order_z,
        d_rank_z,
        e_order_z,
        driver_req,
        exec_req,
        count,
        _FILLS[fill],
        emax,
        num_zones,
        include_executors_in_reserved=(fill != "minimal-fragmentation"),
    )
    return Packing(driver_node=driver_node, executor_nodes=execs, has_capacity=ok)


# ---------------------------------------------------------------------------
# Public strategy entry points (internal/extender/binpack.go:39-54 registry).
# ---------------------------------------------------------------------------


def tightly_pack(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    return spark_bin_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        fill="tightly-pack", emax=emax, num_zones=num_zones,
    )


def distribute_evenly(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    return spark_bin_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        fill="distribute-evenly", emax=emax, num_zones=num_zones,
    )


def minimal_fragmentation(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    return spark_bin_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        fill="minimal-fragmentation", emax=emax, num_zones=num_zones,
    )


def single_az_tightly_pack(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    return _single_az_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        fill="tightly-pack", emax=emax, num_zones=num_zones,
    )


def single_az_minimal_fragmentation(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    return _single_az_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        fill="minimal-fragmentation", emax=emax, num_zones=num_zones,
    )


def az_aware_tightly_pack(cluster, driver_req, exec_req, count, driver_mask, domain_mask, *, emax, num_zones):
    """Try single-AZ tightly-pack, fall back to plain tightly-pack
    (binpack/az_aware_pack_tightly.go:27-38)."""
    az = single_az_tightly_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        emax=emax, num_zones=num_zones,
    )
    plain = tightly_pack(
        cluster, driver_req, exec_req, count, driver_mask, domain_mask,
        emax=emax, num_zones=num_zones,
    )
    pick_az = az.has_capacity
    return Packing(
        driver_node=jnp.where(pick_az, az.driver_node, plain.driver_node),
        executor_nodes=jnp.where(pick_az, az.executor_nodes, plain.executor_nodes),
        has_capacity=pick_az | plain.has_capacity,
    )


# Strategy registry (internal/extender/binpack.go:21-54). Keys match the
# reference's config strings; values are (fn, is_single_az).
BINPACK_FUNCTIONS = {
    "tightly-pack": tightly_pack,
    "distribute-evenly": distribute_evenly,
    "minimal-fragmentation": minimal_fragmentation,
    "single-az-tightly-pack": single_az_tightly_pack,
    "single-az-minimal-fragmentation": single_az_minimal_fragmentation,
    "az-aware-tightly-pack": az_aware_tightly_pack,
}
SINGLE_AZ_PACKERS = frozenset(
    {"single-az-tightly-pack", "single-az-minimal-fragmentation"}
)
DEFAULT_BINPACK = "tightly-pack"


# ---------------------------------------------------------------------------
# Vectorized preemption search (policy subsystem).
# ---------------------------------------------------------------------------

# The single-az fills run the plain inner fill per zone; for the preemption
# *search* (a feasibility probe — the actual admission re-runs the real
# strategy after eviction) each strategy maps to its plain inner fill.
PREEMPTION_FILL = {
    "tightly-pack": "tightly-pack",
    "distribute-evenly": "distribute-evenly",
    "minimal-fragmentation": "minimal-fragmentation",
    "single-az-tightly-pack": "tightly-pack",
    "single-az-minimal-fragmentation": "minimal-fragmentation",
    "az-aware-tightly-pack": "tightly-pack",
}


@partial(jax.jit, static_argnames=("fill", "emax", "num_zones"))
def preemption_batched_fit(
    cluster: ClusterTensors,
    freed_cum: jnp.ndarray,  # [C,N,3] i32 — capacity freed by each candidate eviction set
    driver_req: jnp.ndarray,  # [3] i32
    exec_req: jnp.ndarray,  # [3] i32
    count: jnp.ndarray,  # i32 scalar
    driver_candidate_mask: jnp.ndarray,  # [N] bool
    domain_mask: jnp.ndarray,  # [N] bool
    *,
    fill: str,
    emax: int,
    num_zones: int,
):
    """Masked gang fit for ALL candidate eviction sets in one batched pass.

    Candidate c's availability is `cluster.available + freed_cum[c]` — the
    cluster with eviction set c's reservations released. The node priority
    orders are availability-dependent (ops/sorting.py lexsorts on free
    cpu/mem), so the whole per-candidate program — zone ranks, both priority
    orders, and the `pack_one_app` feasibility identity — is vmapped over
    the candidate axis and compiled once: no per-candidate Python loop over
    kernel calls, which is what makes the search affordable at 100k nodes
    (see PERFORMANCE.md).

    Eligibility masks are availability-independent and computed once.
    Returns (ok[C] bool, driver_node[C] i32, exec_nodes[C,Emax] i32). With
    nested candidate sets (set c = victims[0..c]) the first ok index is the
    minimal eviction set.
    """
    fill_fn = _FILLS[fill]
    n = cluster.available.shape[0]
    _check_cumsum_bound(n, emax)

    domain = domain_mask & cluster.valid
    driver_elig = domain & driver_candidate_mask
    exec_elig = domain & ~cluster.unschedulable & cluster.ready

    def fit_one(freed):
        avail = cluster.available + freed
        zrank = zone_ranks(cluster, domain, num_zones, available=avail)
        d_order, _ = priority_order(
            cluster, driver_elig, zrank, cluster.label_rank_driver, available=avail
        )
        e_order, _ = priority_order(
            cluster, exec_elig, zrank, cluster.label_rank_executor, available=avail
        )
        d_rank = _rank_of_position(d_order)
        driver_node, _one_hot, exec_nodes, ok = pack_one_app(
            avail, exec_elig, driver_elig, d_order, d_rank, e_order,
            driver_req, exec_req, count, fill_fn, emax,
        )
        return ok, driver_node, exec_nodes

    ok, driver_node, exec_nodes = jax.vmap(fit_one)(freed_cum)
    return ok, driver_node.astype(jnp.int32), exec_nodes.astype(jnp.int32)
