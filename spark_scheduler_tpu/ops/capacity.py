"""Per-node executor-capacity kernel.

The scalar loop the reference runs per node
(binpack/minimal_fragmentation.go:113-151 `getNodeCapacity` /
`getCapacityAgainstSingleDimension`) becomes one vectorized expression over
the whole `[N, 3]` availability tensor. Exact integer semantics:

  per dim: 0                       if reserved > available
           INF                     if required == 0
           floor((avail-res)/req)  otherwise
  node capacity = min over dims, never negative.

This kernel is THE hot op of the framework: every packing strategy, the gang
fit check, and the FIFO admission scan all reduce to it plus prefix sums.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import INT32_INF

CAP_INF = INT32_INF


def node_capacities(
    available: jnp.ndarray,  # [N, 3] i32
    reserved: jnp.ndarray,  # [N, 3] i32 (already-tentatively-reserved, e.g. driver)
    request: jnp.ndarray,  # [3] i32 (one executor)
) -> jnp.ndarray:  # [N] i32
    """How many `request`-shaped items fit on each node."""
    diff = available - reserved
    req = request[None, :]
    safe = jnp.maximum(req, 1)
    per_dim = jnp.where(
        reserved > available,
        0,
        jnp.where(req == 0, CAP_INF, jnp.floor_divide(diff, safe)),
    )
    return jnp.maximum(jnp.min(per_dim, axis=-1), 0).astype(jnp.int32)


def fits(
    available: jnp.ndarray,  # [N, 3] i32
    request: jnp.ndarray,  # [3] i32
) -> jnp.ndarray:  # [N] bool
    """Per-node `not request.greater_than(available)` (resources.go:242-245)."""
    return jnp.all(request[None, :] <= available, axis=-1)
