"""Batched FIFO gang admission — the whole scheduling queue as ONE XLA program.

The reference admits drivers one HTTP request at a time, re-running a Go
greedy loop per app (resource.go:125-189) and, for FIFO, re-packing every
earlier driver inside the request (`fitEarlierDrivers`, resource.go:221-258).
This module is the TPU-native replacement: the FIFO-sorted queue of B apps is
a tensor batch, and admission is a `lax.scan` over the app axis threading the
cluster availability tensor — each step is a fully vectorized O(N) gang pack
(driver selection via the feasibility identity + executor fill via prefix
sums, see ops/packing.py), and the scatter-subtract of an admitted app's
usage replaces the reference's `metadata.SubtractUsageIfExists`
(resource.go:251-255).

Reference-faithful FIFO semantics:
  - apps are processed in FIFO order (creation time; host sorts before the
    call, sparkpods.go:60-77);
  - an admitted app's usage is subtracted before the next app packs
    (resource.go:251-255);
  - a *non-skippable* app that fails blocks everything behind it — strict
    FIFO (resource.go:241-249); `skippable[i]` marks apps the age-based
    enforcement lets later apps jump over (resource.go:260-270,
    config/config.go:57-64);
  - node priority orders are computed ONCE from the starting availability
    and reused for every app, exactly as `fitEarlierDrivers` reuses the
    orders computed at resource.go:299 while only availability mutates.

Cost: B scan steps, each O(N) vector work + an O(Emax) fill — ~B*N total,
laid out as dense int32 vector ops XLA maps onto the VPU. The 10k-node x
1k-app north star (BASELINE.md) is one invocation of `batched_fifo_pack`.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import ClusterTensors
from spark_scheduler_tpu.ops.packing import (
    _FILLS,
    _check_cumsum_bound,
    _rank_of_position,
    pack_one_app,
    pack_one_app_single_az,
    single_az_orders,
)
from spark_scheduler_tpu.ops.sorting import priority_order, zone_ranks

# Single-AZ strategies run the per-zone pack + efficiency-scored zone pick
# inside the scan step; az-aware additionally computes the plain fallback
# (az_aware_pack_tightly.go:27-38). Values are the inner executor fill.
_SINGLE_AZ_INNER = {
    "single-az-tightly-pack": "tightly-pack",
    "single-az-minimal-fragmentation": "minimal-fragmentation",
    "az-aware-tightly-pack": "tightly-pack",
}


class AppBatch(NamedTuple):
    """FIFO-ordered queue of gang requests (one row per Spark application).

    The tensor form of `sparkApplicationResources` (sparkpods.go:29-35) x B,
    already sorted by creation time host-side (`filterToEarliestAndSort`,
    sparkpods.go:60-77). Rows past the real queue length are padding with
    `app_valid=False`.

    `driver_cand` / `domain` are OPTIONAL per-app node masks. When both are
    None the kernel runs in queue mode: every app sees the same eligibility
    and the node priority orders are computed once from the starting
    availability (fitEarlierDrivers semantics, resource.go:221-258). When
    set, each app packs exactly as a standalone `spark_bin_pack` call with
    those masks against the then-current availability — the serving path's
    per-request decisions, batched (SURVEY.md §2d row 1).
    """

    driver_req: jnp.ndarray  # [B, 3] i32
    exec_req: jnp.ndarray  # [B, 3] i32
    exec_count: jnp.ndarray  # [B] i32 — gang size (min executors)
    app_valid: jnp.ndarray  # [B] bool — padding mask
    skippable: jnp.ndarray  # [B] bool — FIFO age-based skip (resource.go:260-270)
    driver_cand: jnp.ndarray | None = None  # [B, N] bool — kube candidate list
    domain: jnp.ndarray | None = None  # [B, N] bool — node-affinity domain
    # Segmented WINDOW mode (both set together; core/solver.py pack_window
    # is the caller): each serving request is a segment of rows (its
    # FIFO-earlier drivers, then itself). `reset` marks a segment's first
    # row — availability rewinds to the committed base; `commit` marks the
    # request row — its admission persists into the base. Hypothetical
    # (non-commit) rows subtract only within their segment, replicating the
    # reference's fitEarlierDrivers exactly — INCLUDING its double-count of
    # an admitted-but-still-unbound earlier driver (usage already carries
    # its reservation AND it is re-packed hypothetically,
    # resource.go:221-258 + GetReservedResources) — so windowed == solo
    # serving, decision for decision.
    #
    # FUSED MULTI-WINDOW batches (solver.pack_windows_dispatch) are
    # ordinary segmented batches: K serving windows concatenated in
    # dispatch order need no device-side window marker, because a window
    # boundary IS a segment boundary — the scan's committed base carries
    # across it exactly as `available_after` would be threaded between K
    # sequential dispatches (fuse_app_batches pins the identity). That is
    # what lets K queued windows ride ONE h2d + ONE dispatch + ONE d2h.
    commit: jnp.ndarray | None = None  # [B] bool
    reset: jnp.ndarray | None = None  # [B] bool


def queue_mode_orders(cluster: ClusterTensors, num_zones: int):
    """Queue-mode eligibility + priority orders, fixed from the starting
    availability (fitEarlierDrivers reuses the orders computed at
    resource.go:299 while only availability mutates). Shared by the XLA
    scan and the Pallas queue kernel (ops/pallas_fifo.py) so the two paths
    cannot drift.

    Returns (driver_elig, exec_elig, d_order, d_rank, e_order, zrank)."""
    domain0 = cluster.valid
    exec_elig = domain0 & ~cluster.unschedulable & cluster.ready
    driver_elig = exec_elig  # no kube candidate filter in queue mode
    zrank = zone_ranks(cluster, domain0, num_zones)
    d_order, _ = priority_order(
        cluster, driver_elig, zrank, cluster.label_rank_driver
    )
    e_order, _ = priority_order(
        cluster, exec_elig, zrank, cluster.label_rank_executor
    )
    d_rank = _rank_of_position(d_order)
    return driver_elig, exec_elig, d_order, d_rank, e_order, zrank


class BatchedPacking(NamedTuple):
    """Per-app gang placement for the whole queue."""

    driver_node: jnp.ndarray  # [B] i32, -1 = not admitted
    executor_nodes: jnp.ndarray  # [B, Emax] i32, -1 = padding / not admitted
    admitted: jnp.ndarray  # [B] bool — packed AND not FIFO-blocked
    packed: jnp.ndarray  # [B] bool — would fit, ignoring FIFO blocking
    available_after: jnp.ndarray  # [N, 3] i32 — availability after all admits


@partial(jax.jit, static_argnames=("fill", "emax", "num_zones", "unroll"))
def batched_fifo_pack(
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    unroll: int = 2,
    zone_base: tuple | None = None,
) -> BatchedPacking:
    """Admit a FIFO queue of gang requests in one compiled program.

    `emax` is the static executor-slot padding (>= max(exec_count));
    `num_zones` the static zone-id bound. Strict-FIFO blocking: once a
    non-skippable valid app fails to pack, every later app is rejected
    (`failure-earlier-driver`, resource.go:241-249) but its hypothetical
    packing is still reported in `packed` for demand creation.

    All six strategies batch: the single-AZ wrappers run their per-zone
    pack + efficiency-scored zone pick (single_az.go:23-97) INSIDE the scan
    step (VERDICT r2 #2), with the zone efficiencies always computed against
    the then-current availability.

    `zone_base` (candidate pruning, core/prune.py): constant excluded-row
    zone-sum offsets forwarded to every per-segment zone_ranks call, so a
    gathered top-K sub-cluster ranks zones byte-identically to the full
    solve. Plain fills only — the single-AZ wrappers additionally score
    zones by subset-dependent efficiencies, so the pruned path never routes
    them here with offsets.
    """
    single_az = fill in _SINGLE_AZ_INNER
    if zone_base is not None and single_az:
        raise ValueError(
            "zone_base offsets are only sound for plain fills; "
            f"got single-AZ strategy {fill!r}"
        )
    az_fallback = fill == "az-aware-tightly-pack"
    fill_fn = _FILLS[_SINGLE_AZ_INNER.get(fill, fill)]
    include_exec_in_reserved = _SINGLE_AZ_INNER.get(fill) != "minimal-fragmentation"
    n = cluster.available.shape[0]
    _check_cumsum_bound(n, emax)

    segmented = apps.commit is not None
    # Segmented windows always run with per-row masks (synthesized all-true
    # when absent): each segment is one serving request.
    masked = segmented or apps.driver_cand is not None or apps.domain is not None
    if not masked:
        (driver_elig0, exec_elig0, d_order0, d_rank0, e_order0, zrank0) = (
            queue_mode_orders(cluster, num_zones)
        )
        if single_az:
            zone_orders0 = single_az_orders(
                cluster, driver_elig0, exec_elig0, zrank0, num_zones
            )

    if masked:
        b = apps.driver_req.shape[0]
        ones = jnp.ones((b, n), jnp.bool_)
        extra = (
            apps.driver_cand if apps.driver_cand is not None else ones,
            apps.domain if apps.domain is not None else ones,
        )
    else:
        extra = ()

    def _fresh_orders(avail, driver_elig, exec_elig, domain):
        """Priority orders from the given availability (the sort at
        resource.go:299)."""
        zrank = zone_ranks(
            cluster, domain, num_zones, available=avail, zone_base=zone_base
        )
        d_order, _ = priority_order(
            cluster, driver_elig, zrank, cluster.label_rank_driver,
            available=avail,
        )
        e_order, _ = priority_order(
            cluster, exec_elig, zrank, cluster.label_rank_executor,
            available=avail,
        )
        d_rank = _rank_of_position(d_order)
        out = (d_order, d_rank, e_order)
        if single_az:
            out = out + single_az_orders(
                cluster, driver_elig, exec_elig, zrank, num_zones,
                available=avail,
            )
        return out

    def _orders_placeholder():
        z = jnp.zeros(n, jnp.int32)
        out = (z, z, z)
        if single_az:
            zb = jnp.zeros((num_zones, n), jnp.bool_)
            zi = jnp.zeros((num_zones, n), jnp.int32)
            out = out + (zb, zb, zi, zi, zi)
        return out

    def step(carry, app):
        if segmented:
            base, avail, blocked, carried_orders = carry
            (driver_req, exec_req, count, valid, skippable,
             commit, reset, *masks) = app
            # Segment boundary: rewind to the committed base; FIFO blocking
            # is segment-local (each request's solo solve starts unblocked).
            avail = jnp.where(reset, base, avail)
            blocked = jnp.where(reset, jnp.bool_(False), blocked)
        else:
            avail, blocked = carry
            driver_req, exec_req, count, valid, skippable, *masks = app
        cand_i, dom_i = masks if masked else (None, None)
        # A gang larger than the static slot padding cannot be represented —
        # reject it outright rather than silently truncating it. Callers
        # size emax to the queue's max gang (make_app_batch knows it).
        too_big = count > emax
        count = jnp.minimum(count, emax)

        if masked:
            domain = dom_i & cluster.valid
            driver_elig = domain & cand_i
            exec_elig = domain & ~cluster.unschedulable & cluster.ready

        if segmented:
            # One sort per SEGMENT (= per serving request), computed from
            # the segment-start availability and reused for every row of
            # the segment — exactly the reference, which sorts once per
            # request (resource.go:299) and reuses the orders across
            # fitEarlierDrivers and the final pack while only availability
            # mutates. lax.cond executes the sort only on reset rows.
            orders = jax.lax.cond(
                reset,
                lambda: _fresh_orders(avail, driver_elig, exec_elig, domain),
                lambda: carried_orders,
            )
            d_order, d_rank, e_order = orders[:3]
            if single_az:
                zone_orders = orders[3:]
        elif masked:
            # Per-app masks without segments: each row reproduces a
            # standalone spark_bin_pack call with these masks against the
            # CURRENT availability — ordering and zone ranks recomputed per
            # step exactly as each serving request recomputes them from
            # post-admission usage.
            orders = _fresh_orders(avail, driver_elig, exec_elig, domain)
            d_order, d_rank, e_order = orders[:3]
            if single_az:
                zone_orders = orders[3:]
        else:
            driver_elig, exec_elig = driver_elig0, exec_elig0
            d_order, d_rank, e_order = d_order0, d_rank0, e_order0
            if single_az:
                zone_orders = zone_orders0

        if single_az:
            driver_node, one_hot, exec_nodes, ok = pack_one_app_single_az(
                cluster.zone_id, cluster.schedulable, avail,
                driver_elig, exec_elig, d_rank, *zone_orders,
                driver_req, exec_req, count, fill_fn, emax, num_zones,
                include_executors_in_reserved=include_exec_in_reserved,
            )
            if az_fallback:
                # az-aware: plain tightly-pack when no single zone fits
                # (az_aware_pack_tightly.go:27-38).
                p_driver, p_hot, p_execs, p_ok = pack_one_app(
                    avail, exec_elig, driver_elig, d_order, d_rank, e_order,
                    driver_req, exec_req, count, fill_fn, emax,
                )
                driver_node = jnp.where(ok, driver_node, p_driver)
                one_hot = jnp.where(ok, one_hot, p_hot)
                exec_nodes = jnp.where(ok, exec_nodes, p_execs)
                ok = ok | p_ok
        else:
            driver_node, one_hot, exec_nodes, ok = pack_one_app(
                avail, exec_elig, driver_elig, d_order, d_rank, e_order,
                driver_req, exec_req, count, fill_fn, emax,
            )

        packed = ok & valid & ~too_big
        admitted = packed & ~blocked

        # Scatter-subtract the admitted gang's usage (resource.go:251-255).
        exec_counts = (
            jnp.zeros(n, jnp.int32)
            .at[jnp.clip(exec_nodes, 0, n - 1)]
            .add(jnp.where(exec_nodes >= 0, 1, 0))
        )
        delta = exec_counts[:, None] * exec_req[None, :] + jnp.where(
            one_hot, driver_req[None, :], 0
        )
        new_avail = jnp.where(admitted, avail - delta.astype(avail.dtype), avail)

        # Strict FIFO: a non-skippable valid failure blocks the rest
        # (resource.go:241-249).
        blocked = blocked | (valid & ~packed & ~skippable)

        out_driver = jnp.where(admitted, driver_node, -1).astype(jnp.int32)
        out_execs = jnp.where(admitted, exec_nodes, -1).astype(jnp.int32)
        if segmented:
            base = jnp.where(
                admitted & commit, base - delta.astype(base.dtype), base
            )
            new_carry = (base, new_avail, blocked, orders)
        else:
            new_carry = (new_avail, blocked)
        return new_carry, (out_driver, out_execs, admitted, packed)

    xs = (
        apps.driver_req,
        apps.exec_req,
        apps.exec_count,
        apps.app_valid,
        apps.skippable,
    )
    if segmented:
        xs = xs + (apps.commit, apps.reset)
        init = (
            cluster.available,
            cluster.available,
            jnp.bool_(False),
            _orders_placeholder(),
        )
    else:
        init = (cluster.available, jnp.bool_(False))
    final_carry, (drivers, execs, admitted, packed) = jax.lax.scan(
        step,
        init,
        xs + extra,
        # The step body is tiny relative to loop-trip overhead at 10k nodes
        # (~100 us/step, overhead-bound); unroll=2 lets XLA fuse step pairs
        # for a measurably lower window service time on TPU v5e. Higher
        # unrolls regress, and so does unrolling under vmap (grouped_fifo_pack
        # passes unroll=1). Results are unchanged — unrolling only
        # restructures the loop.
        unroll=unroll,
    )
    avail_after = final_carry[0]
    return BatchedPacking(
        driver_node=drivers,
        executor_nodes=execs,
        admitted=admitted,
        packed=packed,
        available_after=avail_after,
    )


@partial(
    jax.jit,
    static_argnames=("fill", "emax", "num_zones", "unroll"),
    donate_argnums=(0,),
)
def batched_fifo_pack_carry(
    available,
    statics: tuple,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    unroll: int = 2,
) -> BatchedPacking:
    """`batched_fifo_pack` with the base-capacity carry split out and
    DONATED: `available` is consumed and `available_after` reuses its
    buffer in place instead of copy-on-write, so a caller threading the
    committed base across back-to-back windows (the pipelined serving
    engine, the bench's window chains) never pays an [N, 3] copy per
    window. `statics` is `models.cluster.cluster_statics(cluster)` — the
    resident, never-donated fields. The input availability is DEAD after
    the call (jax marks it deleted); callers must thread
    `available_after` forward, never the input."""
    from spark_scheduler_tpu.models.cluster import cluster_from_statics

    return batched_fifo_pack(
        cluster_from_statics(available, statics),
        apps,
        fill=fill,
        emax=emax,
        num_zones=num_zones,
        unroll=unroll,
    )


def make_app_batch(
    driver_reqs,  # [B,3] array-like
    exec_reqs,  # [B,3] array-like
    exec_counts,  # [B] array-like
    *,
    pad_to: int | None = None,
    skippable=None,
    driver_cand=None,  # [B,N] bool — per-app kube candidate masks
    domain=None,  # [B,N] bool — per-app node-affinity domains
    commit=None,  # [B] bool — window mode: request rows (persist into base)
    reset=None,  # [B] bool — window mode: segment-start rows
) -> AppBatch:
    """Host helper: pad a queue to a bucketed batch size. Padding rows get
    all-False masks (they are already app_valid=False)."""
    import numpy as np

    driver_reqs = np.asarray(driver_reqs, np.int32)
    exec_reqs = np.asarray(exec_reqs, np.int32)
    exec_counts = np.asarray(exec_counts, np.int32)
    b = driver_reqs.shape[0]
    if skippable is None:
        skippable = np.zeros(b, bool)
    else:
        skippable = np.asarray(skippable, bool)
    pad = max(pad_to or b, b)
    valid = np.zeros(pad, bool)
    valid[:b] = True

    def _pad_mask(m):
        if m is None:
            return None
        m = np.asarray(m, bool)
        return np.pad(m, ((0, pad - b), (0, 0)))

    def _pad_vec(v, fill=0, dtype=None):
        if v is None:
            return None
        v = np.asarray(v, dtype)
        return np.pad(v, (0, pad - b), constant_values=fill)

    window = commit is not None or reset is not None
    if window and (commit is None or reset is None):
        # Partial window args would silently mis-default (a commit default of
        # True on hypothetical rows would double-subtract them) — refuse.
        raise ValueError("window mode requires commit AND reset together")
    return AppBatch(
        driver_req=np.pad(driver_reqs, ((0, pad - b), (0, 0))),
        exec_req=np.pad(exec_reqs, ((0, pad - b), (0, 0))),
        exec_count=np.pad(exec_counts, (0, pad - b)),
        app_valid=valid,
        skippable=np.pad(skippable, (0, pad - b)),
        driver_cand=_pad_mask(driver_cand),
        domain=_pad_mask(domain),
        commit=_pad_vec(commit, fill=False, dtype=bool),
        reset=_pad_vec(reset, fill=False, dtype=bool),
    )


def fuse_app_batches(batches, *, pad_to: int | None = None) -> AppBatch:
    """Concatenate K segmented WINDOW batches into ONE fused segmented
    batch — the ops-layer contract of the fused multi-window dispatch
    engine (core/solver.py pack_windows_dispatch).

    The fused scan's decisions are IDENTICAL to running the K batches
    sequentially with `available_after` threaded between them: a window
    boundary is just a segment boundary (the next window's first row has
    reset=True, rewinding working availability to the committed base the
    previous window left), FIFO blocking is already segment-local, and
    priority orders are already re-sorted per segment. Each input batch's
    padding rows (app_valid=False) are stripped before concatenation and
    the fused batch re-pads once at the end, so fused row count is the sum
    of REAL rows, not of padded buckets.

    Every batch must be segmented (commit/reset set) and share the node
    axis; per-row masks are synthesized all-true for batches that carried
    none when any other batch carries them (matching the kernel's own
    synthesis, so decisions cannot shift)."""
    import numpy as np

    if not batches:
        raise ValueError("fuse_app_batches requires at least one batch")
    n = None
    for b in batches:
        if b.commit is None or b.reset is None:
            raise ValueError(
                "fuse_app_batches requires segmented window batches"
            )
        for m in (b.driver_cand, b.domain):
            if m is not None:
                m_n = np.asarray(m).shape[1]
                if n is None:
                    n = m_n
                elif n != m_n:
                    raise ValueError("node axes differ across batches")
    any_cand = any(b.driver_cand is not None for b in batches)
    any_dom = any(b.domain is not None for b in batches)

    def _real(b, field, synth_mask=False):
        arr = getattr(b, field)
        sel = np.flatnonzero(np.asarray(b.app_valid))
        if arr is None:
            if not synth_mask:
                return None
            return np.ones((len(sel), n), bool)
        return np.asarray(arr)[sel]

    cat = lambda field, synth=False: np.concatenate(
        [_real(b, field, synth) for b in batches]
    )
    return make_app_batch(
        cat("driver_req"),
        cat("exec_req"),
        cat("exec_count"),
        pad_to=pad_to,
        skippable=cat("skippable"),
        driver_cand=cat("driver_cand", any_cand) if any_cand else None,
        domain=cat("domain", any_dom) if any_dom else None,
        commit=cat("commit"),
        reset=cat("reset"),
    )


def pad_app_batch(apps: AppBatch, pad_to: int) -> AppBatch:
    """Re-pad a host-side batch to a LARGER row bucket (fleet stacking:
    windows grouped into one dispatch must share the app axis, so every
    member grows to the group max). New rows are pure padding
    (app_valid=False, all-zero/False) — identical to what make_app_batch
    would have emitted at the bigger bucket, so decisions cannot shift
    (pad-invariance pinned by tests/test_replay_sweep.py)."""
    import numpy as np

    b = np.asarray(apps.driver_req).shape[0]
    if pad_to <= b:
        return apps
    grow = pad_to - b

    def _rows(a):
        if a is None:
            return None
        a = np.asarray(a)
        return np.pad(a, [(0, grow)] + [(0, 0)] * (a.ndim - 1))

    return AppBatch(*(_rows(getattr(apps, f)) for f in AppBatch._fields))


def stack_app_batches(batches) -> AppBatch:
    """Stack M same-shape batches along a new leading arm axis ([M, B, ...])
    for `bucket_stacked_fifo_pack`. Optional masks must be uniformly set or
    uniformly absent across the group — the fleet coordinator groups serving
    windows, which always carry all fields, so a mix means a caller bug."""
    import numpy as np

    def _stack(field):
        vals = [getattr(b, field) for b in batches]
        if all(v is None for v in vals):
            return None
        if any(v is None for v in vals):
            raise ValueError(
                f"cannot stack batches with mixed None-ness in {field!r}"
            )
        return np.stack([np.asarray(v) for v in vals])

    return AppBatch(*(_stack(f) for f in AppBatch._fields))


@partial(
    jax.jit,
    static_argnames=("fills", "emax", "num_zones"),
    donate_argnums=(0,),
)
def arm_stacked_fifo_pack(
    avail_stack,  # [M, N, 3] i32 — per-arm committed availability, DONATED
    statics: tuple,
    apps: AppBatch,
    *,
    fills: tuple,  # per-arm fill strategy, EQUAL FILLS ADJACENT
    emax: int,
    num_zones: int,
):
    """One window solved for M config arms in ONE device dispatch — the
    replay sweep's stacked-arm kernel (ISSUE 18). The window's app batch,
    statics, and masks are arm-invariant (node events are inputs, not
    decisions); only the availability carry differs per arm, so it stacks
    as `[M, N, 3]` and each arm's solve is a vmap lane over the shared
    segmented scan.

    `fills` selects the binpack strategy PER ARM. Strategy is a static
    (compile-time) property of the scan body, so arm selection does NOT
    ride `lax.switch`: under vmap every switch branch executes select-ized
    — measured 39.5 s/window vs 1.2 s for the vmapped single-fill program
    at 10k nodes on the 2-core CPU rig, a 30x pathology. Instead the
    caller sorts arms so equal fills are adjacent and this kernel vmaps
    each same-fill sub-stack with its own statically-specialized body,
    concatenating along the arm axis — still one jitted program, one
    dispatch, one fetch.

    Returns `(blob, avail_after)`: `blob` is `[M, B, 3+emax]` in the
    `_window_blob` column layout (driver, admitted, packed, exec slots),
    `avail_after` is the `[M, N, 3]` per-arm committed base. Decisions are
    bit-identical per arm to a sequential `batched_fifo_pack` under that
    arm's fill: the kernel is integer-only and vmap/unroll changes only
    restructure the loop (pad-invariance + arm-equivalence pinned by
    tests/test_replay_sweep.py).
    """
    from spark_scheduler_tpu.models.cluster import cluster_from_statics

    if len(fills) != avail_stack.shape[0]:
        raise ValueError(
            f"fills ({len(fills)}) must match the arm axis "
            f"({avail_stack.shape[0]})"
        )

    def solve_one(avail, *, fill):
        out = batched_fifo_pack(
            cluster_from_statics(avail, statics), apps,
            fill=fill, emax=emax, num_zones=num_zones, unroll=1,
        )
        blob = jnp.concatenate(
            [
                out.driver_node[:, None],
                out.admitted[:, None].astype(jnp.int32),
                out.packed[:, None].astype(jnp.int32),
                out.executor_nodes,
            ],
            axis=1,
        )
        return blob, out.available_after

    blobs, avails = [], []
    i = 0
    while i < len(fills):
        j = i
        while j < len(fills) and fills[j] == fills[i]:
            j += 1
        if j > i + 1:
            blob, avail = jax.vmap(partial(solve_one, fill=fills[i]))(
                avail_stack[i:j]
            )
        else:
            blob, avail = solve_one(avail_stack[i], fill=fills[i])
            blob, avail = blob[None], avail[None]
        blobs.append(blob)
        avails.append(avail)
        i = j
    if len(blobs) == 1:
        return blobs[0], avails[0]
    return jnp.concatenate(blobs), jnp.concatenate(avails)


@partial(
    jax.jit,
    static_argnames=("fills", "emax", "num_zones"),
    donate_argnums=(0,),
)
def bucket_stacked_fifo_pack(
    avail_stack,  # [M, N, 3] i32 — per-cluster availability, DONATED
    statics_stack: tuple,  # cluster_statics stacked per field: each [M, N]
    apps_stack: AppBatch,  # fields stacked [M, B, ...]
    *,
    fills: tuple,  # per-member fill strategy, EQUAL FILLS ADJACENT
    emax: int,
    num_zones: int,
):
    """M *different clusters'* windows solved in ONE device dispatch — the
    fleet-serving generalization of `arm_stacked_fifo_pack` (ISSUE 20).
    The sweep stacked M availability carries against ONE shared cluster and
    app batch (arms differ only in config); fleet clusters differ in
    everything, so here statics AND apps stack too and the vmap maps over
    all three. Members need only agree on the padded shapes — `(bucket_n,
    emax, num_zones)` plus the app row bucket, which the coordinator
    equalizes via `pad_app_batch` — not on content: each lane sees its own
    cluster's statics, masks, and availability, so per-member decisions are
    bit-identical to that cluster's standalone `batched_fifo_pack` solve
    (the same vmap-identity PR 18 pinned, extended over the new mapped
    axes).

    `fills` is per member with equal fills adjacent; as in the arm kernel,
    strategy stays a static property of each sub-stack's scan body — never
    `lax.switch`, which select-izes every branch under vmap (30x pathology,
    see arm_stacked_fifo_pack).

    The jitted name carries the `stacked_fifo_pack` donation marker
    (server/config.py JAX_CACHE_DONATION_MARKERS): donated executables
    must not be served from the persistent compile cache.

    Returns `(blob, avail_after)`: `blob` `[M, B, 3+emax]` in the
    `_window_blob` column layout, `avail_after` `[M, N, 3]`.
    """
    from spark_scheduler_tpu.models.cluster import cluster_from_statics

    if len(fills) != avail_stack.shape[0]:
        raise ValueError(
            f"fills ({len(fills)}) must match the member axis "
            f"({avail_stack.shape[0]})"
        )

    def solve_one(avail, statics, apps, *, fill):
        out = batched_fifo_pack(
            cluster_from_statics(avail, statics), apps,
            fill=fill, emax=emax, num_zones=num_zones, unroll=1,
        )
        blob = jnp.concatenate(
            [
                out.driver_node[:, None],
                out.admitted[:, None].astype(jnp.int32),
                out.packed[:, None].astype(jnp.int32),
                out.executor_nodes,
            ],
            axis=1,
        )
        return blob, out.available_after

    _slice = lambda tree, i, j: jax.tree_util.tree_map(
        lambda x: x[i:j], tree
    )
    _pick = lambda tree, i: jax.tree_util.tree_map(lambda x: x[i], tree)

    blobs, avails = [], []
    i = 0
    while i < len(fills):
        j = i
        while j < len(fills) and fills[j] == fills[i]:
            j += 1
        if j > i + 1:
            blob, avail = jax.vmap(partial(solve_one, fill=fills[i]))(
                avail_stack[i:j],
                _slice(statics_stack, i, j),
                _slice(apps_stack, i, j),
            )
        else:
            blob, avail = solve_one(
                avail_stack[i],
                _pick(statics_stack, i),
                _pick(apps_stack, i),
                fill=fills[i],
            )
            blob, avail = blob[None], avail[None]
        blobs.append(blob)
        avails.append(avail)
        i = j
    if len(blobs) == 1:
        return blobs[0], avails[0]
    return jnp.concatenate(blobs), jnp.concatenate(avails)
