"""Packing-efficiency kernel (binpack/efficiency.go:23-156).

Per-node efficiency = (already-reserved + newly-reserved) / schedulable per
dim; GPU only counts on nodes with schedulable GPU. The average over a
packing's entries (driver + one entry PER executor — duplicate nodes count
once per occurrence, matching chooseBestResult, single_az.go:84-97) scores
zones in the single-AZ packers and feeds the binpack metrics.

Deviation from the reference, recorded deliberately: the Go code divides
`resource.Quantity.Value()`s, which ROUNDS sub-unit quantities (500m CPU ->
1); we divide exact fixed-point units in float32, which is strictly more
accurate. Tie behavior between zones can differ only when the reference's
rounding itself changed the winner.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import ClusterTensors
from spark_scheduler_tpu.models.resources import CPU_DIM, GPU_DIM, MEM_DIM


class AvgEfficiency(NamedTuple):
    cpu: jnp.ndarray
    memory: jnp.ndarray
    gpu: jnp.ndarray
    max: jnp.ndarray  # the field zone selection compares (efficiency.go:36-39)


def new_reservation_tensor(
    num_nodes: int,
    driver_node: jnp.ndarray,
    executor_nodes: jnp.ndarray,
    driver_req: jnp.ndarray,
    exec_req: jnp.ndarray,
) -> jnp.ndarray:
    """[N,3] scatter-add of a packing's tentative reservations."""
    out = jnp.zeros((num_nodes, 3), jnp.int32)
    d_ok = driver_node >= 0
    out = out.at[jnp.clip(driver_node, 0)].add(
        jnp.where(d_ok, driver_req, 0).astype(jnp.int32)
    )
    e_ok = executor_nodes >= 0
    out = out.at[jnp.clip(executor_nodes, 0)].add(
        jnp.where(e_ok[:, None], exec_req[None, :], 0).astype(jnp.int32)
    )
    return out


def avg_packing_efficiency_np(
    schedulable,
    available,
    driver_node: int,
    executor_nodes,
    driver_req,
    exec_req,
) -> AvgEfficiency:
    """Pure-numpy twin of `avg_packing_efficiency` for HOST-side reporting
    (serving path, resource.go:347-350). The jnp version runs ~30 eager
    device dispatches when called outside jit — on a tunneled TPU that is
    ~30 RPC round-trips per request. Parity with the jnp kernel is pinned
    by tests/test_packing_golden.py::test_efficiency_np_parity.

    O(entries), not O(nodes): the means only read the driver/executor
    entry rows, so everything is computed on the <= emax+1 gathered rows
    (full [N, 3] temporaries per admitted request were a measured serving
    hotspot at 10k nodes)."""
    import numpy as np

    executor_nodes = np.asarray(executor_nodes)
    entries = np.concatenate([[driver_node], executor_nodes])
    valid = entries >= 0
    if not valid.any():
        return AvgEfficiency(cpu=0.0, memory=0.0, gpu=0.0, max=0.0)
    schedulable = np.asarray(schedulable)
    available = np.asarray(available)
    dreq = np.asarray(driver_req)
    ereq = np.asarray(exec_req)
    idx = np.clip(entries, 0, None).astype(np.int64)
    uniq, pos = np.unique(idx, return_inverse=True)  # entry -> uniq row
    sched_u = schedulable[uniq]
    new_res_u = np.zeros_like(sched_u)
    if driver_node >= 0:
        new_res_u[pos[0]] += dreq
    ex_valid = valid.copy()
    ex_valid[0] = False
    if ex_valid.any():
        np.add.at(new_res_u, pos[ex_valid], ereq)
    reserved_u = (sched_u - available[uniq]) + new_res_u
    denom_u = np.where(sched_u == 0, 1, sched_u).astype(np.float32)
    eff_u = reserved_u.astype(np.float32) / denom_u
    gpu_node_u = sched_u[:, GPU_DIM] != 0
    eff_gpu_u = np.where(gpu_node_u, eff_u[:, GPU_DIM], 0.0)
    node_max_u = np.maximum(
        eff_gpu_u, np.maximum(eff_u[:, CPU_DIM], eff_u[:, MEM_DIM])
    )

    cnt = float(valid.sum())
    cpu_mean = float(np.where(valid, eff_u[pos, CPU_DIM], 0.0).sum() / cnt)
    mem_mean = float(np.where(valid, eff_u[pos, MEM_DIM], 0.0).sum() / cnt)
    gpu_valid = valid & gpu_node_u[pos]
    gpu_cnt = int(gpu_valid.sum())
    gpu_mean = (
        1.0  # no GPU nodes among entries => 1 (efficiency.go:139-144)
        if gpu_cnt == 0
        else float(np.where(gpu_valid, eff_gpu_u[pos], 0.0).sum() / gpu_cnt)
    )
    max_mean = float(np.where(valid, node_max_u[pos], 0.0).sum() / cnt)
    return AvgEfficiency(cpu=cpu_mean, memory=mem_mean, gpu=gpu_mean, max=max_mean)


def avg_packing_efficiency(
    cluster: ClusterTensors,
    driver_node: jnp.ndarray,
    executor_nodes: jnp.ndarray,
    driver_req: jnp.ndarray,
    exec_req: jnp.ndarray,
    *,
    include_executors_in_reserved: bool = True,
) -> AvgEfficiency:
    """`include_executors_in_reserved=False` reproduces a reference quirk:
    `minimalFragmentation` never writes executors into reservedResources
    (minimal_fragmentation.go:68-98, unlike pack_tightly.go:45-49 and
    distribute_evenly.go:58-60), so packing efficiencies — and therefore
    single-AZ zone selection — only see the driver's tentative reservation
    for that strategy. The ENTRIES averaged over are still driver + one per
    executor occurrence (single_az.go:84-97) in both modes."""
    return avg_packing_efficiency_arrays(
        cluster.schedulable,
        cluster.available,
        driver_node,
        executor_nodes,
        driver_req,
        exec_req,
        include_executors_in_reserved=include_executors_in_reserved,
    )


def avg_packing_efficiency_arrays(
    schedulable: jnp.ndarray,  # [N,3] i32
    available: jnp.ndarray,  # [N,3] i32 — CURRENT availability
    driver_node: jnp.ndarray,
    executor_nodes: jnp.ndarray,
    driver_req: jnp.ndarray,
    exec_req: jnp.ndarray,
    *,
    include_executors_in_reserved: bool = True,
) -> AvgEfficiency:
    """Array-based core of `avg_packing_efficiency`: callers that thread a
    mutated availability (the batched FIFO scan admits apps between zone
    scorings) pass it directly instead of rebuilding ClusterTensors."""
    new_res = new_reservation_tensor(
        schedulable.shape[0],
        driver_node,
        jnp.where(include_executors_in_reserved, executor_nodes, -1),
        driver_req,
        exec_req,
    )
    # schedulable - available = current reservation usage (efficiency.go:85-92).
    reserved_total = (schedulable - available) + new_res
    denom = jnp.where(schedulable == 0, 1, schedulable).astype(jnp.float32)
    eff = reserved_total.astype(jnp.float32) / denom  # [N,3]
    gpu_node = schedulable[:, GPU_DIM] != 0
    eff_gpu = jnp.where(gpu_node, eff[:, GPU_DIM], 0.0)
    node_max = jnp.maximum(eff_gpu, jnp.maximum(eff[:, CPU_DIM], eff[:, MEM_DIM]))

    entries = jnp.concatenate([driver_node[None], executor_nodes])
    valid = entries >= 0
    idx = jnp.clip(entries, 0)
    cnt = jnp.maximum(jnp.sum(valid), 1).astype(jnp.float32)

    cpu_mean = jnp.sum(jnp.where(valid, eff[idx, CPU_DIM], 0.0)) / cnt
    mem_mean = jnp.sum(jnp.where(valid, eff[idx, MEM_DIM], 0.0)) / cnt
    gpu_valid = valid & gpu_node[idx]
    gpu_cnt = jnp.sum(gpu_valid)
    gpu_mean = jnp.where(
        gpu_cnt == 0,
        1.0,  # no GPU nodes among entries => 1 (efficiency.go:139-144)
        jnp.sum(jnp.where(gpu_valid, eff_gpu[idx], 0.0))
        / jnp.maximum(gpu_cnt, 1).astype(jnp.float32),
    )
    max_mean = jnp.sum(jnp.where(valid, node_max[idx], 0.0)) / cnt
    # Empty packing => worst efficiency (efficiency.go:44-52).
    none = jnp.sum(valid) == 0
    zero = jnp.float32(0.0)
    return AvgEfficiency(
        cpu=jnp.where(none, zero, cpu_mean),
        memory=jnp.where(none, zero, mem_mean),
        gpu=jnp.where(none, zero, gpu_mean),
        max=jnp.where(none, zero, max_mean),
    )
