"""The FIFO gang-admission queue as ONE Pallas TPU kernel.

`ops/batched.batched_fifo_pack` expresses queue admission as a `lax.scan`
whose per-step body is a handful of O(N) vector ops. At 10k nodes the step
body is ~microseconds of VPU work, so the scan is dominated by loop-trip
overhead (HBM round-trips for the carried availability between steps and
XLA's per-iteration scheduling). This module removes that overhead the
TPU-native way: the ENTIRE queue runs inside one Mosaic kernel with

  - the availability tensor resident in VMEM scratch across grid steps
    (TPU grid iterations execute sequentially on a core, so scratch carries
    the scan state chip-side — it never round-trips to HBM);
  - per-app parameters (requests, counts, flags) delivered via scalar
    prefetch into SMEM;
  - the executor fills re-derived as iterative masked-argmin placement
    (`emax` rounds of "first open position") instead of
    cumsum + searchsorted, because a short static loop of VPU reductions
    beats a 10k-lane prefix scan and Mosaic has no native searchsorted.

Semantics are bit-identical to `batched_fifo_pack` in queue mode (shared
eligibility, priority orders fixed from the starting availability — the
`fitEarlierDrivers` semantics of resource.go:221-258): the golden-parity
suite (tests/test_pallas_fifo.py) and the on-silicon smoke
(hack/tpu_parity_smoke.py) compare the two paths decision-for-decision.

Fill derivations (reference loops -> argmin keys):

  tightly-pack (pack_tightly.go:45-61): fill each node before moving on
      == every slot goes to the FIRST position with remaining capacity
      -> key = position.
  distribute-evenly (distribute_evenly.go:49-71): one executor per open
      node per round, rounds in position order
      == every slot goes to the open position with lexicographically
      smallest (slots already placed there, position)
      -> key = placed * Npad + position  (placed <= emax, so no overflow).
  minimal-fragmentation (minimal_fragmentation.go:68-98): smallest single
      node fitting the whole gang, else consume nodes in (capacity desc,
      position asc) order while the running clamped total stays <= count,
      remainder on the smallest not-consumed node that fits it
      -> <= emax consume rounds of masked max + two masked-min reductions.

This kernel is the queue-mode hot path (the north-star 10k-node x 1k-app
batched admission), covering all six strategies — the single-AZ wrappers
run their per-zone fill + efficiency-scored zone pick in-kernel. Segmented
serving windows run on their own Mosaic path (ops/pallas_window, sharing
this module's fill/driver closures); per-app-masked batches keep the XLA
scan.

Documented deviation (single-AZ zone scoring): the zone efficiency is a
float32 mean, and this kernel sums it as a weighted tile reduction while
the XLA scan sums gathered per-entry values — different summation orders
can differ in the last ulp, so a cross-zone tie closer than ~1 ulp may
break differently between the two paths (same class of deviation as the
module-documented Go-rounding difference in ops/efficiency.py; bit-exact
float reductions across different programs are not guaranteeable). The
parity suites use fixed seeds and are deterministic per jax build.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops.batched import (
    AppBatch,
    BatchedPacking,
    queue_mode_orders,
)

PALLAS_FILLS = ("tightly-pack", "distribute-evenly", "minimal-fragmentation")

# Single-AZ strategies the queue kernel serves (VERDICT r3 #4):
# strategy -> (inner fill, az-aware plain fallback, executors counted in
# the zone-efficiency reservation — the minimalFragmentation quirk,
# ops/efficiency.py avg_packing_efficiency docstring).
PALLAS_SINGLE_AZ = {
    "single-az-tightly-pack": ("tightly-pack", False, True),
    "single-az-minimal-fragmentation": ("minimal-fragmentation", False, False),
    "az-aware-tightly-pack": ("tightly-pack", True, True),
}

_LANES = 128  # int32 lane width
_SUBLANES = 8  # VPU sublanes
# Above this node count the position axis folds row-major into an
# [8, Np/8] tile so vector ops drive all 8 VPU sublanes (measured ~15%
# faster at 10k+ nodes); below it the flat [1, Np] row wins on fixed
# overhead (measured ~35% faster at 1k nodes on a v5e).
_SUBLANE_FOLD_MIN_NODES = 4096


def _layout_rows(n: int) -> int:
    return _SUBLANES if n >= _SUBLANE_FOLD_MIN_NODES else 1


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def pallas_eligible(apps: "AppBatch", fill: str) -> bool:
    """THE single definition of what the Pallas queue kernel supports:
    plain queue mode (no per-app masks, no segmented windows) with any of
    the six strategies — the three plain fills, and since r4 the
    single-AZ wrappers (per-zone fill + efficiency-scored zone pick
    in-kernel). Shared by every routing site so eligibility cannot drift
    when the kernel learns new shapes. (Segmented serving windows have
    their own Mosaic path, ops/pallas_window.)"""
    return (
        (fill in PALLAS_FILLS or fill in PALLAS_SINGLE_AZ)
        and apps.commit is None
        and apps.driver_cand is None
        and apps.domain is None
    )


def make_driver_selector(count, cap_e, cap_wd, fit_d, elig_d, drank):
    """Shared driver-selection closure for the Mosaic kernels (queue AND
    segmented-window paths — ONE implementation so the two cannot drift).
    Picks the best-ranked feasible driver via the feasibility identity
    (ops/packing.py pack_one_app): reserving the driver on node i only
    changes node i's executor capacity."""
    INF = INT32_INF

    def select_driver(zone_mask):
        cap_e_m = jnp.where(zone_mask, cap_e, 0)
        cap_wd_m = jnp.where(zone_mask, cap_wd, 0)
        cap_e_c = jnp.minimum(cap_e_m, count)
        cap_wd_c = jnp.minimum(cap_wd_m, count)
        total_base = jnp.sum(cap_e_c)
        total_if = total_base - cap_e_c + cap_wd_c
        feasible = elig_d & zone_mask & fit_d & (total_if >= count)
        best_rank = jnp.min(jnp.where(feasible, drank, INF))
        found = best_rank < INF
        # drank is a permutation rank -> at most one position matches.
        is_drv = feasible & (drank == best_rank)
        # Executor capacities with the chosen driver reserved.
        caps_fill = jnp.where(is_drv, cap_wd_m, cap_e_m)
        return found, is_drv, caps_fill

    return select_driver


def make_fill_runner(
    inner_fill, emax, n_pad, shape, count, key, node_val, slot_iota
):
    """Shared executor-fill closure for the Mosaic kernels: `emax` rounds
    of masked-argmin placement, parameterized by the priority KEY tensor —
    `iota` itself for the queue kernel (whose node axis is pre-permuted
    into priority order) and the per-segment executor rank for the window
    kernel. `key` must be a permutation over real positions padded with
    INF; `node_val` holds the output node id per position. ONE
    implementation serves both kernels so fill semantics cannot drift."""
    INF = INT32_INF

    def run_fill(ok, caps_fill, elig_mask):
        execs_row = jnp.full((1, emax), -1, jnp.int32)
        exec_counts = jnp.zeros(shape, jnp.int32)
        if inner_fill == "tightly-pack":
            remaining = caps_fill
            for j in range(emax):
                place = ok & (j < count)
                k_sel = jnp.min(jnp.where(remaining > 0, key, INF))
                hit = (key == k_sel) & (remaining > 0) & place
                node_j = jnp.sum(jnp.where(hit, node_val, 0))
                execs_row = jnp.where(
                    (slot_iota == j) & place, node_j, execs_row
                )
                remaining = remaining - hit
                exec_counts = exec_counts + hit
        elif inner_fill == "distribute-evenly":
            # dkey = placed * Npad + key over open positions; placed never
            # exceeds emax and key < Npad at open positions, so the key
            # stays far below int32 range.
            for j in range(emax):
                place = ok & (j < count)
                open_ = elig_mask & (exec_counts < caps_fill)
                dkey = exec_counts * n_pad + key
                k_min = jnp.min(jnp.where(open_, dkey, INF))
                hit = open_ & (dkey == k_min) & place
                node_j = jnp.sum(jnp.where(hit, node_val, 0))
                execs_row = jnp.where(
                    (slot_iota == j) & place, node_j, execs_row
                )
                exec_counts = exec_counts + hit
        elif inner_fill == "minimal-fragmentation":
            cap_ok = caps_fill > 0
            caps_c = jnp.minimum(caps_fill, count)
            # Branch A: smallest single node fitting the whole gang
            # (minimal_fragmentation.go:68-78): min capacity, then best
            # priority (earliest key) on capacity ties.
            mask_a = cap_ok & (caps_fill >= count)
            exists_a = jnp.any(mask_a)
            min_cap_a = jnp.min(jnp.where(mask_a, caps_fill, INF))
            tie_a = mask_a & (caps_fill == min_cap_a)
            rank_a = jnp.min(jnp.where(tie_a, key, INF))
            sel_a = tie_a & (key == rank_a)
            # Branch B: consume (clamped capacity desc, priority asc) while
            # the running total stays <= count (the maximal prefix of the
            # reference's desc sort), remainder on the smallest
            # not-consumed node with UNCLAMPED capacity >= remainder
            # (minimal_fragmentation.go:80-98).
            use_b = ok & ~exists_a
            consumed = jnp.zeros(shape, jnp.bool_)
            placed_total = jnp.int32(0)
            for _ in range(emax):
                open_b = cap_ok & ~consumed
                c_max = jnp.max(jnp.where(open_b, caps_c, -1))
                tie_k = open_b & (caps_c == c_max)
                rank_k = jnp.min(jnp.where(tie_k, key, INF))
                take = use_b & (c_max > 0) & (placed_total + c_max <= count)
                hit = tie_k & (key == rank_k) & take
                node_k = jnp.sum(jnp.where(hit, node_val, 0))
                in_span = (
                    (slot_iota >= placed_total)
                    & (slot_iota < placed_total + c_max)
                    & take
                )
                execs_row = jnp.where(in_span, node_k, execs_row)
                exec_counts = exec_counts + jnp.where(hit, c_max, 0)
                consumed = consumed | hit
                placed_total = placed_total + jnp.where(take, c_max, 0)
            remainder = count - placed_total
            mask_fin = cap_ok & ~consumed & (caps_fill >= remainder)
            min_cap_f = jnp.min(jnp.where(mask_fin, caps_fill, INF))
            tie_f = mask_fin & (caps_fill == min_cap_f)
            rank_f = jnp.min(jnp.where(tie_f, key, INF))
            sel_f = tie_f & (key == rank_f)
            need_fin = use_b & (remainder > 0)
            fin_take = ok & (exists_a | need_fin)
            # Logical blend, not jnp.where: Mosaic cannot select between
            # two i1 vectors.
            fin_sel = (sel_a & exists_a) | (sel_f & ~exists_a)
            fin_count = jnp.where(exists_a, count, remainder)
            fin_hit = fin_sel & fin_take
            node_fin = jnp.sum(jnp.where(fin_hit, node_val, 0))
            fin_start = jnp.where(exists_a, 0, placed_total)
            in_fin = (
                (slot_iota >= fin_start)
                & (slot_iota < fin_start + fin_count)
                & fin_take
            )
            # Branch A overwrites any branch-B spans (it is exclusive).
            execs_row = jnp.where(
                exists_a & (slot_iota < count) & ok,
                node_fin,
                jnp.where(in_fin, node_fin, execs_row),
            )
            exec_counts = jnp.where(
                exists_a & ok,
                jnp.where(sel_a, count, 0),
                exec_counts + jnp.where(fin_hit, fin_count, 0),
            )
        else:  # pragma: no cover — guarded by the kernel builders
            raise ValueError(f"unsupported fill for pallas: {inner_fill}")
        return execs_row, exec_counts

    return run_fill


def make_gang_solver(
    fill: str,
    *,
    num_zones: int,
    emax: int,
    n_pad: int,
    shape,
    count,
    cap_e,
    cap_wd,
    fit_d,
    elig_e,
    elig_d,
    drank,
    key,
    node_val,
    slot_iota,
    zone,
    sched3,
    avail3,
    dreq3,
    ereq3,
):
    """THE per-gang solve shared by BOTH Mosaic kernels (queue and
    segmented-window): driver selection + executor fill for the plain
    fills, and for the single-AZ wrappers the per-zone pack,
    efficiency-scored strictly-greater zone pick, and az-aware plain
    fallback (single_az.go:23-97 / az_aware_pack_tightly.go:27-38) —
    ONE implementation so the two kernels cannot drift.

    `key`/`node_val` parameterize the priority walk exactly as
    make_fill_runner documents (position iota for the pre-permuted queue
    kernel, the per-segment executor rank for the window kernel);
    `zone`/`sched3`/`avail3` feed the zone loop and its efficiency scoring
    (`sched3`/`avail3`/`dreq3`/`ereq3` are per-dim reads hoisted by the
    caller — nothing here mutates between zones).

    Returns ``solve() -> (ok, is_drv, execs_row, exec_counts,
    driver_node)``."""
    INF = INT32_INF
    single_az = fill in PALLAS_SINGLE_AZ
    if single_az:
        inner_fill, az_fallback, include_exec_in_reserved = (
            PALLAS_SINGLE_AZ[fill]
        )
    else:
        inner_fill, az_fallback, include_exec_in_reserved = fill, False, True

    select_driver = make_driver_selector(
        count, cap_e, cap_wd, fit_d, elig_d, drank
    )
    run_fill = make_fill_runner(
        inner_fill, emax, n_pad, shape, count, key, node_val, slot_iota
    )

    def solve():
        if not single_az:
            found, is_drv, caps_fill = select_driver(
                jnp.ones(shape, jnp.bool_)
            )
            ok = found  # the feasibility identity guarantees the fill
            execs_row, exec_counts = run_fill(ok, caps_fill, elig_e)
        else:
            # --- per-zone pack + strictly-greater efficiency selection
            # (single_az.go:23-97). Zone "first appearance" rank in driver
            # priority order breaks efficiency ties (single_az.go:58-73);
            # zones with no executor-eligible node are skipped
            # (single_az.go:40-43).
            best_eff = jnp.float32(-1.0)
            best_first = jnp.int32(INF)
            any_valid = jnp.bool_(False)
            is_drv = jnp.zeros(shape, jnp.bool_)
            execs_row = jnp.full((1, emax), -1, jnp.int32)
            exec_counts = jnp.zeros(shape, jnp.int32)
            for z in range(num_zones):
                zmask = zone == z
                zone_first = jnp.min(
                    jnp.where(elig_d & zmask, drank, INF)
                )
                zone_has_exec = jnp.any(elig_e & zmask)
                found_z, is_drv_z, caps_z = select_driver(zmask)
                execs_z, counts_z = run_fill(
                    found_z, caps_z, elig_e & zmask
                )
                # Zone score: mean over ENTRIES (driver + one per executor
                # occurrence) of per-node max dim efficiency with the
                # tentative reservation applied (efficiency.go:85-144).
                w = counts_z + is_drv_z
                eff_cpu = jnp.zeros(shape, jnp.float32)
                eff_mem = jnp.zeros(shape, jnp.float32)
                eff_gpu = jnp.zeros(shape, jnp.float32)
                for d in range(3):
                    sched_d = sched3[d]
                    new_res = jnp.where(is_drv_z, dreq3[d], 0)
                    if include_exec_in_reserved:
                        new_res = new_res + counts_z * ereq3[d]
                    reserved = (sched_d - avail3[d]) + new_res
                    denom = jnp.maximum(sched_d, 1).astype(jnp.float32)
                    eff_d = reserved.astype(jnp.float32) / denom
                    if d == 0:
                        eff_cpu = eff_d
                    elif d == 1:
                        eff_mem = eff_d
                    else:
                        gpu_node = sched_d != 0
                        eff_gpu = jnp.where(gpu_node, eff_d, 0.0)
                node_max = jnp.maximum(
                    eff_gpu, jnp.maximum(eff_cpu, eff_mem)
                )
                entries = (count + 1).astype(jnp.float32)
                eff_z = (
                    jnp.sum(node_max * w.astype(jnp.float32)) / entries
                )
                valid_z = found_z & (zone_first < INF) & zone_has_exec
                better = valid_z & (
                    (eff_z > best_eff)
                    | ((eff_z == best_eff) & (zone_first < best_first))
                )
                best_eff = jnp.where(better, eff_z, best_eff)
                best_first = jnp.where(better, zone_first, best_first)
                any_valid = any_valid | valid_z
                is_drv = (is_drv_z & better) | (is_drv & ~better)
                execs_row = jnp.where(better, execs_z, execs_row)
                exec_counts = jnp.where(better, counts_z, exec_counts)
            # chooseBestResult starts from WorstAvgPackingEfficiency
            # (Max=0.0) and replaces only on strictly-greater, so a zone
            # whose best efficiency is exactly 0.0 is rejected entirely
            # (single_az.go:84-97).
            ok = any_valid & (best_eff > 0.0)
            if az_fallback:
                # az-aware: plain pack when no single zone fits
                # (az_aware_pack_tightly.go:27-38).
                found_p, is_drv_p, caps_p = select_driver(
                    jnp.ones(shape, jnp.bool_)
                )
                execs_p, counts_p = run_fill(found_p, caps_p, elig_e)
                use_p = ~ok & found_p
                is_drv = (is_drv_p & use_p) | (is_drv & ~use_p)
                execs_row = jnp.where(use_p, execs_p, execs_row)
                exec_counts = jnp.where(use_p, counts_p, exec_counts)
                ok = ok | found_p
            is_drv = is_drv & ok
            execs_row = jnp.where(ok, execs_row, -1)
            exec_counts = jnp.where(ok, exec_counts, 0)
        driver_node = jnp.sum(jnp.where(is_drv, node_val, 0))
        return ok, is_drv, execs_row, exec_counts, driver_node

    return solve


def _make_kernel(
    fill: str,
    emax: int,
    n_pad: int,
    n_apps: int,
    rows: int,
    *,
    num_zones: int = 0,
):
    """Build the kernel body. Everything static (fill, emax, padding,
    layout) is closed over; per-app scalars arrive via prefetch refs.

    The position axis is laid out 2D row-major — position p lives at
    [p // cols, p % cols] of a [rows, cols] tile (`_layout_rows`).

    `fill` may be a plain fill OR a PALLAS_SINGLE_AZ strategy: the
    single-AZ path runs the inner fill once per zone (restricted to the
    zone's positions), scores each feasible zone's average packing
    efficiency against the live availability, and keeps the
    strictly-greatest (ties to the zone appearing first in driver
    priority order) — single_az.go:23-97 semantics, entirely in-kernel
    (make_gang_solver, shared with the segmented-window kernel)."""

    INF = INT32_INF
    cols = n_pad // rows
    shape = (rows, cols)

    def kernel(
        dreq_ref,  # SMEM [B, 3] i32 — driver request
        ereq_ref,  # SMEM [B, 3] i32 — executor request
        cnt_ref,  # SMEM [B] i32 — gang size
        valid_ref,  # SMEM [B] i32 — app_valid
        skip_ref,  # SMEM [B] i32 — skippable
        avail_ref,  # VMEM [3, rows, cols] i32 — starting availability (position order)
        elig_e_ref,  # VMEM [rows, cols] i32 — executor eligibility
        elig_d_ref,  # VMEM [rows, cols] i32 — driver eligibility
        drank_ref,  # VMEM [rows, cols] i32 — driver-priority rank per position
        nodeid_ref,  # VMEM [rows, cols] i32 — original node index per position
        zone_ref,  # VMEM [rows, cols] i32 — zone id per position (single-AZ)
        sched_ref,  # VMEM [3, rows, cols] i32 — schedulable (single-AZ scoring)
        meta_out,  # VMEM [B, 4] i32 — (driver_node, admitted, packed, 0)
        execs_out,  # VMEM [B, emax] i32
        avail_out,  # VMEM [3, rows, cols] i32 — availability after all admits
        avail_scr,  # VMEM [3, rows, cols] i32 scratch — the scan carry
        blocked_scr,  # SMEM [1] i32 scratch — strict-FIFO blocked flag
    ):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            avail_scr[:] = avail_ref[:]
            blocked_scr[0] = 0

        iota = (
            jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
            + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        )
        elig_e = elig_e_ref[:] != 0
        elig_d = elig_d_ref[:] != 0
        drank = drank_ref[:]
        node_id = nodeid_ref[:]

        raw_count = cnt_ref[b]
        too_big = raw_count > emax
        count = jnp.minimum(raw_count, emax)
        valid = valid_ref[b] != 0
        skippable = skip_ref[b] != 0
        blocked_in = blocked_scr[0] != 0

        # --- node capacities (ops/capacity.py node_capacities, exact
        # integer semantics: per dim 0 if reserved > avail, INF if req == 0,
        # else floor((avail-reserved)/req); node cap = max(min over dims, 0))
        cap_e = jnp.full(shape, INF, jnp.int32)  # no reservation
        cap_wd = jnp.full(shape, INF, jnp.int32)  # driver reserved
        fit_d = jnp.ones(shape, jnp.bool_)
        for d in range(3):
            a = avail_scr[d]
            er = ereq_ref[b, d]
            dr = dreq_ref[b, d]
            safe = jnp.maximum(er, 1)
            per_e = jnp.where(
                0 > a, 0, jnp.where(er == 0, INF, jnp.floor_divide(a, safe))
            )
            per_wd = jnp.where(
                dr > a,
                0,
                jnp.where(er == 0, INF, jnp.floor_divide(a - dr, safe)),
            )
            cap_e = jnp.minimum(cap_e, per_e)
            cap_wd = jnp.minimum(cap_wd, per_wd)
            fit_d = fit_d & (dr <= a)
        cap_e = jnp.where(elig_e, jnp.maximum(cap_e, 0), 0)
        cap_wd = jnp.where(elig_e, jnp.maximum(cap_wd, 0), 0)

        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (1, emax), 1)
        # The queue kernel's node axis is pre-permuted into executor
        # priority order, so the priority KEY is the position itself.
        solve = make_gang_solver(
            fill,
            num_zones=num_zones, emax=emax, n_pad=n_pad, shape=shape,
            count=count, cap_e=cap_e, cap_wd=cap_wd, fit_d=fit_d,
            elig_e=elig_e, elig_d=elig_d, drank=drank,
            key=iota, node_val=node_id, slot_iota=slot_iota,
            zone=zone_ref[:],
            sched3=[sched_ref[0], sched_ref[1], sched_ref[2]],
            avail3=[avail_scr[0], avail_scr[1], avail_scr[2]],
            dreq3=[dreq_ref[b, 0], dreq_ref[b, 1], dreq_ref[b, 2]],
            ereq3=[ereq_ref[b, 0], ereq_ref[b, 1], ereq_ref[b, 2]],
        )
        ok, is_drv, execs_row, exec_counts, driver_node = solve()

        packed = ok & valid & ~too_big
        admitted = packed & ~blocked_in

        # --- scatter-subtract the admitted gang (resource.go:251-255)
        for d in range(3):
            delta = exec_counts * ereq_ref[b, d] + jnp.where(
                is_drv, dreq_ref[b, d], 0
            )
            a = avail_scr[d]
            avail_scr[d] = jnp.where(admitted, a - delta, a)

        # Strict FIFO: a non-skippable valid failure blocks the rest
        # (resource.go:241-249).
        blocked_scr[0] = jnp.where(
            blocked_in | (valid & ~packed & ~skippable), 1, 0
        ).astype(jnp.int32)

        m_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 4), 1)
        out_driver = jnp.where(admitted, driver_node, -1)
        meta = jnp.where(
            m_iota == 0,
            out_driver,
            jnp.where(
                m_iota == 1,
                admitted.astype(jnp.int32),
                jnp.where(m_iota == 2, packed.astype(jnp.int32), 0),
            ),
        )
        meta_out[pl.ds(b, 1), :] = meta
        execs_out[pl.ds(b, 1), :] = jnp.where(admitted, execs_row, -1)

        @pl.when(b == n_apps - 1)
        def _():
            avail_out[:] = avail_scr[:]

    return kernel


# Deferred imports so the module imports cleanly where jax.experimental
# pallas is unavailable (the routing layer falls back to the XLA scan).
try:  # pragma: no cover - import guard
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


@partial(
    jax.jit, static_argnames=("fill", "emax", "num_zones", "interpret")
)
def fifo_pack_pallas(
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    interpret: bool = False,
) -> BatchedPacking:
    """Queue-mode `batched_fifo_pack`, executed as one Pallas kernel.

    All six strategies are supported (plain fills + the single-AZ
    wrappers, whose per-zone pack and efficiency-scored zone pick run
    in-kernel), in queue mode only (no per-app masks, no segmented
    windows) — exactly the shape of the north-star batched admission.
    Callers should route through `fifo_pack_auto`, which falls back to
    the XLA scan everywhere else.
    """
    if not pallas_eligible(apps, fill):
        raise ValueError(
            f"pallas path supports queue mode with "
            f"{PALLAS_FILLS + tuple(PALLAS_SINGLE_AZ)}, got "
            f"fill={fill!r} masked={apps.driver_cand is not None or apps.domain is not None} "
            f"segmented={apps.commit is not None}"
        )

    n = cluster.available.shape[0]
    b = apps.driver_req.shape[0]
    if b == 0:
        # An empty queue admits nothing and leaves availability unchanged
        # (the grid would be (0,) and the kernel would never run).
        return BatchedPacking(
            driver_node=jnp.zeros((0,), jnp.int32),
            executor_nodes=jnp.zeros((0, emax), jnp.int32),
            admitted=jnp.zeros((0,), jnp.bool_),
            packed=jnp.zeros((0,), jnp.bool_),
            available_after=jnp.asarray(cluster.available, jnp.int32),
        )
    rows = _layout_rows(n)
    tile = rows * _LANES
    n_pad = _round_up(max(n, tile), tile)
    cols = n_pad // rows

    (driver_elig, exec_elig, d_order, d_rank, e_order, _zrank) = (
        queue_mode_orders(cluster, num_zones)
    )

    # Re-arrange the node axis into executor-priority position order so the
    # kernel's "first open position" argmin IS the executor priority walk,
    # then fold positions row-major into [rows, cols] (position p at
    # [p // cols, p % cols]) per the sublane layout rule.
    pad_cols = n_pad - n

    def pos_row(x, fill_value):
        row = x[e_order]
        return jnp.pad(row, (0, pad_cols), constant_values=fill_value).reshape(
            rows, cols
        )

    avail_pos = (
        jnp.pad(cluster.available[e_order].T, ((0, 0), (0, pad_cols)))
        .astype(jnp.int32)
        .reshape(3, rows, cols)
    )
    elig_e_pos = pos_row(exec_elig.astype(jnp.int32), 0)
    elig_d_pos = pos_row(driver_elig.astype(jnp.int32), 0)
    drank_pos = pos_row(d_rank, INT32_INF)
    nodeid_pos = pos_row(jnp.arange(n, dtype=jnp.int32), 0)
    # Zone ids padded with an out-of-range id (padding matches no zone);
    # schedulable feeds the single-AZ zone-efficiency scoring.
    zone_pos = pos_row(cluster.zone_id.astype(jnp.int32), num_zones)
    sched_pos = (
        jnp.pad(cluster.schedulable[e_order].T, ((0, 0), (0, pad_cols)))
        .astype(jnp.int32)
        .reshape(3, rows, cols)
    )

    kernel = _make_kernel(fill, emax, n_pad, b, rows, num_zones=num_zones)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 7,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((3, rows, cols), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
    )
    meta, execs, avail_after_pos = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b, 4), jnp.int32),
            jax.ShapeDtypeStruct((b, emax), jnp.int32),
            jax.ShapeDtypeStruct((3, rows, cols), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        apps.driver_req.astype(jnp.int32),
        apps.exec_req.astype(jnp.int32),
        apps.exec_count.astype(jnp.int32),
        apps.app_valid.astype(jnp.int32),
        apps.skippable.astype(jnp.int32),
        avail_pos,
        elig_e_pos,
        elig_d_pos,
        drank_pos,
        nodeid_pos,
        zone_pos,
        sched_pos,
    )

    # Un-permute the availability back into node order.
    avail_after = (
        jnp.zeros_like(cluster.available)
        .at[e_order]
        .set(avail_after_pos.reshape(3, n_pad)[:, :n].T)
    )
    return BatchedPacking(
        driver_node=meta[:, 0],
        executor_nodes=execs,
        admitted=meta[:, 1] != 0,
        packed=meta[:, 2] != 0,
        available_after=avail_after,
    )


_PALLAS_AVAILABLE: bool | None = None


def pallas_available() -> bool:
    """True when the default backend can compile Mosaic kernels (probed
    once with a trivial kernel and cached)."""
    global _PALLAS_AVAILABLE
    if _PALLAS_AVAILABLE is None:
        if not _PALLAS_IMPORTED:
            _PALLAS_AVAILABLE = False
            return False
        try:

            def _probe(x_ref, o_ref):
                o_ref[:] = x_ref[:] + 1

            out = pl.pallas_call(
                _probe,
                out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.int32),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(jnp.zeros((8, _LANES), jnp.int32))
            _PALLAS_AVAILABLE = bool(np.asarray(out)[0, 0] == 1)
        except Exception:
            _PALLAS_AVAILABLE = False
    return _PALLAS_AVAILABLE


def fifo_pack_auto(
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    prefer_pallas: bool = True,
) -> BatchedPacking:
    """Route a queue solve to the Pallas kernel when the backend supports
    Mosaic and the request is queue-mode with a plain fill; otherwise the
    XLA scan. Decisions are identical either way (golden-parity tested)."""
    from spark_scheduler_tpu.ops.batched import batched_fifo_pack

    if prefer_pallas and pallas_eligible(apps, fill) and pallas_available():
        return fifo_pack_pallas(
            cluster, apps, fill=fill, emax=emax, num_zones=num_zones
        )
    return batched_fifo_pack(
        cluster, apps, fill=fill, emax=emax, num_zones=num_zones
    )
