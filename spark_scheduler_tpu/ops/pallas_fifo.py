"""The FIFO gang-admission queue as ONE Pallas TPU kernel.

`ops/batched.batched_fifo_pack` expresses queue admission as a `lax.scan`
whose per-step body is a handful of O(N) vector ops. At 10k nodes the step
body is ~microseconds of VPU work, so the scan is dominated by loop-trip
overhead (HBM round-trips for the carried availability between steps and
XLA's per-iteration scheduling). This module removes that overhead the
TPU-native way: the ENTIRE queue runs inside one Mosaic kernel with

  - the availability tensor resident in VMEM scratch across grid steps
    (TPU grid iterations execute sequentially on a core, so scratch carries
    the scan state chip-side — it never round-trips to HBM);
  - per-app parameters (requests, counts, flags) delivered via scalar
    prefetch into SMEM;
  - the executor fills re-derived as iterative masked-argmin placement
    (`emax` rounds of "first open position") instead of
    cumsum + searchsorted, because a short static loop of VPU reductions
    beats a 10k-lane prefix scan and Mosaic has no native searchsorted.

Semantics are bit-identical to `batched_fifo_pack` in queue mode (shared
eligibility, priority orders fixed from the starting availability — the
`fitEarlierDrivers` semantics of resource.go:221-258): the golden-parity
suite (tests/test_pallas_fifo.py) and the on-silicon smoke
(hack/tpu_parity_smoke.py) compare the two paths decision-for-decision.

Fill derivations (reference loops -> argmin keys):

  tightly-pack (pack_tightly.go:45-61): fill each node before moving on
      == every slot goes to the FIRST position with remaining capacity
      -> key = position.
  distribute-evenly (distribute_evenly.go:49-71): one executor per open
      node per round, rounds in position order
      == every slot goes to the open position with lexicographically
      smallest (slots already placed there, position)
      -> key = placed * Npad + position  (placed <= emax, so no overflow).
  minimal-fragmentation (minimal_fragmentation.go:68-98): smallest single
      node fitting the whole gang, else consume nodes in (capacity desc,
      position asc) order while the running clamped total stays <= count,
      remainder on the smallest not-consumed node that fits it
      -> <= emax consume rounds of masked max + two masked-min reductions.

Masked/segmented serving windows keep the XLA path (they re-sort per
segment inside the scan, which wants XLA's fused sorts); this kernel is the
queue-mode hot path: the north-star 10k-node x 1k-app batched admission.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.ops.batched import (
    AppBatch,
    BatchedPacking,
    queue_mode_orders,
)

PALLAS_FILLS = ("tightly-pack", "distribute-evenly", "minimal-fragmentation")

_LANES = 128  # int32 lane width
_SUBLANES = 8  # VPU sublanes
# Above this node count the position axis folds row-major into an
# [8, Np/8] tile so vector ops drive all 8 VPU sublanes (measured ~15%
# faster at 10k+ nodes); below it the flat [1, Np] row wins on fixed
# overhead (measured ~35% faster at 1k nodes on a v5e).
_SUBLANE_FOLD_MIN_NODES = 4096


def _layout_rows(n: int) -> int:
    return _SUBLANES if n >= _SUBLANE_FOLD_MIN_NODES else 1


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def pallas_eligible(apps: "AppBatch", fill: str) -> bool:
    """THE single definition of what the Pallas queue kernel supports:
    plain queue mode (no per-app masks, no segmented windows) with one of
    the three plain fills. Shared by every routing site so eligibility
    cannot drift when the kernel learns new shapes."""
    return (
        fill in PALLAS_FILLS
        and apps.commit is None
        and apps.driver_cand is None
        and apps.domain is None
    )


def _make_kernel(fill: str, emax: int, n_pad: int, n_apps: int, rows: int):
    """Build the kernel body. Everything static (fill, emax, padding,
    layout) is closed over; per-app scalars arrive via prefetch refs.

    The position axis is laid out 2D row-major — position p lives at
    [p // cols, p % cols] of a [rows, cols] tile (`_layout_rows`)."""

    INF = INT32_INF
    cols = n_pad // rows

    def kernel(
        dreq_ref,  # SMEM [B, 3] i32 — driver request
        ereq_ref,  # SMEM [B, 3] i32 — executor request
        cnt_ref,  # SMEM [B] i32 — gang size
        valid_ref,  # SMEM [B] i32 — app_valid
        skip_ref,  # SMEM [B] i32 — skippable
        avail_ref,  # VMEM [3, rows, cols] i32 — starting availability (position order)
        elig_e_ref,  # VMEM [rows, cols] i32 — executor eligibility
        elig_d_ref,  # VMEM [rows, cols] i32 — driver eligibility
        drank_ref,  # VMEM [rows, cols] i32 — driver-priority rank per position
        nodeid_ref,  # VMEM [rows, cols] i32 — original node index per position
        meta_out,  # VMEM [B, 4] i32 — (driver_node, admitted, packed, 0)
        execs_out,  # VMEM [B, emax] i32
        avail_out,  # VMEM [3, rows, cols] i32 — availability after all admits
        avail_scr,  # VMEM [3, rows, cols] i32 scratch — the scan carry
        blocked_scr,  # SMEM [1] i32 scratch — strict-FIFO blocked flag
    ):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            avail_scr[:] = avail_ref[:]
            blocked_scr[0] = 0

        iota = (
            jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) * cols
            + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        )
        elig_e = elig_e_ref[:] != 0
        elig_d = elig_d_ref[:] != 0
        drank = drank_ref[:]
        node_id = nodeid_ref[:]

        raw_count = cnt_ref[b]
        too_big = raw_count > emax
        count = jnp.minimum(raw_count, emax)
        valid = valid_ref[b] != 0
        skippable = skip_ref[b] != 0
        blocked_in = blocked_scr[0] != 0

        # --- node capacities (ops/capacity.py node_capacities, exact
        # integer semantics: per dim 0 if reserved > avail, INF if req == 0,
        # else floor((avail-reserved)/req); node cap = max(min over dims, 0))
        shape = (rows, cols)
        cap_e = jnp.full(shape, INF, jnp.int32)  # no reservation
        cap_wd = jnp.full(shape, INF, jnp.int32)  # driver reserved
        fit_d = jnp.ones(shape, jnp.bool_)
        for d in range(3):
            a = avail_scr[d]
            er = ereq_ref[b, d]
            dr = dreq_ref[b, d]
            safe = jnp.maximum(er, 1)
            per_e = jnp.where(
                0 > a, 0, jnp.where(er == 0, INF, jnp.floor_divide(a, safe))
            )
            per_wd = jnp.where(
                dr > a,
                0,
                jnp.where(er == 0, INF, jnp.floor_divide(a - dr, safe)),
            )
            cap_e = jnp.minimum(cap_e, per_e)
            cap_wd = jnp.minimum(cap_wd, per_wd)
            fit_d = fit_d & (dr <= a)
        cap_e = jnp.where(elig_e, jnp.maximum(cap_e, 0), 0)
        cap_wd = jnp.where(elig_e, jnp.maximum(cap_wd, 0), 0)

        # --- driver selection via the feasibility identity
        # (ops/packing.py pack_one_app): reserving the driver on node i only
        # changes node i's executor capacity.
        cap_e_c = jnp.minimum(cap_e, count)
        cap_wd_c = jnp.minimum(cap_wd, count)
        total_base = jnp.sum(cap_e_c)
        total_if = total_base - cap_e_c + cap_wd_c
        feasible = elig_d & fit_d & (total_if >= count)
        best_rank = jnp.min(jnp.where(feasible, drank, INF))
        found = best_rank < INF
        # drank is a permutation rank -> at most one position matches.
        p_star = jnp.min(jnp.where(feasible & (drank == best_rank), iota, INF))
        is_drv = iota == p_star
        driver_node = jnp.sum(jnp.where(is_drv, node_id, 0))

        # Executor capacities with the chosen driver tentatively reserved.
        caps_fill = jnp.where(is_drv, cap_wd, cap_e)

        # --- executor fill: emax rounds of masked-argmin placement.
        slot_iota = jax.lax.broadcasted_iota(jnp.int32, (1, emax), 1)
        execs_row = jnp.full((1, emax), -1, jnp.int32)
        exec_counts = jnp.zeros(shape, jnp.int32)
        ok = found  # feasibility identity guarantees the fill succeeds

        if fill == "tightly-pack":
            remaining = caps_fill
            for j in range(emax):
                place = ok & (j < count)
                pos_j = jnp.min(jnp.where(remaining > 0, iota, INF))
                hit = (iota == pos_j) & place
                node_j = jnp.sum(jnp.where(hit, node_id, 0))
                execs_row = jnp.where(
                    (slot_iota == j) & place, node_j, execs_row
                )
                remaining = remaining - hit
                exec_counts = exec_counts + hit
        elif fill == "distribute-evenly":
            # key = placed * Npad + position over open positions; placed
            # never exceeds emax so the key stays far below int32 range.
            for j in range(emax):
                place = ok & (j < count)
                open_ = elig_e & (exec_counts < caps_fill)
                key = exec_counts * n_pad + iota
                k_min = jnp.min(jnp.where(open_, key, INF))
                pos_j = jnp.where(k_min < INF, k_min % n_pad, INF)
                hit = (iota == pos_j) & place
                node_j = jnp.sum(jnp.where(hit, node_id, 0))
                execs_row = jnp.where(
                    (slot_iota == j) & place, node_j, execs_row
                )
                exec_counts = exec_counts + hit
        elif fill == "minimal-fragmentation":
            cap_ok = caps_fill > 0
            caps_c = jnp.minimum(caps_fill, count)
            # Branch A: smallest single node fitting the whole gang
            # (minimal_fragmentation.go:68-78): min capacity, then earliest
            # position on capacity ties.
            mask_a = cap_ok & (caps_fill >= count)
            exists_a = jnp.any(mask_a)
            min_cap_a = jnp.min(jnp.where(mask_a, caps_fill, INF))
            pos_a = jnp.min(
                jnp.where(mask_a & (caps_fill == min_cap_a), iota, INF)
            )
            # Branch B: consume (clamped capacity desc, position asc) while
            # the running total stays <= count (the maximal prefix of the
            # reference's desc sort), remainder on the smallest
            # not-consumed node with UNCLAMPED capacity >= remainder
            # (minimal_fragmentation.go:80-98).
            use_b = ok & ~exists_a
            consumed = jnp.zeros(shape, jnp.bool_)
            placed_total = jnp.int32(0)
            for _ in range(emax):
                open_b = cap_ok & ~consumed
                c_max = jnp.max(jnp.where(open_b, caps_c, -1))
                pos_k = jnp.min(
                    jnp.where(open_b & (caps_c == c_max), iota, INF)
                )
                take = use_b & (c_max > 0) & (placed_total + c_max <= count)
                hit = (iota == pos_k) & take
                node_k = jnp.sum(jnp.where(hit, node_id, 0))
                in_span = (
                    (slot_iota >= placed_total)
                    & (slot_iota < placed_total + c_max)
                    & take
                )
                execs_row = jnp.where(in_span, node_k, execs_row)
                exec_counts = exec_counts + jnp.where(hit, c_max, 0)
                consumed = consumed | hit
                placed_total = placed_total + jnp.where(take, c_max, 0)
            remainder = count - placed_total
            mask_fin = cap_ok & ~consumed & (caps_fill >= remainder)
            min_cap_f = jnp.min(jnp.where(mask_fin, caps_fill, INF))
            pos_f = jnp.min(
                jnp.where(mask_fin & (caps_fill == min_cap_f), iota, INF)
            )
            need_fin = use_b & (remainder > 0)
            chosen_pos = jnp.where(exists_a, pos_a, pos_f)
            fin_take = ok & (exists_a | need_fin)
            fin_count = jnp.where(exists_a, count, remainder)
            fin_hit = (iota == chosen_pos) & fin_take
            node_fin = jnp.sum(jnp.where(fin_hit, node_id, 0))
            fin_start = jnp.where(exists_a, 0, placed_total)
            in_fin = (
                (slot_iota >= fin_start)
                & (slot_iota < fin_start + fin_count)
                & fin_take
            )
            # Branch A overwrites any branch-B spans (it is exclusive).
            execs_row = jnp.where(
                exists_a & (slot_iota < count) & ok,
                node_fin,
                jnp.where(in_fin, node_fin, execs_row),
            )
            exec_counts = jnp.where(
                exists_a & ok,
                jnp.where(iota == chosen_pos, count, 0),
                exec_counts + jnp.where(fin_hit, fin_count, 0),
            )
        else:  # pragma: no cover — guarded by fifo_pack_pallas
            raise ValueError(f"unsupported fill for pallas: {fill}")

        packed = ok & valid & ~too_big
        admitted = packed & ~blocked_in

        # --- scatter-subtract the admitted gang (resource.go:251-255)
        for d in range(3):
            delta = exec_counts * ereq_ref[b, d] + jnp.where(
                is_drv, dreq_ref[b, d], 0
            )
            a = avail_scr[d]
            avail_scr[d] = jnp.where(admitted, a - delta, a)

        # Strict FIFO: a non-skippable valid failure blocks the rest
        # (resource.go:241-249).
        blocked_scr[0] = jnp.where(
            blocked_in | (valid & ~packed & ~skippable), 1, 0
        ).astype(jnp.int32)

        m_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 4), 1)
        out_driver = jnp.where(admitted, driver_node, -1)
        meta = jnp.where(
            m_iota == 0,
            out_driver,
            jnp.where(
                m_iota == 1,
                admitted.astype(jnp.int32),
                jnp.where(m_iota == 2, packed.astype(jnp.int32), 0),
            ),
        )
        meta_out[pl.ds(b, 1), :] = meta
        execs_out[pl.ds(b, 1), :] = jnp.where(admitted, execs_row, -1)

        @pl.when(b == n_apps - 1)
        def _():
            avail_out[:] = avail_scr[:]

    return kernel


# Deferred imports so the module imports cleanly where jax.experimental
# pallas is unavailable (the routing layer falls back to the XLA scan).
try:  # pragma: no cover - import guard
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


@partial(
    jax.jit, static_argnames=("fill", "emax", "num_zones", "interpret")
)
def fifo_pack_pallas(
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    interpret: bool = False,
) -> BatchedPacking:
    """Queue-mode `batched_fifo_pack`, executed as one Pallas kernel.

    Only the three plain fills are supported, and only queue mode (no
    per-app masks, no segmented windows) — exactly the shape of the
    north-star batched admission. Callers should route through
    `fifo_pack_auto`, which falls back to the XLA scan everywhere else.
    """
    if not pallas_eligible(apps, fill):
        raise ValueError(
            f"pallas path supports queue mode with {PALLAS_FILLS}, got "
            f"fill={fill!r} masked={apps.driver_cand is not None or apps.domain is not None} "
            f"segmented={apps.commit is not None}"
        )

    n = cluster.available.shape[0]
    b = apps.driver_req.shape[0]
    if b == 0:
        # An empty queue admits nothing and leaves availability unchanged
        # (the grid would be (0,) and the kernel would never run).
        return BatchedPacking(
            driver_node=jnp.zeros((0,), jnp.int32),
            executor_nodes=jnp.zeros((0, emax), jnp.int32),
            admitted=jnp.zeros((0,), jnp.bool_),
            packed=jnp.zeros((0,), jnp.bool_),
            available_after=jnp.asarray(cluster.available, jnp.int32),
        )
    rows = _layout_rows(n)
    tile = rows * _LANES
    n_pad = _round_up(max(n, tile), tile)
    cols = n_pad // rows

    (driver_elig, exec_elig, d_order, d_rank, e_order, _zrank) = (
        queue_mode_orders(cluster, num_zones)
    )

    # Re-arrange the node axis into executor-priority position order so the
    # kernel's "first open position" argmin IS the executor priority walk,
    # then fold positions row-major into [rows, cols] (position p at
    # [p // cols, p % cols]) per the sublane layout rule.
    pad_cols = n_pad - n

    def pos_row(x, fill_value):
        row = x[e_order]
        return jnp.pad(row, (0, pad_cols), constant_values=fill_value).reshape(
            rows, cols
        )

    avail_pos = (
        jnp.pad(cluster.available[e_order].T, ((0, 0), (0, pad_cols)))
        .astype(jnp.int32)
        .reshape(3, rows, cols)
    )
    elig_e_pos = pos_row(exec_elig.astype(jnp.int32), 0)
    elig_d_pos = pos_row(driver_elig.astype(jnp.int32), 0)
    drank_pos = pos_row(d_rank, INT32_INF)
    nodeid_pos = pos_row(jnp.arange(n, dtype=jnp.int32), 0)

    kernel = _make_kernel(fill, emax, n_pad, b, rows)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((3, rows, cols), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
    )
    meta, execs, avail_after_pos = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b, 4), jnp.int32),
            jax.ShapeDtypeStruct((b, emax), jnp.int32),
            jax.ShapeDtypeStruct((3, rows, cols), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        apps.driver_req.astype(jnp.int32),
        apps.exec_req.astype(jnp.int32),
        apps.exec_count.astype(jnp.int32),
        apps.app_valid.astype(jnp.int32),
        apps.skippable.astype(jnp.int32),
        avail_pos,
        elig_e_pos,
        elig_d_pos,
        drank_pos,
        nodeid_pos,
    )

    # Un-permute the availability back into node order.
    avail_after = (
        jnp.zeros_like(cluster.available)
        .at[e_order]
        .set(avail_after_pos.reshape(3, n_pad)[:, :n].T)
    )
    return BatchedPacking(
        driver_node=meta[:, 0],
        executor_nodes=execs,
        admitted=meta[:, 1] != 0,
        packed=meta[:, 2] != 0,
        available_after=avail_after,
    )


_PALLAS_AVAILABLE: bool | None = None


def pallas_available() -> bool:
    """True when the default backend can compile Mosaic kernels (probed
    once with a trivial kernel and cached)."""
    global _PALLAS_AVAILABLE
    if _PALLAS_AVAILABLE is None:
        if not _PALLAS_IMPORTED:
            _PALLAS_AVAILABLE = False
            return False
        try:

            def _probe(x_ref, o_ref):
                o_ref[:] = x_ref[:] + 1

            out = pl.pallas_call(
                _probe,
                out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.int32),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(jnp.zeros((8, _LANES), jnp.int32))
            _PALLAS_AVAILABLE = bool(np.asarray(out)[0, 0] == 1)
        except Exception:
            _PALLAS_AVAILABLE = False
    return _PALLAS_AVAILABLE


def fifo_pack_auto(
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
    prefer_pallas: bool = True,
) -> BatchedPacking:
    """Route a queue solve to the Pallas kernel when the backend supports
    Mosaic and the request is queue-mode with a plain fill; otherwise the
    XLA scan. Decisions are identical either way (golden-parity tested)."""
    from spark_scheduler_tpu.ops.batched import batched_fifo_pack

    if prefer_pallas and pallas_eligible(apps, fill) and pallas_available():
        return fifo_pack_pallas(
            cluster, apps, fill=fill, emax=emax, num_zones=num_zones
        )
    return batched_fifo_pack(
        cluster, apps, fill=fill, emax=emax, num_zones=num_zones
    )
