"""Node-priority ordering as a lexicographic sort kernel.

Rebuilds internal/sort/nodesorting.go as one XLA sort over composite keys:

  1. AZ priority: zones ranked ascending by total available (memory first,
     then CPU) over the metadata domain (nodesorting.go:97-121,
     `resourcesLessThan` :74-81).
  2. Within a zone: available memory asc, then CPU asc, then node name
     (nodesorting.go:84-95; the reference's `sort.Slice` is unstable when
     mem+cpu tie but GPU differs — any order is reference-compatible there,
     we pin it with the name rank).
  3. Optional configured label priority as a FINAL stable re-sort
     (nodesorting.go:62-64,160-185), i.e. the label rank becomes the most
     significant key, missing labels rank last.

Ineligible nodes sort to the end; callers get `(order, count)`.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_scheduler_tpu.models.cluster import ClusterTensors, INT32_INF
from spark_scheduler_tpu.models.resources import CPU_DIM, MEM_DIM


def zone_ranks(
    cluster: ClusterTensors,
    domain_mask: jnp.ndarray,  # [N] bool — nodes in the metadata domain
    num_zones: int,  # static upper bound on zone-id space
    available: jnp.ndarray | None = None,  # [N,3] override (defaults to cluster's)
    zone_base: tuple | None = None,  # pruned-solve zone-sum offsets (see below)
) -> jnp.ndarray:  # [num_zones] i32: rank of each zone (0 = highest priority)
    """Zones ordered ascending by (total available memory, total CPU)
    (nodesorting.go:101-104, 124-134). Zones with no domain nodes rank last.

    `available` lets callers rank against a mutated availability (the batched
    FIFO scan threads availability through admissions) without rebuilding the
    whole ClusterTensors.

    `zone_base` is the candidate-pruning contract (core/prune.py): a gathered
    top-K sub-cluster must still rank zones by the FULL domain's availability
    sums, so the host ships the pruned-away rows' per-zone sums as a constant
    (mem_hi, mem_lo, cpu_hi, cpu_lo, present) tuple of [num_zones] arrays —
    each int64 sum S split into int32 limbs hi = S >> 24, lo = S & 0xFFFFFF
    (exact for |S| < 2^55, i.e. any 100k-node cluster of int32 rows). The
    offsets stay constant across the window's scan because a certified pruned
    solve never places on an excluded row.

    Offset DERIVATION contract (ISSUE 12): the host derives each excluded
    sum as `zone total − Σ kept rows` from resident, event-maintained
    per-zone totals (core/zone_aggregates.ZoneAggregates) — exact int64
    integer sums, never a per-window O(N) re-aggregation — so the identity
    `chunks(kept) + limbs(total − kept) ≡ chunks(full domain)` holds in the
    carry-normal form this kernel compares (the subset-domain sweep derives
    the same limbs by direct summation; both are pinned by the planner
    exactness oracle and the offset-identity test)."""
    if available is None:
        available = cluster.available
    mask = domain_mask & cluster.valid

    def _zone_sum_chunks(vals: jnp.ndarray, base_hi=None, base_lo=None) -> list[jnp.ndarray]:
        # Exact int32-safe aggregation without x64: split each value into
        # four 8-bit chunks (top chunk keeps the sign via arithmetic shift),
        # segment-sum each, then normalize carries upward. Each low-chunk
        # sum is <= n*255, exact for n < 2^23 nodes; the top-chunk sum is
        # bounded by n*2^7 after the shift. Chunks returned most-significant
        # first, comparable lexicographically. Excluded-row base offsets add
        # into the chunks BEFORE carry normalization, so the normal form
        # (and therefore the rank order) equals the unpruned sums exactly.
        v = jnp.where(mask, vals, 0)

        def seg(x):
            return jnp.zeros(num_zones, jnp.int32).at[cluster.zone_id].add(x)

        s3 = seg(v >> 24)
        s2 = seg((v >> 16) & 0xFF)
        s1 = seg((v >> 8) & 0xFF)
        s0 = seg(v & 0xFF)
        if base_hi is not None:
            s3 = s3 + base_hi
            s2 = s2 + ((base_lo >> 16) & 0xFF)
            s1 = s1 + ((base_lo >> 8) & 0xFF)
            s0 = s0 + (base_lo & 0xFF)
        s1 = s1 + (s0 >> 8)
        s0 = s0 & 0xFF
        s2 = s2 + (s1 >> 8)
        s1 = s1 & 0xFF
        s3 = s3 + (s2 >> 8)
        s2 = s2 & 0xFF
        return [s3, s2, s1, s0]

    if zone_base is not None:
        mem_hi, mem_lo, cpu_hi, cpu_lo, base_present = zone_base
        mem_k = _zone_sum_chunks(available[:, MEM_DIM], mem_hi, mem_lo)
        cpu_k = _zone_sum_chunks(available[:, CPU_DIM], cpu_hi, cpu_lo)
    else:
        base_present = None
        mem_k = _zone_sum_chunks(available[:, MEM_DIM])
        cpu_k = _zone_sum_chunks(available[:, CPU_DIM])
    present = jnp.zeros(num_zones, jnp.bool_).at[cluster.zone_id].max(mask)
    if base_present is not None:
        present = present | base_present
    # Absent zones last; ties between zones are unordered in the reference
    # (map iteration); pin with zone id. lexsort: last key is primary.
    keys = (
        [jnp.arange(num_zones)]
        + list(reversed(cpu_k))
        + list(reversed(mem_k))
        + [jnp.where(present, 0, 1)]
    )
    order = jnp.lexsort(keys)
    ranks = jnp.zeros(num_zones, jnp.int32).at[order].set(
        jnp.arange(num_zones, dtype=jnp.int32)
    )
    return ranks


def priority_order(
    cluster: ClusterTensors,
    eligible: jnp.ndarray,  # [N] bool
    zrank: jnp.ndarray,  # [num_zones] i32 from zone_ranks
    label_rank: jnp.ndarray,  # [N] i32 (INT32_INF = unranked)
    available: jnp.ndarray | None = None,  # [N,3] override (defaults to cluster's)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(order[N] node indices, count) — eligible nodes in priority order,
    ineligible pushed to the end."""
    if available is None:
        available = cluster.available
    elig = eligible & cluster.valid
    az = zrank[cluster.zone_id]
    mem = available[:, MEM_DIM]
    cpu = available[:, CPU_DIM]
    # lexsort: last key is primary.
    order = jnp.lexsort(
        (cluster.name_rank, cpu, mem, az, label_rank, jnp.where(elig, 0, 1))
    )
    count = jnp.sum(elig).astype(jnp.int32)
    return order.astype(jnp.int32), count
