"""XLA compute kernels: capacity, node sorting, bin-packing strategies.

Every kernel is a pure jittable function over `ClusterTensors` + app-shape
arrays. The five packing strategies of the reference
(internal/extender/binpack.go:39-54) are reproduced with slot-exact placement
semantics, but as vectorized tensor programs (prefix sums / sorts /
searchsorted) instead of per-slot greedy loops.
"""

from spark_scheduler_tpu.ops.packing import (  # noqa: F401
    Packing,
    spark_bin_pack,
    tightly_pack,
    distribute_evenly,
    minimal_fragmentation,
    single_az_tightly_pack,
    single_az_minimal_fragmentation,
    az_aware_tightly_pack,
    BINPACK_FUNCTIONS,
    SINGLE_AZ_PACKERS,
)
from spark_scheduler_tpu.ops.capacity import node_capacities, fits  # noqa: F401
from spark_scheduler_tpu.ops.pallas_fifo import (  # noqa: F401
    fifo_pack_auto,
    pallas_available,
)
from spark_scheduler_tpu.ops.sorting import priority_order  # noqa: F401
from spark_scheduler_tpu.ops.efficiency import avg_packing_efficiency  # noqa: F401
