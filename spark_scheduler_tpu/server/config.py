"""Install-time configuration (config/config.go:24-84).

YAML-loadable install config with the reference's option surface: FIFO mode
+ age-based enforcement per instance group, binpack algorithm selection,
async write-back retry budget, unschedulable-pod timeout, prioritized node
labels for driver/executor sorting, single-AZ dynamic-allocation flag, and
the serving port. `from_yaml` accepts the reference's field names.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_scheduler_tpu.core.extender import FifoConfig


@dataclasses.dataclass
class LabelPriorityOrder:
    """config.LabelPriorityOrder (config/config.go:66-70)."""

    name: str
    descending_priority_values: list[str]

    def as_tuple(self) -> tuple[str, list[str]]:
        return (self.name, self.descending_priority_values)


@dataclasses.dataclass
class InstallConfig:
    fifo: bool = False
    fifo_config: FifoConfig = dataclasses.field(default_factory=FifoConfig)
    binpack_algo: str = "tightly-pack"
    instance_group_label: str = "instance-group"
    async_client_retry_count: int = 5
    unschedulable_pod_timeout_s: float = 600.0
    should_schedule_dynamically_allocated_executors_in_same_az: bool = False
    driver_prioritized_node_label: Optional[LabelPriorityOrder] = None
    executor_prioritized_node_label: Optional[LabelPriorityOrder] = None
    port: int = 8484
    sync_writes: bool = False  # drain write-back inline (tests/single-thread)
    # One batched device solve per driver request (FIFO prefix + current
    # app, core/solver.py pack_window); False forces the per-earlier-driver
    # sequential loop.
    batched_admission: bool = True
    # Append a JSON line per metric series on every reporter tick (the
    # reference's 30s metric flush, metrics/metrics.go:79). None = off;
    # metrics remain pollable at GET /metrics either way.
    metrics_log: Optional[str] = None
    # Kubernetes apiserver base URL for list+watch ingestion (the informer
    # slot, cmd/server.go:111-147). None = state arrives via PUT /state/*
    # or an embedding program driving the backend directly.
    kube_api_url: Optional[str] = None
    # Conversion webhook client URL wired into the ResourceReservation CRD
    # (config.go:79-84 WebhookServiceConfig + conversionwebhook client
    # config). None = conversion strategy "None".
    conversion_webhook_url: Optional[str] = None
    # JSONL write-ahead log path for the durable backend (the etcd slot);
    # used by the CLI to construct a DurableBackend. None = in-memory only.
    durable_store_path: Optional[str] = None
    # TLS material (the witchcraft server slot: reference install config
    # server.cert-file / key-file / client-ca-files, examples/extender.yml
    # :75-80). Both cert+key set => serve HTTPS; client_ca_files (any
    # number of CAs) additionally requires client certificates (mTLS).
    cert_file: Optional[str] = None
    key_file: Optional[str] = None
    client_ca_files: list[str] = dataclasses.field(default_factory=list)
    # Disable TLS verification of the kube-api-url endpoint (self-signed
    # dev apiservers). NEVER the default: without it, https endpoints are
    # verified against system CAs (or the serviceaccount CA in-cluster).
    kube_api_insecure_skip_tls_verify: bool = False
    # Client-side rate limit for apiserver writes/reads (reference config
    # qps/burst, config/config.go:30-31).
    kube_api_qps: float = 5.0
    kube_api_burst: int = 10
    # Per-connection socket read timeout (extender protocol budget is 30 s,
    # examples/extender.yml:59).
    request_timeout_s: float = 30.0
    # Serving transport: "threaded" (stdlib thread-per-connection stack —
    # the default until the bench A/B proves the async floor on the target
    # box) or "async" (single-threaded event loop with pipelined keep-alive
    # framing and explicit backpressure; see server/transport_async.py).
    # YAML: `server.transport`.
    server_transport: str = "threaded"
    # Serving ingest lane: "python" (json.loads + dict walk per predicate
    # body) or "native" (the C++ framer/decoder in native/runtime.cpp:
    # request framing and the candidate-name bulk never touch Python on
    # the hot path — see server/ingest.py). Composes with either
    # transport; degrades to "python" with a RuntimeWarning when the
    # native runtime cannot be built. YAML: `server.ingest`.
    server_ingest: str = "python"
    # Largest request body either transport will buffer; bigger bodies are
    # answered 413 with the body drained (keep-alive survives). The 10k-node
    # predicate bodies measure ~200 KB, so 16 MiB is generous headroom.
    # YAML: `server.max-body-bytes`.
    max_body_bytes: int = 16 * 1024 * 1024
    # Async-transport connection cap: connections past it are answered with
    # a canned 503 + close instead of accumulating per-connection state
    # (the threaded transport's analogue is its bounded listen backlog).
    # YAML: `server.max-connections`.
    max_connections: int = 512
    # Predicate load shedding: when the batcher's un-claimed backlog
    # reaches this depth, new /predicates calls get an immediate 503
    # instead of parking until the request timeout. 0 disables.
    # YAML: `server.shed-queue-depth`.
    shed_queue_depth: int = 256
    # Expose /debug/* (trace dump + JAX profiler control). Off by default:
    # on the cluster-exposed port these routes are unauthenticated.
    debug_routes: bool = False
    # Structured per-request access logging (the witchcraft req2log slot):
    # one request.2 line per HTTP call with method, path, status, duration,
    # trace id. Off by default (one log line per predicate call is real
    # I/O at serving rates).
    request_log: bool = False
    # Predicate window tuning: max coalesced requests per device solve, and
    # the busy-period accumulation hold (how long the dispatcher waits for
    # stragglers after a coalesced window — a throughput/latency tradeoff;
    # a lone request on an idle server is never held).
    predicate_max_window: int = 32
    predicate_hold_ms: float = 25.0
    # In-process elastic autoscaler (spark_scheduler_tpu/autoscaler/): when
    # enabled, pending Demand CRDs are consumed IN PROCESS — simulated
    # nodes are provisioned (zone-affine, template-shaped) and demand
    # phases flip pending -> fulfilled / cannot-fulfill; nodes idle past
    # the TTL are cordoned then drained, never a node holding a hard or
    # soft reservation. Off by default: on a real cluster the Demand CRD
    # belongs to the external autoscaler.
    autoscaler_enabled: bool = False
    # Hard cap on total node count; demands that would push past it are
    # marked cannot-fulfill.
    autoscaler_max_cluster_size: int = 1000
    # A node idle (no reservations, no bound pods) this long is cordoned,
    # then removed on the next pass if still idle.
    autoscaler_idle_ttl_s: float = 300.0
    autoscaler_poll_interval_s: float = 2.0
    # Template shape of provisioned nodes (k8s quantity strings).
    autoscaler_node_cpu: str = "8"
    autoscaler_node_memory: str = "8Gi"
    autoscaler_node_gpu: str = "1"
    # Zones provisioned nodes spread across (round-robin) when a demand
    # doesn't pin one; empty = the default zone.
    autoscaler_zones: list[str] = dataclasses.field(default_factory=list)
    # Path to the REFRESHABLE runtime-config YAML (the witchcraft Runtime
    # embed, config.go:24-47): log level, fifo, batched-admission, and the
    # async retry budget reload live on file change or SIGHUP
    # (server/runtime.py). None = no runtime reloads.
    runtime_config_path: Optional[str] = None
    # Persistent XLA compilation cache directory: window-shape buckets
    # compile once per machine/image instead of once per process, so a
    # restarted scheduler serves its first windows without multi-second
    # compile stalls. None = per-process compiles.
    jax_compilation_cache_dir: Optional[str] = None
    # Multi-device window-solve engine (core/solver.py): `solver.device-pool`
    # keeps a resident cluster replica on N devices and round-robins
    # concurrent window solves (disjoint-domain windows partition across the
    # pool — instance groups solve in parallel); `solver.mesh` is the full
    # {groups, node-shards} form, where node-shards > 1 additionally shards
    # each slot's node axis over a GSPMD sub-mesh (when a single window's
    # 10k-node solve is the bottleneck and the interconnect is fast — see
    # README "Multi-device serving" for when sharded vs pooled wins).
    # device-pool N is shorthand for mesh {groups: N, node-shards: 1}.
    # 1 / unset = the classic single-device serving path.
    solver_device_pool: int = 1
    solver_mesh_groups: Optional[int] = None
    solver_mesh_node_shards: Optional[int] = None
    # Sound top-K candidate pruning (`solver.prune-top-k` /
    # `solver.prune-slack`, core/prune.py — the two-tier solve): when
    # top-k > 0, eligible serving windows solve a gathered top-K
    # sub-cluster (K per zone = max(top-k, window aggregate demand x
    # slack)) instead of the full [N,3] tensor, and every pruned decision
    # is verified by a post-solve certificate — a failed certificate
    # escalates the window to the exact full re-solve, so decisions stay
    # byte-identical to the unpruned path by construction
    # (`foundry.spark.scheduler.solver.prune.*` counts the escalations).
    # 0 (the default) = off: the classic full-tensor paths byte-for-byte.
    solver_prune_top_k: int = 0
    solver_prune_slack: float = 2.0
    # Delta STATIC uploads (`solver.delta-statics`, ISSUE 11): node events
    # touching few rows ship a row-scatter of the changed static-field
    # rows to the resident device state (and lagging pool replicas catch
    # up from the epoch journal) instead of re-uploading the full
    # multi-MB statics blob per epoch per slot. ON by default — pinned
    # byte-identical to the full-upload path by the delta-equivalence
    # suite; false restores full uploads (and the drain-on-any-statics-
    # change pipeline contract).
    solver_delta_statics: bool = True
    # Million-node scale tier (`solver.scale-tier`): certificate
    # escalations and cold full-tensor re-solves run as a node-sharded
    # device solve across the local device mesh (parallel/solve
    # node_sharding) instead of the host-Python greedy walk. Decisions
    # byte-identical (same kernels; escalation-parity test pinned); any
    # device failure falls back to the host greedy oracle. OFF by
    # default — node-axis sharding wants an ICI-class interconnect.
    solver_scale_tier: bool = False
    # O(K + changed) tensor build (ISSUE 13). `solver.build-oracle`: after
    # every event-fed dirty-set mirror sync, ALSO run the dense [N]-wide
    # compare as an oracle and fail loudly on a missed row — the
    # equivalence suites' guard; off in production (it re-adds the O(N)
    # sweep the dirty set retires). `solver.lazy-warm-start`: a full
    # device upload whose host-side change feed stayed exact keeps the
    # prune planner's resident per-zone orders (a warm restart skips the
    # O(N log N) cold replan); false restores the hard invalidate.
    solver_build_oracle: bool = False
    solver_lazy_warm_start: bool = True
    # Fused multi-window device dispatch (`solver.fuse-windows`): when the
    # predicate backlog holds more than one window's worth of requests,
    # the batcher claims up to fuse-windows x predicate-max-window of them
    # and dispatches the sub-windows as ONE fused device program carrying
    # the committed base on-device between windows — K windows share one
    # h2d + dispatch + d2h round trip (the tunneled-TPU
    # `device_rtt_floor_ms` amortizes by K). Decisions are byte-identical
    # to sequential single-window dispatch (equivalence-suite pinned).
    # 1 (default) = today's one-window-per-dispatch behavior.
    solver_fuse_windows: int = 1
    # Scheduling flight recorder (observability/): every extender decision
    # appends an explainable DecisionRecord (verdict, per-node failure map,
    # FIFO queue position, padding bucket, compile-cache hit, phase wall
    # times) to a bounded ring queryable at GET /debug/decisions, and the
    # solver publishes foundry.spark.scheduler.solver.* telemetry. On by
    # default — bench.py's recorder-overhead section keeps the hot-path
    # cost measured; False strips both for the control measurement.
    flight_recorder: bool = True
    flight_recorder_capacity: int = 2048
    # Durable decision trace (spark_scheduler_tpu/replay/, ISSUE 17): when
    # a path is set (and the flight recorder is on), a TraceWriter journals
    # every input a decision consumed — node/pod events, predicate
    # requests, the config fingerprint — plus the answered verdicts, as a
    # versioned JSONL stream `python -m spark_scheduler_tpu.replay` can
    # re-execute bit-identically or what-if under an altered config.
    #   trace: {path, decisions}
    # `decisions: true` additionally journals the informational
    # DecisionRecord copies (replay never needs them — the result events
    # carry every compared verdict — and they roughly double the
    # serving-path encode cost, so they are opt-in).
    trace_path: Optional[str] = None
    trace_decisions: bool = False
    # Active-active HA (spark_scheduler_tpu/ha/): run this process as one
    # replica of a lease-elected group. The replica starts as a warm
    # standby (caches tailed hot from backend events / the shared WAL) and
    # serves only after winning the lease and running the failover
    # reconcile; reservation/demand writes carry the lease's fencing epoch
    # so a deposed leader's in-flight commits are rejected. YAML block:
    #   ha: {enabled, replica-id, lease-ttl, heartbeat-interval}
    ha_enabled: bool = False
    ha_replica_id: str = "replica-0"
    ha_lease_ttl_s: float = 3.0
    # None = lease-ttl / 3 (three renew chances before takeover).
    ha_heartbeat_s: Optional[float] = None
    # Fleet federation (fleet/): the server boots F independent
    # per-cluster solver stacks behind one FleetFacade instead of a
    # single-cluster app. YAML block:
    #   fleet: {enabled, clusters, max-spillover-hops, stack-window-ms}
    # `stack-window-ms` > 0 turns on fused fleet dispatch (ISSUE 20): a
    # cluster's staged window waits up to that long for windows from the
    # other live clusters, and same-shape-bucket windows flush as ONE
    # stacked device launch (fleet/dispatch.py). 0 (default) = off; every
    # serving blob and decision is then byte-identical to the unstacked
    # fleet.
    fleet_enabled: bool = False
    fleet_clusters: int = 2
    fleet_max_spillover_hops: int = 1
    fleet_stack_window_ms: float = 0.0
    # Request-gap resync threshold (`extender.resync-gap-seconds`,
    # resource.go:191-202): a gap longer than this resyncs durable state
    # from observed pods. Skipped entirely while the HA lease is held.
    resync_gap_seconds: float = 15.0
    # Degraded-mode policy (`server.degraded-mode`, ISSUE 9): what the
    # scheduler does when NO device slot can serve (every pool slot
    # quarantined, or the single device died).
    #   greedy  keep serving decisions via the host-side greedy fallback
    #           (core/fallback.py — byte-identical packing semantics,
    #           O(nodes) Python per row); readiness stays 200 but reports
    #           degraded.
    #   shed    answer /predicates 503 with Retry-After
    #           (`server.degraded-retry-after`); readiness flips 503 so
    #           load balancers drain the replica.
    degraded_mode: str = "greedy"
    degraded_retry_after_s: float = 5.0
    # How often a quarantined device slot is probed for reinstatement
    # (`solver.quarantine-probe`): a tiny device program runs on the slot;
    # success puts it back into rotation (statics re-upload lazily).
    quarantine_probe_s: float = 5.0
    # Shared retry-ladder shape (`retry:` block): base/multiplier/cap for
    # the exponential-backoff-with-full-jitter policy the kube write-back
    # clients ride. `async-client-retry-count` remains the attempt budget
    # (back-compat alias).
    retry_base_delay_s: float = 0.02
    retry_multiplier: float = 2.0
    retry_max_delay_s: float = 2.0
    # Circuit breaker over backend write-back: consecutive failures
    # before opening, and how long an open breaker waits before admitting
    # a half-open probe. 0 failures disables the breaker.
    breaker_failure_threshold: int = 8
    breaker_reset_timeout_s: float = 5.0
    # Policy engine (spark_scheduler_tpu/policy/, ISSUE 16): priority
    # tiers, vectorized preemption search, DRF window ordering, and the
    # pool-driven continuous defragmenter. OFF by default — with
    # `policy.enabled: false` no PolicyEngine is constructed and every
    # extender decision takes the exact pre-policy FIFO branch
    # (byte-identity pinned by tests/test_policy_identity.py + CI).
    #   policy:
    #     enabled: true
    #     ordering: fifo | priority | drf
    #     preemption: true
    #     max-evictions: 8
    #     promote-after: 5m        # anti-starvation age promotion step
    #     protected-class: system  # never evicted
    #     defrag: {enabled, interval, budget}
    policy_enabled: bool = False
    policy_ordering: str = "fifo"
    policy_preemption: bool = False
    policy_max_evictions: int = 8
    policy_promote_after_s: float = 300.0
    policy_protected_class: str = "system"
    policy_defrag: bool = False
    policy_defrag_interval_s: float = 30.0
    policy_defrag_budget: int = 4

    # Module-name markers of DONATED jitted programs (the persistent cache
    # key string is "<module_name>-<hash>"). Donation is invisible in the
    # key, so donated entry points carry it in their function names
    # (core/solver._window_blob_split_donated explains the convention);
    # batched_fifo_pack_carry is the ops-level donated entry the bench
    # drives directly; stacked_fifo_pack covers the arm/bucket stacking
    # kernels (replay sweeps + the fleet dispatch coordinator), which
    # donate their [M, N, 3] availability stacks.
    JAX_CACHE_DONATION_MARKERS = (
        "donated", "batched_fifo_pack_carry", "stacked_fifo_pack",
    )

    @staticmethod
    def serialize_jax_cache_io() -> bool:
        """Make the persistent compilation cache safe for this scheduler's
        concurrent, donation-heavy serving paths. Two measures, installed
        idempotently at the cache's get/put seam:

        1. DONATION GATE — donated programs never read from or write to
           the persistent cache. Executables RELOADED from the cache with
           donated argument buffers intermittently returned WRONG window
           decisions (spurious failure-fit / shifted placements in
           otherwise-deterministic runs; reproduced 4/4 on the HA chaos
           soak whenever the donated window-solve entry was a cache hit,
           0/3 with cache reads disabled — PR 8 ran
           hack/ha_shard_bench.py cache-free as the workaround). Donated
           programs now always compile in-process; the expensive
           undonated kernels (the Mosaic window/queue programs that
           motivated the cache) keep full caching.

        2. WRITE/READ SERIALIZATION — one process-wide lock around the
           cache's executable (de)serialization + file I/O, so two
           threads can never interleave backend.serialize_executable /
           deserialize_executable through the cache (compiles themselves
           still overlap).

        Returns whether the wrappers are installed."""
        try:
            from jax._src import compilation_cache as _cc
        except Exception:
            return False
        if getattr(_cc, "_spark_scheduler_cache_lock", None) is not None:
            return True
        import threading as _threading

        lock = _threading.Lock()
        markers = InstallConfig.JAX_CACHE_DONATION_MARKERS
        _get, _put = _cc.get_executable_and_time, _cc.put_executable_and_time

        def _donation_marked(module_name: str) -> bool:
            return any(m in module_name for m in markers)

        def get_gated(cache_key, *a, **kw):
            if _donation_marked(cache_key.rsplit("-", 1)[0]):
                return None, None  # always a miss: compile in-process
            with lock:
                return _get(cache_key, *a, **kw)

        def put_gated(cache_key, module_name, *a, **kw):
            if _donation_marked(module_name):
                return None  # never persisted
            with lock:
                return _put(cache_key, module_name, *a, **kw)

        _cc.get_executable_and_time = get_gated
        _cc.put_executable_and_time = put_gated
        _cc._spark_scheduler_cache_lock = lock
        return True

    @staticmethod
    def enable_jax_compile_cache(cache_dir: str) -> None:
        """Point jax at a persistent compilation cache (shared helper for
        the server bootstrap and the bench). No-op on older jax without
        the knobs."""
        import jax

        InstallConfig.serialize_jax_cache_io()
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5
            )
            # Without this, MLIR op locations embed the FULL Python call
            # stack, and the Mosaic custom-call payload serializes those
            # locations where the cache key's strip-debuginfo pass cannot
            # reach (it only strips the outer module). Any difference in
            # the call path into pack_window — server dispatcher vs bench
            # precompile vs a shifted line number after an edit — then
            # changes every Pallas program's cache key, and each shape
            # recompiles 20-40 s on the live serving path. Primitive-frame
            # locations are stable (they point inside this package), keep
            # errors attributable, and make the persistent cache actually
            # persistent for Mosaic kernels. Verified: identical
            # canonicalized IR across shifted call sites with this off,
            # differing bytes with it on.
            jax.config.update(
                "jax_include_full_tracebacks_in_locations", False
            )
        except Exception:
            pass

    @classmethod
    def from_dict(cls, raw: dict) -> "InstallConfig":
        fifo_cfg = FifoConfig()
        if "fifo-config" in raw:
            fc = raw["fifo-config"]
            fifo_cfg = FifoConfig(
                enforce_after_pod_age_s=_parse_duration(
                    fc.get("default-enforce-after-pod-age", 0)
                ),
                enforce_after_pod_age_by_instance_group={
                    k: _parse_duration(v)
                    for k, v in fc.get("enforce-after-pod-age-by-instance-group", {}).items()
                },
            )

        def label_prio(key):
            if key not in raw:
                return None
            return LabelPriorityOrder(
                name=raw[key]["name"],
                descending_priority_values=list(
                    raw[key]["descending-priority-values"]
                ),
            )

        # Reference nests TLS + port under a "server" block
        # (examples/extender.yml:73-80); flat keys also accepted.
        server_block = raw.get("server") or {}
        ca_files = server_block.get("client-ca-files") or []
        autoscaler_block = raw.get("autoscaler") or {}
        solver_block = raw.get("solver") or {}
        mesh_block = solver_block.get("mesh") or {}
        ha_block = raw.get("ha") or {}
        fleet_block = raw.get("fleet") or {}
        trace_block = raw.get("trace") or {}
        extender_block = raw.get("extender") or {}
        retry_block = raw.get("retry") or {}
        policy_block = raw.get("policy") or {}
        defrag_block = policy_block.get("defrag") or {}

        def block_key(block, key, default):
            # Present-but-null keys (`device-pool:` with no value) must
            # read as the default, not None — same YAML idiom the
            # autoscaler block defends against.
            v = block.get(key)
            return default if v is None else v

        def autoscaler_key(key, default):
            return block_key(autoscaler_block, key, default)
        return cls(
            fifo=bool(raw.get("fifo", False)),
            fifo_config=fifo_cfg,
            binpack_algo=raw.get("binpack-algo", "tightly-pack"),
            instance_group_label=raw.get("instance-group-label", "instance-group"),
            async_client_retry_count=int(raw.get("async-client-retry-count", 5)),
            unschedulable_pod_timeout_s=_parse_duration(
                raw.get("unschedulable-pod-timeout", 600.0)
            ),
            should_schedule_dynamically_allocated_executors_in_same_az=bool(
                raw.get(
                    "should-schedule-dynamically-allocated-executors-in-same-az",
                    False,
                )
            ),
            driver_prioritized_node_label=label_prio("driver-prioritized-node-label"),
            executor_prioritized_node_label=label_prio("executor-prioritized-node-label"),
            port=int(server_block.get("port", raw.get("port", 8484))),
            batched_admission=bool(raw.get("batched-admission", True)),
            metrics_log=raw.get("metrics-log"),
            kube_api_url=raw.get("kube-api-url"),
            conversion_webhook_url=raw.get("conversion-webhook-url"),
            durable_store_path=raw.get("durable-store-path"),
            cert_file=server_block.get("cert-file", raw.get("cert-file")),
            key_file=server_block.get("key-file", raw.get("key-file")),
            client_ca_files=list(ca_files),
            kube_api_insecure_skip_tls_verify=bool(
                raw.get("kube-api-insecure-skip-tls-verify", False)
            ),
            kube_api_qps=float(raw.get("qps", 5.0)),
            kube_api_burst=int(raw.get("burst", 10)),
            request_timeout_s=_parse_duration(raw.get("request-timeout", 30.0)),
            server_transport=str(
                server_block.get("transport", raw.get("transport", "threaded"))
            ),
            server_ingest=str(
                server_block.get("ingest", raw.get("ingest", "python"))
            ),
            max_body_bytes=int(
                server_block.get(
                    "max-body-bytes",
                    raw.get("max-body-bytes", 16 * 1024 * 1024),
                )
            ),
            max_connections=int(
                server_block.get(
                    "max-connections", raw.get("max-connections", 512)
                )
            ),
            shed_queue_depth=int(
                server_block.get(
                    "shed-queue-depth", raw.get("shed-queue-depth", 256)
                )
            ),
            debug_routes=bool(raw.get("debug-routes", False)),
            request_log=bool(raw.get("request-log", False)),
            predicate_max_window=int(raw.get("predicate-max-window", 32)),
            predicate_hold_ms=float(raw.get("predicate-hold-ms", 25.0)),
            autoscaler_enabled=bool(autoscaler_key("enabled", False)),
            autoscaler_max_cluster_size=int(
                autoscaler_key("max-cluster-size", 1000)
            ),
            autoscaler_idle_ttl_s=_parse_duration(
                autoscaler_key("idle-ttl", 300.0)
            ),
            autoscaler_poll_interval_s=_parse_duration(
                autoscaler_key("poll-interval", 2.0)
            ),
            autoscaler_node_cpu=str(autoscaler_key("node-cpu", "8")),
            autoscaler_node_memory=str(autoscaler_key("node-memory", "8Gi")),
            autoscaler_node_gpu=str(autoscaler_key("node-gpu", "1")),
            autoscaler_zones=list(autoscaler_key("zones", [])),
            solver_device_pool=int(block_key(solver_block, "device-pool", 1)),
            solver_mesh_groups=(
                int(v)
                if (v := block_key(mesh_block, "groups", None)) is not None
                else None
            ),
            solver_mesh_node_shards=(
                int(v)
                if (v := block_key(mesh_block, "node-shards", None))
                is not None
                else None
            ),
            solver_fuse_windows=int(
                block_key(solver_block, "fuse-windows", 1)
            ),
            solver_prune_top_k=int(
                block_key(solver_block, "prune-top-k", 0)
            ),
            solver_prune_slack=float(
                block_key(solver_block, "prune-slack", 2.0)
            ),
            solver_delta_statics=bool(
                block_key(solver_block, "delta-statics", True)
            ),
            solver_scale_tier=bool(
                block_key(solver_block, "scale-tier", False)
            ),
            solver_build_oracle=bool(
                block_key(solver_block, "build-oracle", False)
            ),
            solver_lazy_warm_start=bool(
                block_key(solver_block, "lazy-warm-start", True)
            ),
            runtime_config_path=raw.get("runtime-config-path"),
            jax_compilation_cache_dir=raw.get("jax-compilation-cache-dir"),
            flight_recorder=bool(raw.get("flight-recorder", True)),
            flight_recorder_capacity=int(
                raw.get("flight-recorder-capacity", 2048)
            ),
            trace_path=trace_block.get("path", raw.get("trace-path")),
            trace_decisions=bool(block_key(trace_block, "decisions", False)),
            ha_enabled=bool(block_key(ha_block, "enabled", False)),
            ha_replica_id=str(
                block_key(ha_block, "replica-id", "replica-0")
            ),
            ha_lease_ttl_s=_parse_duration(
                block_key(ha_block, "lease-ttl", 3.0)
            ),
            ha_heartbeat_s=(
                _parse_duration(v)
                if (v := block_key(ha_block, "heartbeat-interval", None))
                is not None
                else None
            ),
            fleet_enabled=bool(block_key(fleet_block, "enabled", False)),
            fleet_clusters=int(block_key(fleet_block, "clusters", 2)),
            fleet_max_spillover_hops=int(
                block_key(fleet_block, "max-spillover-hops", 1)
            ),
            fleet_stack_window_ms=float(
                block_key(fleet_block, "stack-window-ms", 0.0)
            ),
            resync_gap_seconds=_parse_duration(
                block_key(
                    extender_block,
                    "resync-gap-seconds",
                    raw.get("resync-gap-seconds", 15.0),
                )
            ),
            degraded_mode=str(
                block_key(server_block, "degraded-mode", "greedy")
            ),
            degraded_retry_after_s=_parse_duration(
                block_key(server_block, "degraded-retry-after", 5.0)
            ),
            quarantine_probe_s=_parse_duration(
                block_key(solver_block, "quarantine-probe", 5.0)
            ),
            retry_base_delay_s=_parse_duration(
                block_key(retry_block, "base-delay", 0.02)
            ),
            retry_multiplier=float(
                block_key(retry_block, "multiplier", 2.0)
            ),
            retry_max_delay_s=_parse_duration(
                block_key(retry_block, "max-delay", 2.0)
            ),
            breaker_failure_threshold=int(
                block_key(retry_block, "breaker-failure-threshold", 8)
            ),
            breaker_reset_timeout_s=_parse_duration(
                block_key(retry_block, "breaker-reset-timeout", 5.0)
            ),
            policy_enabled=bool(block_key(policy_block, "enabled", False)),
            policy_ordering=str(block_key(policy_block, "ordering", "fifo")),
            policy_preemption=bool(
                block_key(policy_block, "preemption", False)
            ),
            policy_max_evictions=int(
                block_key(policy_block, "max-evictions", 8)
            ),
            policy_promote_after_s=_parse_duration(
                block_key(policy_block, "promote-after", 300.0)
            ),
            policy_protected_class=str(
                block_key(policy_block, "protected-class", "system")
            ),
            policy_defrag=bool(block_key(defrag_block, "enabled", False)),
            policy_defrag_interval_s=_parse_duration(
                block_key(defrag_block, "interval", 30.0)
            ),
            policy_defrag_budget=int(block_key(defrag_block, "budget", 4)),
        )


def _parse_duration(val) -> float:
    """'10m' / '30s' / '1h' / numeric seconds -> seconds."""
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    units = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    return float(s)
