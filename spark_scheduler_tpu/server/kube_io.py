"""Kubernetes JSON wire codecs.

Decodes the k8s-shaped JSON the extender protocol carries (v1.Pod inside
`ExtenderArgs`, vendor/k8s.io/kube-scheduler/extender/v1/types.go:71-80)
into the framework's models, and node objects for the state-sync endpoints.
Only the fields the scheduler consumes are mapped (the reference reads the
same subset through client-go listers).
"""

from __future__ import annotations

from typing import Any

from spark_scheduler_tpu.models.kube import Container, Node, Pod, PodCondition
from spark_scheduler_tpu.models.resources import Resources


def _parse_time(val) -> float:
    """Missing/unparsable creationTimestamp => "now": treating it as epoch 0
    would give ~56-year pod ages, tripping stuck-pod detection and poisoning
    the wait-time histograms."""
    import time as _time

    if val is None:
        return _time.time()
    if isinstance(val, (int, float)):
        return float(val)
    import datetime

    try:
        return datetime.datetime.fromisoformat(str(val).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return _time.time()


def _resources_from_requests(requests: dict | None) -> Resources:
    requests = requests or {}
    return Resources.from_quantities(
        str(requests.get("cpu", "0")),
        str(requests.get("memory", "0")),
        str(requests.get("nvidia.com/gpu", "0")),
    )


def _containers(raw: list | None) -> list[Container]:
    out = []
    for c in raw or []:
        out.append(
            Container(
                name=c.get("name", ""),
                requests=_resources_from_requests(
                    (c.get("resources") or {}).get("requests")
                ),
            )
        )
    return out


def _node_affinity(spec: dict) -> dict[str, list[str]]:
    """Flatten requiredDuringScheduling nodeSelectorTerms matchExpressions
    (In operator) into {label: [values]} (internal/podspec.go:29-53)."""
    out: dict[str, list[str]] = {}
    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in required.get("nodeSelectorTerms") or []:
        for expr in term.get("matchExpressions") or []:
            if expr.get("operator") == "In":
                out.setdefault(expr["key"], []).extend(expr.get("values") or [])
    return out


def pod_from_k8s(raw: dict[str, Any]) -> Pod:
    meta = raw.get("metadata") or {}
    spec = raw.get("spec") or {}
    status = raw.get("status") or {}
    conditions = [
        PodCondition(
            type=c.get("type", ""),
            status=str(c.get("status", "False")).lower() == "true",
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=_parse_time(c.get("lastTransitionTime")),
        )
        for c in status.get("conditions") or []
    ]
    containers = _containers(spec.get("containers"))
    statuses = {
        cs.get("name"): cs for cs in status.get("containerStatuses") or []
    }
    for c in containers:
        cs = statuses.get(c.name)
        if cs is not None and "terminated" in (cs.get("state") or {}):
            c.terminated = True
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        creation_timestamp=_parse_time(meta.get("creationTimestamp")),
        uid=meta.get("uid", ""),
        deletion_timestamp=(
            _parse_time(meta["deletionTimestamp"])
            if meta.get("deletionTimestamp")
            else None
        ),
        scheduler_name=spec.get("schedulerName", ""),
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        node_affinity=_node_affinity(spec),
        containers=containers,
        init_containers=_containers(spec.get("initContainers")),
        phase=status.get("phase", "Pending"),
        conditions=conditions,
    )


def node_from_k8s(raw: dict[str, Any]) -> Node:
    meta = raw.get("metadata") or {}
    spec = raw.get("spec") or {}
    status = raw.get("status") or {}
    alloc = status.get("allocatable") or {}
    ready = True
    for c in status.get("conditions") or []:
        if c.get("type") == "Ready":
            ready = str(c.get("status", "True")).lower() == "true"
    return Node(
        name=meta.get("name", ""),
        allocatable=Resources.from_quantities(
            str(alloc.get("cpu", "0")),
            str(alloc.get("memory", "0")),
            str(alloc.get("nvidia.com/gpu", "0")),
            round_up=False,
        ),
        labels=dict(meta.get("labels") or {}),
        unschedulable=bool(spec.get("unschedulable", False)),
        ready=ready,
        creation_timestamp=_parse_time(meta.get("creationTimestamp")),
    )


def _requests_to_k8s(res: Resources) -> dict:
    from spark_scheduler_tpu.models.resources import resources_to_quantity_map

    return resources_to_quantity_map(res)


def pod_to_k8s(pod: Pod) -> dict[str, Any]:
    """Inverse of pod_from_k8s: emit the k8s-shaped JSON the parser reads
    back losslessly (numeric epoch timestamps are accepted by _parse_time,
    so sub-second creation times survive). Used by the durable store's
    log records and by test fixtures."""
    containers = []
    container_statuses = []
    for c in pod.containers:
        containers.append(
            {"name": c.name, "resources": {"requests": _requests_to_k8s(c.requests)}}
        )
        if c.terminated:
            container_statuses.append({"name": c.name, "state": {"terminated": {}}})
    raw: dict[str, Any] = {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "labels": dict(pod.labels),
            "annotations": dict(pod.annotations),
            "creationTimestamp": pod.creation_timestamp,
            "uid": pod.uid,
            **(
                {"deletionTimestamp": pod.deletion_timestamp}
                if pod.deletion_timestamp is not None
                else {}
            ),
        },
        "spec": {
            "schedulerName": pod.scheduler_name,
            **({"nodeName": pod.node_name} if pod.node_name else {}),
            "nodeSelector": dict(pod.node_selector),
            **(
                {
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": k,
                                                "operator": "In",
                                                "values": list(vals),
                                            }
                                            for k, vals in pod.node_affinity.items()
                                        ]
                                    }
                                ]
                            }
                        }
                    }
                }
                if pod.node_affinity
                else {}
            ),
            "containers": containers,
            "initContainers": [
                {"name": c.name, "resources": {"requests": _requests_to_k8s(c.requests)}}
                for c in pod.init_containers
            ],
        },
        "status": {
            "phase": pod.phase,
            "conditions": [
                {
                    "type": c.type,
                    "status": "True" if c.status else "False",
                    "reason": c.reason,
                    "message": c.message,
                    "lastTransitionTime": c.last_transition_time,
                }
                for c in pod.conditions
            ],
            **(
                {"containerStatuses": container_statuses}
                if container_statuses
                else {}
            ),
        },
    }
    return raw


def node_to_k8s(node: Node) -> dict[str, Any]:
    """Inverse of node_from_k8s."""
    return {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {
            "name": node.name,
            "labels": dict(node.labels),
            "creationTimestamp": node.creation_timestamp,
        },
        "spec": {"unschedulable": node.unschedulable},
        "status": {
            "allocatable": _requests_to_k8s(node.allocatable),
            "conditions": [
                {"type": "Ready", "status": "True" if node.ready else "False"}
            ],
        },
    }


def filter_result_to_k8s(result) -> dict[str, Any]:
    """ExtenderFilterResult with Go field names (types.go:86-101; the Go
    struct has no json tags, so fields serialize capitalized). Internal
    failures use the protocol's whole-request Error channel (the per-node
    messages are identical in that case)."""
    error = ""
    if result.outcome == "failure-internal" and result.failed_nodes:
        error = next(iter(result.failed_nodes.values()))
    return {
        "NodeNames": list(result.node_names),
        "FailedNodes": dict(result.failed_nodes),
        "Error": error,
    }


def extender_args_from_k8s(raw: dict[str, Any]):
    """(pod, node_names) from ExtenderArgs JSON. `NodeNames` when the
    scheduler is nodeCacheCapable (examples/extender.yml:56), else the full
    `Nodes` list."""
    pod = pod_from_k8s(raw.get("Pod") or raw.get("pod") or {})
    node_names = raw.get("NodeNames") or raw.get("nodeNames")
    if node_names is None:
        nodes = (raw.get("Nodes") or raw.get("nodes") or {}).get("items") or []
        node_names = [((n.get("metadata") or {}).get("name", "")) for n in nodes]
    return pod, list(node_names)
