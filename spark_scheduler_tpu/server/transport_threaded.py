"""Thread-per-connection HTTP transport — the stdlib `ThreadingHTTPServer`
stack the scheduler served from day one, now reduced to a pure transport:
it frames requests (Content-Length validation, Transfer-Encoding rejection,
max-body-bytes) and hands them to a `routing.SyncRoutes` table; the handler
thread blocks until the route responds. Keep-alive discipline, the
drain-before-close dance, TLS wrapping, and the per-request access log all
live here — byte-compatible with the pre-split server (the raw-socket HTTP
tests pin every edge).

This transport remains the DEFAULT (`server.transport: threaded`) until a
benched A/B proves the async event loop's ceiling on the target box
(bench.py `transport_rig_ceiling`); its thread-per-connection model is also
the simplest one to reason about under debuggers and profilers.

Ingest lanes: this transport keeps its stdlib socket framing on BOTH
`server.ingest` lanes — the native lane plugs in downstream, where
routing._parse_predicate hands predicate bodies to the C++ decoder
(server/ingest.py) instead of json.loads. The async transport is the one
that additionally swaps its framing for the native IngestConn.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from spark_scheduler_tpu.server.routing import (
    BodyTooLarge,
    Request,
    UnframeableBody,
    UnsupportedTransferEncoding,
    json_response,
)


def build_server_ssl_context(
    cert_file: str | None, key_file: str | None, client_ca_files=None
):
    """Server-side SSLContext from install-config TLS material (reference
    server.cert-file/key-file/client-ca-files, examples/extender.yml:75-80).
    `client_ca_files` (str or list) requires client certificates signed by
    ANY of the given CAs (mTLS). None when TLS is not configured. Shared by
    both transports."""
    if not cert_file:
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file or cert_file)
    if isinstance(client_ca_files, str):
        client_ca_files = [client_ca_files]
    for ca in client_ca_files or []:
        ctx.load_verify_locations(ca)
    if client_ca_files:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


class _RoutedHandler(BaseHTTPRequestHandler):
    """Framing + keep-alive discipline; every verb funnels into _dispatch
    which builds a routing.Request and writes the routes' Response."""

    # Keep-alive: without this the stdlib default (HTTP/1.0) closes the
    # connection after EVERY response, so each request pays TCP connect +
    # a fresh handler thread — measured ~6 ms/call on loopback, dwarfing
    # the actual handler work. Every response sets Content-Length, which
    # HTTP/1.1 persistent connections require.
    protocol_version = "HTTP/1.1"

    # Class attributes stamped by ThreadedTransport at construction:
    routes = None
    request_log = False
    max_body_bytes: int | None = None
    telemetry = None

    def log_message(self, *args):  # stdlib's unstructured stderr lines: quiet
        pass

    def log_request(self, code="-", size="-"):
        # Called by send_response mid-request; capture the status and defer
        # the log line to handle_one_request so it carries the FULL
        # duration (handler + response write).
        self._log_status = code

    def setup(self):
        super().setup()
        self._conn_requests = 0
        tel = self.telemetry
        if tel is not None:
            tel.on_connection_open()

    def finish(self):
        tel = self.telemetry
        if tel is not None:
            tel.on_connection_close()
        super().finish()

    def _content_length(self) -> int:
        """Validated Content-Length. Raises UnframeableBody — after flagging
        the connection for drain+close — on negative or non-numeric values
        (int() would raise / read(-1) would block to EOF) and on duplicate
        headers with differing values (RFC 7230 3.3.2: reading only the
        first would leave the rest of the body to desync the next keep-alive
        request — request smuggling)."""
        raws = self.headers.get_all("Content-Length") or []
        vals = {r.strip() for r in raws}
        length = None
        if len(vals) <= 1:
            raw = next(iter(vals), None)
            if raw is None:
                return 0
            # RFC 7230: 1*DIGIT only. Bare int() also accepts '1_6', '+16'
            # and Unicode digits — forms an RFC-strict proxy in front of us
            # would frame differently (the smuggling vector again).
            if raw.isascii() and raw.isdigit():
                length = int(raw)
            else:
                length = None
        if length is None or length < 0:
            self.close_connection = True
            self._drain_on_close = True
            raise UnframeableBody("invalid Content-Length")
        return length

    def _read_body(self) -> tuple[bytes, Exception | None]:
        """Frame the request body up front. On framing failures the error
        is DEFERRED into the Request so the route decides the status (a
        Transfer-Encoding body on a 404 route still 404s); the connection
        is flagged for drain+close where the unread bytes could desync a
        keep-alive follow-up."""
        if self.headers.get("Transfer-Encoding"):
            # No chunked decoder here — without this, a chunked POST would
            # parse as an empty body and be answered with a confidently
            # wrong success. Unframeable (and Content-Length may lie
            # alongside it): don't block in read(); close after the
            # response instead.
            self.close_connection = True
            self._drain_on_close = True
            return b"", UnsupportedTransferEncoding(
                "Transfer-Encoding not supported; send Content-Length"
            )
        try:
            length = self._content_length()
        except UnframeableBody as exc:
            return b"", exc  # never read; drained at close
        cap = self.max_body_bytes
        if cap is not None and length > cap:
            if self.telemetry is not None:
                self.telemetry.on_body_rejected()
            # Drain in bounded chunks (the body never lands in one
            # allocation) so the keep-alive framing survives the 413.
            remaining = length
            while remaining > 0:
                chunk = self.rfile.read(min(65536, remaining))
                if not chunk:
                    self.close_connection = True
                    break
                remaining -= len(chunk)
            return b"", BodyTooLarge(
                f"request body of {length} bytes exceeds max-body-bytes={cap}"
            )
        return (self.rfile.read(length) if length else b""), None

    def _dispatch(self):
        body, body_error = self._read_body()
        parsed = urlparse(self.path)
        req = Request(
            method=self.command,
            path=parsed.path,
            query=parse_qs(parsed.query),
            headers=self.headers,
            body=body,
            body_error=body_error,
        )
        self._conn_requests += 1
        tel = self.telemetry
        if tel is not None:
            tel.on_request(reused=self._conn_requests > 1)
        try:
            resp = self.routes.handle(req)
        except Exception as exc:  # last resort: never a dropped connection
            resp = json_response(500, {"error": str(exc)})
        if resp.close:
            self.close_connection = True
        self.send_response(resp.status)
        self.send_header("Content-Type", resp.content_type)
        self.send_header("Content-Length", str(len(resp.body)))
        if resp.headers:
            for name, value in resp.headers.items():
                self.send_header(name, str(value))
        if self.close_connection:
            # Advertise the close so a pipelining client doesn't race its
            # next request onto a socket we're about to shut.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(resp.body)
        if tel is not None:
            tel.on_bytes_out(len(resp.body))

    do_GET = _dispatch
    do_POST = _dispatch
    do_PUT = _dispatch
    do_DELETE = _dispatch

    def parse_request(self):
        # Request-log clock: started AFTER the request line arrived, so a
        # keep-alive connection's idle wait for the client's next request
        # never counts into the logged duration.
        self._req_start = time.monotonic()
        return super().parse_request()

    def handle_one_request(self):
        self._drain_on_close = False
        self._log_status = None
        self._req_start = None
        super().handle_one_request()
        start = self._req_start
        if self.request_log and self._log_status is not None and start is not None:
            from spark_scheduler_tpu.tracing import svc1log

            headers = getattr(self, "headers", None)
            try:
                status = int(self._log_status)
            except (TypeError, ValueError):  # send_error's "-" placeholder
                status = 0
            svc1log().request(
                getattr(self, "command", "-") or "-",
                getattr(self, "path", "-") or "-",
                status,
                int((time.monotonic() - start) * 1e6),
                protocol=self.protocol_version,
                trace_id=(
                    headers.get("X-B3-TraceId") or headers.get("x-b3-traceid")
                )
                if headers
                else None,
            )
        # An unframeable body (Transfer-Encoding, garbage Content-Length)
        # was answered without being read; close the connection so the
        # unread bytes can never desync a subsequent request on the
        # persistent socket.
        if self._drain_on_close:
            self.close_connection = True
            # Drain the unread body so close() sends FIN, not RST (unread
            # receive data at close resets the connection on Linux and can
            # destroy the in-flight response). The body usually rode in
            # with the headers and sits read-ahead in rfile's user-space
            # buffer — invisible to connection.recv — so consume that
            # first, non-blocking.
            try:
                self.connection.setblocking(False)
                while self.rfile.read1(65536):
                    pass
            except (OSError, ValueError):
                pass
            # Then a short timed kernel drain for bytes still in flight,
            # bounded in bytes and wall time so a client streaming forever
            # can't pin the handler thread.
            try:
                self.connection.settimeout(0.05)
                budget = 1 << 18
                deadline = time.monotonic() + 1.0
                while budget > 0 and time.monotonic() < deadline:
                    got = self.connection.recv(65536)
                    if not got:
                        break
                    budget -= len(got)
            except OSError:
                pass


class _Server(ThreadingHTTPServer):
    # Default listen backlog (5) resets connections under a concurrent
    # client burst — exactly the load the predicate batcher exists for.
    request_queue_size = 128


def _run_threaded(server: ThreadingHTTPServer, name: str) -> threading.Thread:
    thread = threading.Thread(target=server.serve_forever, daemon=True, name=name)
    thread.start()
    return thread


def _maybe_wrap_tls(
    server: ThreadingHTTPServer,
    cert_file: str | None,
    key_file: str | None,
    client_ca_files=None,
    handshake_timeout_s: float = 30.0,
) -> bool:
    """Serve HTTPS when a cert/key pair is configured — the witchcraft
    server slot. Returns True if TLS was enabled.

    The TLS handshake runs PER CONNECTION in the worker thread (via a
    finish_request override), never in the accept loop: a client that
    stalls mid-handshake ties up one bounded-timeout worker, not the whole
    server."""
    ctx = build_server_ssl_context(cert_file, key_file, client_ca_files)
    if ctx is None:
        return False
    import ssl

    orig_finish_request = server.finish_request

    def finish_request(request, client_address):
        # ThreadingMixIn calls finish_request from the per-connection worker
        # thread; the handshake happens here under a timeout.
        try:
            request.settimeout(handshake_timeout_s)
            tls_request = ctx.wrap_socket(request, server_side=True)
        except (OSError, ssl.SSLError):
            try:
                request.close()
            except OSError:
                pass
            return
        orig_finish_request(tls_request, client_address)

    server.finish_request = finish_request
    return True


class ThreadedTransport:
    """Transport facade the server front-ends drive: bind at construction
    (ephemeral ports resolve immediately), serve on start(), drain on
    stop()."""

    def __init__(
        self,
        routes,
        host: str = "127.0.0.1",
        port: int = 8484,
        *,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        request_log: bool = False,
        max_body_bytes: int | None = None,
        telemetry=None,
        name: str = "scheduler-http",
    ):
        # Socket read timeout per connection: a stalled client cannot pin a
        # handler thread forever (the extender protocol budget is 30 s,
        # examples/extender.yml:59).
        handler = type(
            "Handler",
            (_RoutedHandler,),
            {
                "routes": routes,
                "request_log": request_log,
                "max_body_bytes": max_body_bytes,
                "telemetry": telemetry,
                "timeout": request_timeout_s,
            },
        )
        self._handler_cls = handler
        self._name = name
        self._server = _Server((host, port), handler)
        self.telemetry = telemetry
        self.tls = _maybe_wrap_tls(
            self._server,
            cert_file,
            key_file,
            client_ca_files,
            handshake_timeout_s=request_timeout_s,
        )
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def set_request_log(self, enabled: bool) -> None:
        self._handler_cls.request_log = enabled

    def start(self) -> None:
        self._thread = _run_threaded(self._server, self._name)

    def stop(self) -> None:
        # shutdown() blocks on serve_forever()'s exit handshake — only call
        # it if serving actually started (Ctrl-C can land before start()
        # finished, e.g. during the pre-start cache-sync wait).
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
        self._server.server_close()

    def join(self) -> None:
        """Block until the serving thread exits (after start())."""
        if self._thread is not None:
            self._thread.join()
