"""Serving layer: install config, dependency wiring, extender-protocol HTTP
front-end, conversion webhook. Rebuilds cmd/ + config/ of the reference."""

from spark_scheduler_tpu.server.config import InstallConfig  # noqa: F401
from spark_scheduler_tpu.server.app import SchedulerApp, build_scheduler_app  # noqa: F401
