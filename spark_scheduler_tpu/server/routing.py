"""Transport-agnostic serving core: request/response model + route tables.

The HTTP front-end used to fuse transport, routing, and handlers into
stdlib `BaseHTTPRequestHandler` subclasses; the round-5 numbers showed the
served path capped at the stdlib stack's own ceiling (96.6% of the
null-handler rig), so the stack is now layered:

  transport  (server/transport_threaded.py, server/transport_async.py)
      owns sockets, framing (Content-Length validation, Transfer-Encoding
      rejection, max-body-bytes), keep-alive discipline, TLS, timeouts,
      and writes — and hands each framed request here;
  routing    (this module)
      owns the URL table and every handler body: the extender protocol,
      state-sync, metrics/debug surfaces, conversion. Handlers are plain
      `Request -> Response` functions with no socket awareness, so both
      transports serve byte-identical routes.

The predicate route has TWO entry points: `handle` blocks the calling
thread on `PredicateBatcher.submit` (the threaded transport's model — one
handler thread per connection), while `handle_nowait` registers a
completion callback via `PredicateBatcher.submit_nowait` and returns
immediately (the async transport's model — the event loop must never block
on a device solve; the batcher's dispatcher thread was always the real
serialization point, so parked handler threads bought nothing).
"""

from __future__ import annotations

import dataclasses
import json
import threading


class UnframeableBody(ValueError):
    """The request body's length cannot be determined safely (client
    framing error — mapped to a 400, and the connection is closed)."""


class UnsupportedTransferEncoding(UnframeableBody):
    """Request body uses Transfer-Encoding (no chunked decoder here)."""


class BodyTooLarge(ValueError):
    """Request body exceeds `server.max-body-bytes` — mapped to a 413
    after the transport drained the body (keep-alive framing survives)."""


def error_code(exc: Exception) -> int:
    # Client framing errors are 4xx, not server failures (a 500 would
    # count against server error budgets and invite pointless retries).
    if isinstance(exc, BodyTooLarge):
        return 413
    return 400 if isinstance(exc, UnframeableBody) else 500


@dataclasses.dataclass
class Request:
    """One framed HTTP request, transport-independent.

    `headers` is any case-insensitive mapping with `.get` (the stdlib
    email.Message for the threaded transport, the async transport's
    `Headers`). `body_error` carries a framing failure the transport
    deferred so the ROUTE decides the status (a Transfer-Encoding body on
    a 404 route must still 404 — pinned by the HTTP tests)."""

    method: str
    path: str
    query: dict
    headers: object
    body: bytes = b""
    body_error: Exception | None = None
    # Native-ingest hand-off: the transport already decoded the predicate
    # body into a (pod, node_names) ticket — the route must not re-parse.
    predicate_parsed: object = None
    # The transport already TRIED the native decoder (hit or miss): on a
    # miss the route must go straight to the Python parser instead of
    # re-tokenizing the same ~200 KB body a second time.
    native_decode_attempted: bool = False

    def json(self):
        if self.body_error is not None:
            raise self.body_error
        return json.loads(self.body or b"{}")

    def q(self, name: str):
        vals = self.query.get(name)
        return vals[0] if vals else None


@dataclasses.dataclass
class Response:
    status: int
    body: bytes
    content_type: str = "application/json"
    close: bool = False  # transport must close the connection after writing
    # Extra response headers (e.g. Retry-After on degraded-mode sheds).
    # None on the hot path — transports only walk it when set.
    headers: dict | None = None


def json_response(
    status: int, payload, close: bool = False, headers: dict | None = None
) -> Response:
    return Response(
        status, json.dumps(payload).encode(), close=close, headers=headers
    )


def text_response(status: int, text: str, content_type: str) -> Response:
    return Response(status, text.encode(), content_type)


_NOT_FOUND = {"error": "not found"}

# Canned hot-path bodies: liveness/readiness probes and 404s are hit every
# scrape interval (and 404 floods under misconfigured probes); re-running
# json.dumps per request for a constant payload was pure GIL time. Bytes
# are json.dumps-identical (pinned by tests/test_ingest_native.py).
_NOT_FOUND_BODY = json.dumps(_NOT_FOUND).encode()
_LIVENESS_BODY = json.dumps({"status": "up"}).encode()
_READY_BODY = json.dumps({"ready": True}).encode()
_NOT_READY_BODY = json.dumps({"ready": False}).encode()
# Queue-depth 503s vary only in the depth digit — splice it in.
_SHED_PRE = b'{"error": "scheduler overloaded", "queue_depth": '


def not_found_response() -> Response:
    return Response(404, _NOT_FOUND_BODY)


# ------------------------------------------------- filter-result encoding

# Serialized FailedNodes maps keyed by (candidate-names key, message): a
# fleet's failure storms repeat the SAME uniform 10k-entry map per
# candidate list, and json.dumps of that map (~ms at 10k nodes) dominated
# the failure path. The key is the node_names object itself when it
# carries a content digest (the native lane's NativeNodeNames — hash is
# the digest, equality memcmps the blob) or a tuple of the names
# otherwise; either way a colliding hash cannot alias two lists.
_FAILED_MAP_CACHE_CAP = 32
_failed_map_cache = None


def _encode_failed_nodes(failed: dict, node_names) -> bytes:
    payload = None
    if (
        node_names is not None
        and len(failed) == len(node_names) > 8
    ):
        vals = iter(failed.values())
        first = next(vals)
        if all(v == first for v in vals) and all(
            a is b or a == b for a, b in zip(failed, node_names)
        ):
            global _failed_map_cache
            if _failed_map_cache is None:
                from spark_scheduler_tpu.core.lru import LRUCache

                _failed_map_cache = LRUCache(_FAILED_MAP_CACHE_CAP)
            key_names = (
                node_names
                if getattr(node_names, "names_digest", None) is not None
                else tuple(node_names)
            )
            key = (key_names, first)
            payload = _failed_map_cache.get(key)
            if payload is None:
                payload = json.dumps(dict(failed)).encode()
                _failed_map_cache.put(key, payload)
            return payload
    return json.dumps(dict(failed)).encode()


def encode_filter_result(result, node_names=None) -> bytes:
    """ExtenderFilterResult response bytes, byte-identical to
    `json.dumps(filter_result_to_k8s(result))` (test-pinned) without
    re-serializing the hot shapes: the success body is a template splice
    around the decision bytes, and uniform failure maps reuse the cached
    per-candidate-list fragment."""
    error = ""
    if result.outcome == "failure-internal" and result.failed_nodes:
        error = next(iter(result.failed_nodes.values()))
    names = ", ".join(json.dumps(n) for n in result.node_names)
    if result.failed_nodes:
        failed = _encode_failed_nodes(result.failed_nodes, node_names)
    else:
        failed = b"{}"
    return (
        b'{"NodeNames": ['
        + names.encode()
        + b'], "FailedNodes": '
        + failed
        + b', "Error": '
        + json.dumps(error).encode()
        + b"}"
    )


class SyncRoutes:
    """Base routing contract both transports drive. Synchronous-only route
    tables implement `handle`; `handle_nowait` falls through to it."""

    def handle(self, req: Request) -> Response:
        raise NotImplementedError

    def handle_nowait(self, req: Request, respond, schedule_timeout=None):
        """CPS entry for event-loop transports: `respond(Response)` exactly
        once, now or later from any thread. `schedule_timeout(delay_s, cb)`
        (optional) arms a transport timer and returns a handle with
        `.cancel()`."""
        respond(self.handle(req))


class ConversionRoutes(SyncRoutes):
    """The standalone conversion webhook's table: liveness + POST /convert
    (the reference ships this as a second binary,
    spark-scheduler-conversion-webhook/cmd/server.go:39-54)."""

    def handle(self, req: Request) -> Response:
        if req.method == "GET" and req.path == "/status/liveness":
            return Response(200, _LIVENESS_BODY)
        if req.method == "POST" and req.path == "/convert":
            return _convert(req)
        return not_found_response()


def _convert(req: Request) -> Response:
    from spark_scheduler_tpu.server.conversion import convert_review

    try:
        review = req.json()
    except Exception as exc:
        code = 413 if isinstance(exc, BodyTooLarge) else 400
        return json_response(code, {"error": str(exc)})
    return json_response(200, convert_review(review))


class SchedulerRoutes(SyncRoutes):
    """The scheduler front-end's full table (cmd/endpoints.go:28-42 plus
    the state-sync/debug/metrics surfaces — see server/http.py's module
    docstring for the route list)."""

    def __init__(self, server):
        # The owning SchedulerHTTPServer: app, registry, batcher, ready
        # event, debug_routes flag, shed/timeout knobs, transport stats.
        self._s = server

    # ------------------------------------------------------------- dispatch

    def handle(self, req: Request) -> Response:
        if req.method == "POST" and req.path == "/predicates":
            return self._predicate_blocking(req)
        return self._handle_common(req)

    def handle_nowait(self, req: Request, respond, schedule_timeout=None):
        if req.method == "POST" and req.path == "/predicates":
            self._predicate_nowait(req, respond, schedule_timeout)
            return
        respond(self._handle_common(req))

    def _handle_common(self, req: Request) -> Response:
        try:
            if req.method == "GET":
                return self._get(req)
            if req.method == "POST":
                return self._post(req)
            if req.method == "PUT":
                return self._put(req)
            if req.method == "DELETE":
                return self._delete(req)
        except Exception as exc:  # route bodies own their error mapping;
            # this is the last-resort 500 (never a dropped connection)
            return json_response(500, {"error": str(exc)})
        return not_found_response()

    # ------------------------------------------------------------------ GET

    def _get(self, req: Request) -> Response:
        s = self._s
        path = req.path
        if path == "/status/liveness":
            return Response(200, _LIVENESS_BODY)
        if path == "/status/readiness":
            ha = getattr(s, "ha", None)
            if ha is not None and not s.ready.is_set() and s.app.backend.list_nodes():
                # HA replicas receive cluster state by TAILING the shared
                # backend (WAL poll / event bus), never through the
                # PUT /state/nodes that flips `ready` on a standalone
                # server — without this re-check a promoted standby would
                # answer 503 forever and kube would never route to it.
                s.ready.set()
            degraded = getattr(s.app.solver, "degraded", None)
            deg_active = degraded is not None and degraded.active
            if ha is not None:
                # HA replica: ready = state synced AND a serving role
                # (leader / active shard member). Standbys answer 503 with
                # the role so kube routes traffic to the leader while the
                # warm replica stays probeable. Degraded mode composes:
                # a shedding leader must flip 503 too, or the load
                # balancer never drains the replica that answers every
                # predicate 503 — exactly the multi-replica topology
                # where draining elsewhere is the point of shed.
                up = (
                    s.ready.is_set()
                    and ha.is_serving()
                    and not (deg_active and degraded.sheds)
                )
                body = {"ready": up, "role": ha.role}
                if deg_active:
                    body.update(
                        degraded=True,
                        policy=degraded.policy,
                        reason=degraded.reason,
                    )
                return json_response(200 if up else 503, body)
            if deg_active:
                # Degraded mode (ISSUE 9): with the greedy policy the
                # replica still serves (host fallback) — stay ready but
                # say so; with shed it answers predicates 503, so flip
                # readiness too and let load balancers drain it while
                # probes keep watching.
                up = s.ready.is_set() and not degraded.sheds
                return json_response(
                    200 if up else 503,
                    {
                        "ready": up,
                        "degraded": True,
                        "policy": degraded.policy,
                        "reason": degraded.reason,
                    },
                )
            up = s.ready.is_set()
            return Response(
                200 if up else 503, _READY_BODY if up else _NOT_READY_BODY
            )
        if path == "/debug/ha" and getattr(s, "ha", None) is not None:
            # Operational surface (role, lease epoch/age, tailer counters):
            # served whenever HA is wired — failover forensics must not
            # depend on the debug-routes opt-in.
            return json_response(200, s.ha.state())
        if path == "/debug/fleet" and getattr(s, "fleet", None) is not None:
            # Fleet surface (router picks, spillovers, per-cluster
            # aggregates): served whenever the facade is wired — same
            # always-on rule as /debug/ha.
            return json_response(200, s.fleet.state())
        if path == "/metrics":
            return self._metrics(req)
        if path == "/debug/traces" and s.debug_routes:
            from spark_scheduler_tpu.tracing import tracer

            return json_response(200, {"spans": tracer().finished_spans()})
        if path == "/debug/decisions" and s.debug_routes:
            return self._debug_decisions(req)
        if path == "/debug/trace" and s.debug_routes:
            tw = getattr(s.app, "trace_writer", None)
            if tw is None:
                return json_response(404, {"error": "trace sink disabled"})
            body = tw.stats()
            # Last in-process multi-arm sweep (ISSUE 18), when one ran —
            # the replay counters live next to the trace they replayed.
            from spark_scheduler_tpu.replay.sweep import (
                last_sweep_telemetry,
            )

            replay = last_sweep_telemetry()
            if replay:
                body = dict(body, replay=replay)
            return json_response(200, body)
        if path == "/debug/state" and s.debug_routes:
            from spark_scheduler_tpu.observability import debug_state_snapshot

            return json_response(200, debug_state_snapshot(s.app))
        return not_found_response()

    def _metrics(self, req: Request) -> Response:
        s = self._s
        # Compile gauges are pull-synced: the jax.monitoring listener feeds
        # process totals, the scrape publishes.
        telemetry = getattr(s.app.solver, "telemetry", None)
        if telemetry is not None:
            telemetry.sync_compile_gauges()
        snap = s.registry.snapshot() if s.registry else {}
        fmt = req.q("format") or ""
        accept = req.headers.get("Accept", "") or ""
        from spark_scheduler_tpu.observability import (
            prefers_prometheus,
            render_prometheus,
        )

        if fmt == "prometheus" or (fmt != "json" and prefers_prometheus(accept)):
            # Prometheus text exposition: the pull surface for scrape
            # stacks (`?format=` forces either way).
            extra = {
                f"foundry.spark.scheduler.predicate.batcher.{k}": v
                for k, v in s.batcher.stats().items()
                if isinstance(v, (int, float))
            }
            extra.update(
                {
                    f"foundry.spark.scheduler.server.{k}": v
                    for k, v in s.transport_stats().items()
                    if isinstance(v, (int, float))
                }
            )
            ingest_stats = getattr(s, "ingest_stats", dict)()
            extra.update(
                {
                    f"foundry.spark.scheduler.server.ingest.{k}": v
                    for k, v in ingest_stats.items()
                    if isinstance(v, (int, float))
                }
            )
            recorder = getattr(s.app, "recorder", None)
            if recorder is not None:
                # ring-overflow drops are THE signal that forensic history
                # is being lost — export alongside the other ring stats
                extra.update(
                    {
                        f"foundry.spark.scheduler.recorder.{k}": v
                        for k, v in recorder.stats().items()
                        if isinstance(v, (int, float))
                    }
                )
            tw = getattr(s.app, "trace_writer", None)
            if tw is not None:
                extra.update(
                    {
                        f"foundry.spark.scheduler.trace.{k}": v
                        for k, v in tw.stats().items()
                        if isinstance(v, (int, float))
                    }
                )
            return text_response(
                200,
                render_prometheus(snap, extra_gauges=extra),
                "text/plain; version=0.0.4",
            )
        snap["predicate_batcher"] = s.batcher.stats()
        snap["server_transport"] = s.transport_stats()
        snap["server_ingest"] = getattr(s, "ingest_stats", dict)()
        recorder = getattr(s.app, "recorder", None)
        if recorder is not None:
            snap["flight_recorder"] = recorder.stats()
        tw = getattr(s.app, "trace_writer", None)
        if tw is not None:
            snap["trace"] = tw.stats()
        return json_response(200, snap)

    def _debug_decisions(self, req: Request) -> Response:
        recorder = getattr(self._s.app, "recorder", None)
        if recorder is None:
            return json_response(404, {"error": "flight recorder disabled"})
        try:
            limit = int(req.q("limit") or 100)
        except ValueError:
            return json_response(400, {"error": "bad limit"})
        since_seq = req.q("since_seq")
        if since_seq is not None:
            try:
                since_seq = int(since_seq)
            except ValueError:
                return json_response(400, {"error": "bad since_seq"})
        return json_response(
            200,
            {
                "decisions": recorder.query(
                    # `app_id` aliases `app` (the label the records carry)
                    app=req.q("app") or req.q("app_id"),
                    verdict=req.q("verdict"),
                    role=req.q("role"),
                    namespace=req.q("namespace"),
                    limit=limit,
                    instance_group=req.q("instance_group"),
                    since_seq=since_seq,
                ),
                "recorder": recorder.stats(),
            },
        )

    # ----------------------------------------------------------------- POST

    def _post(self, req: Request) -> Response:
        s = self._s
        if req.path == "/convert":
            return _convert(req)
        if req.path == "/debug/profile/start" and s.debug_routes:
            return self._profile_start(req)
        if req.path == "/debug/profile/stop" and s.debug_routes:
            from spark_scheduler_tpu.tracing import stop_jax_profile

            try:
                out_dir = stop_jax_profile()
            except Exception as exc:
                return json_response(500, {"profiling": False, "error": str(exc)})
            return json_response(
                200 if out_dir else 409, {"profiling": False, "dir": out_dir}
            )
        return not_found_response()

    def _profile_start(self, req: Request) -> Response:
        from spark_scheduler_tpu.tracing import start_jax_profile

        try:
            body = req.json()
        except (UnframeableBody, BodyTooLarge) as exc:
            # The body (with its would-be "dir") was never read — reject
            # rather than silently profiling into the default dir.
            return json_response(error_code(exc), {"error": str(exc)})
        except Exception:
            body = {}  # empty/garbage body: defaults are fine
        if not isinstance(body, dict):
            body = {}
        log_dir = body.get("dir") or "/tmp/spark-scheduler-jax-trace"
        try:
            started = start_jax_profile(log_dir)
        except Exception as exc:  # unwritable dir etc.
            return json_response(500, {"profiling": False, "error": str(exc)})
        return json_response(
            200 if started else 409, {"profiling": started, "dir": log_dir}
        )

    # ------------------------------------------------------------ PUT/DELETE

    def _put(self, req: Request) -> Response:
        from spark_scheduler_tpu.server.kube_io import node_from_k8s, pod_from_k8s

        s = self._s
        try:
            if req.path == "/state/nodes":
                node = node_from_k8s(req.json())
                existing = s.app.backend.get_node(node.name)
                if existing is None:
                    s.app.backend.add_node(node)
                else:
                    s.app.backend.update("nodes", node)
                s.ready.set()  # first synced node => ready
                return json_response(200, {"applied": node.name})
            if req.path == "/state/pods":
                pod = pod_from_k8s(req.json())
                if s.app.backend.get("pods", pod.namespace, pod.name) is None:
                    s.app.backend.add_pod(pod)
                else:
                    s.app.backend.update_pod(pod)
                return json_response(200, {"applied": pod.name})
            return not_found_response()
        except Exception as exc:
            return json_response(error_code(exc), {"error": str(exc)})

    def _delete(self, req: Request) -> Response:
        s = self._s
        try:
            parts = req.path.strip("/").split("/")
            if len(parts) == 4 and parts[:2] == ["state", "pods"]:
                ns, name = parts[2], parts[3]
                pod = s.app.backend.get("pods", ns, name)
                if pod is None:
                    return json_response(404, {"error": "pod not found"})
                s.app.backend.delete_pod(pod)
                return json_response(200, {"deleted": name})
            return not_found_response()
        except Exception as exc:  # e.g. concurrent-delete race
            return json_response(500, {"error": str(exc)})

    # ----------------------------------------------------------- predicates

    def _parse_predicate(self, req: Request):
        """(pod, node_names) for POST /predicates, by lane:

          - the async transport's native framer may have decoded the body
            already (`req.predicate_parsed` — the zero-copy ticket);
          - a binary-protocol body decodes natively when the codec is
            loaded, through the pure-Python decoder otherwise;
          - a JSON body tries the native fast path on the native lane, and
            ANY deviation falls back to the Python parser below —
            identical decisions either way, the miss is telemetry.
        """
        parsed = req.predicate_parsed
        if parsed is not None:
            return parsed
        if req.body_error is not None:
            raise req.body_error
        from spark_scheduler_tpu.server import ingest
        from spark_scheduler_tpu.server.kube_io import extender_args_from_k8s

        codec = None
        if not req.native_decode_attempted:
            codec = getattr(self._s, "ingest_codec", None)
        if ingest.is_binary_content_type(req.headers.get("Content-Type")):
            if codec is not None:
                parsed = codec.decode_predicate_body(req.body, binary=True)
                if parsed is not None:
                    return parsed
            return ingest.decode_predicate_binary_py(req.body)
        if codec is not None:
            parsed = codec.decode_predicate_body(req.body, binary=False)
            if parsed is not None:
                return parsed
        return extender_args_from_k8s(req.json())

    def _shed_response(self) -> Response | None:
        """503 load shedding tied to the batcher queue depth: a backlog the
        window solver will never catch up on is answered immediately
        instead of parking it until the request timeout (overload would
        otherwise spiral — dead entries crowd out live ones)."""
        s = self._s
        threshold = s.shed_queue_depth
        if not threshold:
            return None
        depth = s.batcher.queue_depth()  # one lock round-trip per check
        if depth >= threshold:
            s.on_queue_shed()
            return Response(503, _SHED_PRE + str(depth).encode() + b"}")
        return None

    @staticmethod
    def _predicate_ok(pod, result, node_names=None) -> Response:
        from spark_scheduler_tpu.tracing import pod_safe_params, svc1log

        svc1log().info(
            "predicate",
            outcome=result.outcome,
            nodes=list(result.node_names),
            **pod_safe_params(pod),
        )
        return Response(200, encode_filter_result(result, node_names))

    @staticmethod
    def _predicate_err(pod, exc) -> Response:
        # Internal errors ride the protocol's Error channel
        # (ExtenderFilterResult.Error) so kube-scheduler gets a well-formed
        # response instead of a dropped connection.
        from spark_scheduler_tpu.faults.errors import DegradedUnavailableError
        from spark_scheduler_tpu.tracing import pod_safe_params, svc1log

        if isinstance(exc, DegradedUnavailableError):
            # Degraded-mode shed (ISSUE 9): no device can serve and the
            # policy is "shed" — a 503 with Retry-After, NOT a protocol
            # Error (the kube-scheduler extender client retries 5xx; an
            # Error would fail the pod's scheduling cycle outright).
            svc1log().warn(
                "predicate shed: degraded mode",
                error=str(exc),
                retryAfterS=exc.retry_after_s,
                **pod_safe_params(pod),
            )
            return json_response(
                503,
                {"error": str(exc), "degraded": True},
                headers={"Retry-After": str(int(max(1, exc.retry_after_s)))},
            )
        svc1log().error(
            "predicate failed", error=repr(exc), **pod_safe_params(pod)
        )
        return json_response(
            200, {"NodeNames": [], "FailedNodes": {}, "Error": str(exc)}
        )

    def _predicate_blocking(self, req: Request) -> Response:
        """Threaded-transport path: the handler thread parks in
        `batcher.submit` until its window completes."""
        from spark_scheduler_tpu.core.extender import ExtenderArgs
        from spark_scheduler_tpu.tracing import tracer

        s = self._s
        try:
            pod, node_names = self._parse_predicate(req)
        except Exception as exc:
            return json_response(error_code(exc), {"Error": str(exc)})
        shed = self._shed_response()
        if shed is not None:
            return shed
        # Fleet mode: the facade routes to the home cluster's own stack
        # (bypassing this endpoint's batcher — each cluster serializes on
        # its own worker). `?cluster=N` tags which cluster endpoint the
        # caller believed it hit; wrong-cluster calls are forwarded and
        # counted, decisions byte-identical either way.
        fleet = getattr(s, "fleet", None)
        if fleet is not None:
            via = req.q("cluster")
            with tracer().root_from_headers(
                req.headers, "predicate", pod=f"{pod.namespace}/{pod.name}"
            ) as root:
                try:
                    decision = fleet.schedule(
                        pod,
                        node_names or None,
                        via=int(via) if via is not None else None,
                    )
                except Exception as exc:
                    root.tag("outcome", "failure-internal")
                    return self._predicate_err(pod, exc)
                root.tag("outcome", decision.result.outcome)
                root.tag("cluster", str(decision.cluster))
                return self._predicate_ok(pod, decision.result, node_names)
        # Root span continues the caller's b3 trace context (the
        # witchcraft tracing middleware slot).
        with tracer().root_from_headers(
            req.headers, "predicate", pod=f"{pod.namespace}/{pod.name}"
        ) as root:
            try:
                result = s.batcher.submit(
                    ExtenderArgs(pod=pod, node_names=node_names),
                    timeout=s.request_timeout_s,
                )
            except Exception as exc:
                root.tag("outcome", "failure-internal")
                return self._predicate_err(pod, exc)
            root.tag("outcome", result.outcome)
            return self._predicate_ok(pod, result, node_names)

    def _predicate_nowait(self, req: Request, respond, schedule_timeout):
        """Event-loop path: no thread parks. The batcher invokes `done`
        from its dispatcher thread when the window completes; a transport
        timer sheds the entry at the request timeout. Exactly one respond
        fires whichever side wins the race."""
        from spark_scheduler_tpu.core.extender import ExtenderArgs
        from spark_scheduler_tpu.tracing import tracer

        s = self._s
        try:
            pod, node_names = self._parse_predicate(req)
        except Exception as exc:
            respond(json_response(error_code(exc), {"Error": str(exc)}))
            return
        shed = self._shed_response()
        if shed is not None:
            respond(shed)
            return
        # Detached root span: the event loop's span stack cannot hold it
        # open across interleaved requests, so it is begun/finished by
        # hand and carried to the dispatcher via the batcher entry (the
        # same trace-context slot the threaded path populates).
        ctx = tracer().root_from_headers(
            req.headers, "predicate", pod=f"{pod.namespace}/{pod.name}"
        )
        span = ctx.span
        tracer().begin_detached(span)
        lock = threading.Lock()
        state = {"sent": False, "timer": None}

        def claim() -> bool:
            """First winner (completion vs timeout) responds; the loser's
            late call is a no-op — the span, log line, and response are
            all written exactly once."""
            with lock:
                if state["sent"]:
                    return False
                state["sent"] = True
            timer = state["timer"]
            if timer is not None:
                try:
                    timer.cancel()
                except Exception:
                    pass
            return True

        def done(result, exc):
            if not claim():
                return
            # Attach the detached root while building the response so the
            # svc1log line carries the caller's trace id, exactly like the
            # threaded path's in-span logging.
            with tracer().attach(span):
                if exc is not None:
                    span.tags["outcome"] = "failure-internal"
                    resp = self._predicate_err(pod, exc)
                else:
                    span.tags["outcome"] = result.outcome
                    resp = self._predicate_ok(pod, result, node_names)
            tracer().finish_detached(span)
            respond(resp)

        try:
            entry = s.batcher.submit_nowait(
                ExtenderArgs(pod=pod, node_names=node_names),
                done,
                trace_span=span,
            )
        except Exception as exc:  # shutdown race
            done(None, exc)
            return
        if schedule_timeout is not None and s.request_timeout_s:

            def on_timeout():
                # Shed the abandoned entry if the dispatcher has not
                # claimed it; a claimed entry's solve proceeds and its
                # late `done` loses the claim race harmlessly.
                s.batcher.abandon(entry)
                if not claim():
                    return
                span.tags["outcome"] = "failure-internal"
                with tracer().attach(span):
                    resp = self._predicate_err(
                        pod, TimeoutError("predicate window timed out")
                    )
                tracer().finish_detached(span)
                respond(resp)

            state["timer"] = schedule_timeout(s.request_timeout_s, on_timeout)
