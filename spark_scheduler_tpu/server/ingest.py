"""Ingest lanes: how a framed POST /predicates body becomes ExtenderArgs.

Two lanes, selected by `server.ingest` (install YAML `server.ingest:
python|native`, CLI `--ingest`):

  python (default)  the route parses the body with json.loads and walks the
                    k8s-shaped dict (server/kube_io.extender_args_from_k8s)
                    — ~200 KB of JSON and ~10k PyUnicode/dict allocations
                    per request at 10k nodes, all under the GIL the batcher
                    is competing for.
  native            native/runtime.cpp tokenizes the body into a reusable
                    arena slot: the pod sub-document (a ~1 KB JSON span —
                    still parsed by json.loads, it is off the bulk path)
                    plus the candidate-node-name bulk as a '\0'-separated
                    blob with an offsets table and an FNV-1a 64 digest.
                    The slot IS the ticket: `NativeNodeNames` wraps it as a
                    lazy Sequence[str] whose hash/equality ride the digest,
                    so the solver's candidate-mask cache hits WITHOUT ever
                    materializing the 10k names (the zero-copy hit).

Wire formats (both lanes serve both):

  JSON              the existing extender schema
                    {"Pod": {...}, "NodeNames": [...]} — the native lane
                    fast-paths exactly this shape and falls back to the
                    Python parser on ANY deviation (escapes, duplicate
                    keys, "Nodes" form), counted in the hit-ratio gauge.
  binary            Content-Type application/x-spark-predicate —
                    length-prefixed frames:
                      "SPRD" | u8 version=1 | u32le pod_len | pod JSON
                      | u32le count | count x (u16le len | name bytes)
                    decoded natively on the native lane, by the pure-Python
                    decoder here otherwise.

A native-lane server whose native runtime failed to build DEGRADES to the
python lane (log-once in spark_scheduler_tpu.native, RuntimeWarning at
server construction) — `server.ingest: native` never takes the server
down on a toolchain-less host.
"""

from __future__ import annotations

import json
import struct
import threading
from collections.abc import Sequence

BINARY_CONTENT_TYPE = "application/x-spark-predicate"

INGESTS = ("python", "native")


def is_binary_content_type(content_type: str | None) -> bool:
    if not content_type:
        return False
    return content_type.split(";", 1)[0].strip().lower() == BINARY_CONTENT_TYPE


class BinaryPredicateError(ValueError):
    """Malformed application/x-spark-predicate body (same 500-with-Error
    mapping as garbage JSON on the python lane)."""


def encode_predicate_binary(pod_raw, node_names) -> bytes:
    """Client-side encoder (bench, tests): `pod_raw` is the k8s-shaped Pod
    dict (or pre-serialized JSON bytes)."""
    pod = pod_raw if isinstance(pod_raw, bytes) else json.dumps(pod_raw).encode()
    out = bytearray(b"SPRD\x01")
    out += struct.pack("<I", len(pod))
    out += pod
    names = [n.encode() if isinstance(n, str) else n for n in node_names]
    out += struct.pack("<I", len(names))
    for n in names:
        if len(n) > 0xFFFF:
            raise BinaryPredicateError(f"node name too long: {len(n)} bytes")
        out += struct.pack("<H", len(n))
        out += n
    return bytes(out)


def decode_predicate_binary_py(body: bytes):
    """Pure-Python binary decoder — the python lane's (and the degraded
    native lane's) handler for binary bodies. Returns (pod, node_names)."""
    from spark_scheduler_tpu.server.kube_io import pod_from_k8s

    if len(body) < 13 or body[:4] != b"SPRD":
        raise BinaryPredicateError("bad magic: not a SPRD predicate body")
    if body[4] != 1:
        raise BinaryPredicateError(f"unsupported SPRD version {body[4]}")
    (pod_len,) = struct.unpack_from("<I", body, 5)
    pos = 9
    if pos + pod_len + 4 > len(body):
        raise BinaryPredicateError("truncated pod frame")
    pod_raw = json.loads(body[pos : pos + pod_len] or b"{}")
    pos += pod_len
    (count,) = struct.unpack_from("<I", body, pos)
    pos += 4
    names = []
    for _ in range(count):
        if pos + 2 > len(body):
            raise BinaryPredicateError("truncated name frame")
        (n,) = struct.unpack_from("<H", body, pos)
        pos += 2
        if pos + n > len(body):
            raise BinaryPredicateError("truncated name frame")
        names.append(body[pos : pos + n].decode("utf-8"))
        pos += n
    if pos != len(body):
        raise BinaryPredicateError("trailing bytes after name frames")
    return pod_from_k8s(pod_raw), names


class NativeNodeNames(Sequence):
    """The candidate-node-names half of a predicate ticket: a Sequence[str]
    view over a native arena slot. Hash and equality ride the slot's
    FNV-1a 64 digest (equality memcmps the blobs natively — a colliding
    digest can never alias two different candidate lists), so the solver's
    candidate-mask LRU keys on this object directly and a steady-state
    request never materializes its 10k names. Iteration/indexing decode
    lazily and memoize for the slow paths (failure maps, logging)."""

    __slots__ = ("slot", "names_digest", "_count", "_list", "_set")

    def __init__(self, slot):
        self.slot = slot
        self.names_digest = slot.digest
        self._count = slot.names_count
        self._list = None
        self._set = None

    def _materialize(self) -> list:
        if self._list is None:
            blob = self.slot.names_blob()
            self._list = (
                [s.decode("utf-8") for s in blob.split(b"\0")[:-1]]
                if blob
                else []
            )
        return self._list

    def __len__(self) -> int:
        return self._count

    def __getitem__(self, i):
        if self._list is not None:
            return self._list[i]
        if isinstance(i, slice):
            return self._materialize()[i]
        if i < 0:
            i += self._count
        return self.slot.name_at(i)

    def __iter__(self):
        return iter(self._materialize())

    def __contains__(self, name) -> bool:
        if self._set is None:
            self._set = set(self._materialize())
        return name in self._set

    def __hash__(self) -> int:
        return hash(self.names_digest)

    def __eq__(self, other) -> bool:
        if self is other:
            return True
        if isinstance(other, NativeNodeNames):
            return (
                self.names_digest == other.names_digest
                and self._count == other._count
                and self.slot.blob_equal(other.slot)
            )
        if isinstance(other, (list, tuple)):
            return self._materialize() == list(other)
        return NotImplemented

    def __repr__(self) -> str:
        return (
            f"NativeNodeNames(count={self._count}, "
            f"digest={self.names_digest:#x})"
        )


class IngestTelemetry:
    """`foundry.spark.scheduler.server.ingest.*` — the native lane's
    internals: decode time, fast-path hit ratio, arena occupancy, and how
    often (and why) the lane degraded. Counter methods take the lock (the
    threaded transport decodes from many handler threads); `stats()` is
    the pull snapshot GET /metrics surfaces in both formats."""

    def __init__(self, lane: str):
        self.lane = lane
        self._lock = threading.Lock()
        self.decode_hits = 0  # native fast-path decodes (zero-copy tickets)
        self.decode_fallbacks = 0  # deviating bodies parsed by json.loads
        self.binary_requests = 0
        self.parse_ns_total = 0  # native framer time (async transport)
        self.decode_ns_total = 0  # native body-decode time
        self.degraded = False  # native requested but unavailable

    def on_decode(self, *, hit: bool, binary: bool, decode_ns: int) -> None:
        with self._lock:
            if hit:
                self.decode_hits += 1
            else:
                self.decode_fallbacks += 1
            if binary:
                self.binary_requests += 1
            self.decode_ns_total += decode_ns

    def on_parse_ns(self, ns: int) -> None:
        with self._lock:
            self.parse_ns_total += ns

    def stats(self) -> dict:
        from spark_scheduler_tpu import native

        hits, misses = self.decode_hits, self.decode_fallbacks
        total = hits + misses
        return {
            "ingest": self.lane,
            "degraded": int(self.degraded),
            "decode_hits": hits,
            "decode_fallbacks": misses,
            "zero_copy_hit_ratio": round(hits / total, 4) if total else 0.0,
            "binary_requests": self.binary_requests,
            "native_parse_ns_total": self.parse_ns_total,
            "native_decode_ns_total": self.decode_ns_total,
            "decode_mean_us": (
                round(self.decode_ns_total / total / 1e3, 2) if total else 0.0
            ),
            "arena_live_slots": native.live_slot_count(),
        }


class IngestUnavailable(RuntimeError):
    """`server.ingest: native` requested but the native runtime could not
    be built/loaded (carries native.load_error())."""


class NativeIngestCodec:
    """The native lane: framer factory + body decoders, shared by the
    async transport (decode straight from the connection buffer) and the
    routing layer (decode from an already-copied body on the threaded
    transport)."""

    def __init__(self, telemetry: IngestTelemetry | None = None):
        from spark_scheduler_tpu import native

        if not native.available():
            raise IngestUnavailable(
                native.load_error() or "native runtime unavailable"
            )
        self._native = native
        self.telemetry = telemetry or IngestTelemetry("native")

    # ------------------------------------------------------------- framing

    def new_conn(self, max_body_bytes: int | None, max_header_bytes: int):
        return self._native.IngestConn(max_body_bytes, max_header_bytes)

    # ------------------------------------------------------------ decoding

    def _finish(self, slot, hit: bool, binary: bool):
        self.telemetry.on_decode(
            hit=hit, binary=binary, decode_ns=slot.decode_ns if hit else 0
        )
        if not hit:
            return None
        from spark_scheduler_tpu.server.kube_io import pod_from_k8s

        pod = pod_from_k8s(json.loads(slot.pod_json()))
        return pod, NativeNodeNames(slot)

    def decode_predicate_body(self, body: bytes, *, binary: bool):
        """(pod, node_names) on a fast-path hit, None when the caller must
        fall back to the Python parser."""
        slot = self._native.PredicateSlot()
        hit = slot.decode_binary(body) if binary else slot.decode_json(body)
        return self._finish(slot, hit, binary)

    def decode_from_conn(self, conn, *, binary: bool):
        """Same, but tokenizing straight out of the connection buffer (the
        async transport's zero-copy hand-off: the body bytes never become
        a Python object)."""
        slot = self._native.PredicateSlot()
        hit = conn.decode_into(slot, binary=binary)
        return self._finish(slot, hit, binary)

    def stats(self) -> dict:
        return self.telemetry.stats()


def try_native_codec() -> NativeIngestCodec | None:
    """NativeIngestCodec, or None when the native runtime is unavailable
    (the caller degrades to the python lane and warns)."""
    try:
        return NativeIngestCodec()
    except IngestUnavailable:
        return None
