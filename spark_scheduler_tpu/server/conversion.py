"""CRD version-conversion webhook (SURVEY.md L9).

Rebuilds the reference's conversion webhook
(internal/conversionwebhook/resource_reservation.go:44-98 and the standalone
service spark-scheduler-conversion-webhook/): a `POST /convert` route that
receives a Kubernetes `ConversionReview` and converts CRD objects between
served versions:

  ResourceReservation  sparkscheduler.palantir.com  v1beta1 <-> v1beta2
  Demand               scaler.palantir.com          v1alpha1 <-> v1alpha2

Wire-object codecs live here (the apiserver speaks JSON-shaped CRD objects);
the pure model-to-model conversion rules live in
`models.reservations` / `models.demands` (the k8s-free layer, mirroring
v1beta1/conversion_resource_reservation.go:29-121 and apis/scaler/v1alpha1).
Unknown fields are preserved verbatim where the round-trip annotation
carries them; unknown groups/versions fail the review with a `Failed`
result, matching controller-runtime's conversion handler behavior.
"""

from __future__ import annotations

from typing import Any, Callable

from spark_scheduler_tpu.models.demands import (
    Demand,
    DemandSpec,
    DemandStatus,
    DemandUnit,
    DemandUnitV1Alpha1,
    DemandV1Alpha1,
    convert_demand_from_v1alpha1,
    convert_demand_to_v1alpha1,
)
from spark_scheduler_tpu.models.reservations import (
    PRIORITY_CLASS_ANNOTATION,
    Reservation,
    ReservationSpec,
    ReservationStatus,
    ReservationV1Beta1,
    ResourceReservation,
    ResourceReservationV1Beta1,
    convert_from_v1beta1,
    convert_to_v1beta1,
)
from spark_scheduler_tpu.models.resources import (
    format_quantity_kib,
    format_quantity_milli,
    resources_from_quantity_map,
    resources_to_quantity_map,
)

SPARK_SCHEDULER_GROUP = "sparkscheduler.palantir.com"
SCALER_GROUP = "scaler.palantir.com"

RR_V1BETA1 = f"{SPARK_SCHEDULER_GROUP}/v1beta1"
RR_V1BETA2 = f"{SPARK_SCHEDULER_GROUP}/v1beta2"
DEMAND_V1ALPHA1 = f"{SCALER_GROUP}/v1alpha1"
DEMAND_V1ALPHA2 = f"{SCALER_GROUP}/v1alpha2"


# metadata keys the models interpret; everything else rides metadata_extra.
# resourceVersion is deliberately NOT here: it is an opaque string per the
# k8s API contract, so it rides metadata_extra verbatim (the model's int
# resource_version is a best-effort parse for internal versioning only).
_KNOWN_META = ("name", "namespace", "labels", "annotations")


def _metadata_to_wire(obj) -> dict:
    """Re-emit metadata losslessly: uninterpreted fields (uid,
    creationTimestamp, generation, ownerReferences, finalizers, ...) first,
    overlaid with the model-owned fields. The apiserver rejects conversion
    responses that mutate immutable metadata, so this must round-trip
    everything (reference DeepCopies ObjectMeta through conversion)."""
    meta: dict[str, Any] = dict(getattr(obj, "metadata_extra", None) or {})
    meta["name"] = obj.name
    meta["namespace"] = obj.namespace
    if obj.labels:
        meta["labels"] = dict(obj.labels)
    annotations = getattr(obj, "annotations", None)
    if annotations:
        meta["annotations"] = dict(annotations)
    # metadata_extra carries the wire resourceVersion verbatim; only objects
    # built internally (no extra) emit the parsed int form.
    if obj.resource_version and "resourceVersion" not in meta:
        meta["resourceVersion"] = str(obj.resource_version)
    return meta


def _metadata_fields(raw: dict, *, with_annotations: bool = True) -> dict:
    meta = raw.get("metadata") or {}
    rv = str(meta.get("resourceVersion") or "0")
    out = {
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", "default"),
        "labels": dict(meta.get("labels") or {}),
        # Opaque string per API contract; parse best-effort for the models'
        # internal optimistic-concurrency checks, never re-emitted when the
        # original is carried in metadata_extra.
        "resource_version": int(rv) if rv.isdigit() else 0,
        "metadata_extra": {k: v for k, v in meta.items() if k not in _KNOWN_META},
    }
    if with_annotations:
        out["annotations"] = dict(meta.get("annotations") or {})
    elif meta.get("annotations"):
        # The Demand models carry no annotations field; ride them through
        # metadata_extra so conversion doesn't erase operator-set annotations.
        out["metadata_extra"]["annotations"] = dict(meta["annotations"])
    return out


# ------------------------------------------------- ResourceReservation wire


def rr_v1beta2_to_wire(rr: ResourceReservation) -> dict:
    """types_resource_reservation.go:40-102 (v1beta2 storage shape).

    A gang's priority class (policy subsystem) is a first-class optional
    spec field in v1beta2, emitted only when present so pre-policy objects
    stay byte-identical; in v1beta1 it simply stays in annotations."""
    spec: dict = {
        "reservations": {
            name: {"node": r.node, "resources": resources_to_quantity_map(r.resources)}
            for name, r in rr.spec.reservations.items()
        }
    }
    priority_class = rr.annotations.get(PRIORITY_CLASS_ANNOTATION)
    if priority_class is not None:
        spec["priorityClass"] = priority_class
    wire = {
        "apiVersion": RR_V1BETA2,
        "kind": "ResourceReservation",
        "metadata": _metadata_to_wire(rr),
        "spec": spec,
        "status": {"pods": dict(rr.status.pods)},
    }
    if priority_class is not None:
        # The annotation is the in-model carrier; the wire carries the spec
        # field only (no duplicate), matching how reservation-spec stashes
        # are stripped on upgrade.
        wire["metadata"].get("annotations", {}).pop(PRIORITY_CLASS_ANNOTATION, None)
        if not wire["metadata"].get("annotations"):
            wire["metadata"].pop("annotations", None)
    return wire


def rr_v1beta2_from_wire(raw: dict) -> ResourceReservation:
    spec_raw = raw.get("spec") or {}
    reservations = {
        name: Reservation(
            node=r.get("node", ""),
            resources=resources_from_quantity_map(r.get("resources")),
        )
        for name, r in (spec_raw.get("reservations") or {}).items()
    }
    rr = ResourceReservation(
        spec=ReservationSpec(reservations),
        status=ReservationStatus(dict((raw.get("status") or {}).get("pods") or {})),
        **_metadata_fields(raw),
    )
    priority_class = spec_raw.get("priorityClass")
    if priority_class is not None:
        rr.annotations.setdefault(PRIORITY_CLASS_ANNOTATION, str(priority_class))
    return rr


def rr_v1beta1_to_wire(rr1: ResourceReservationV1Beta1) -> dict:
    """v1beta1 flat shape (types_resource_reservation.go:22-68): per-slot
    {node, cpu, memory}; GPU travels in the reservation-spec annotation."""
    return {
        "apiVersion": RR_V1BETA1,
        "kind": "ResourceReservation",
        "metadata": _metadata_to_wire(rr1),
        "spec": {
            "reservations": {
                name: {
                    "node": r.node,
                    "cpu": format_quantity_milli(r.cpu_milli),
                    "memory": format_quantity_kib(r.mem_kib),
                }
                for name, r in rr1.reservations.items()
            }
        },
        "status": {"pods": dict(rr1.pods)},
    }


def rr_v1beta1_from_wire(raw: dict) -> ResourceReservationV1Beta1:
    reservations = {}
    for name, r in ((raw.get("spec") or {}).get("reservations") or {}).items():
        res = resources_from_quantity_map({"cpu": r.get("cpu", "0"), "memory": r.get("memory", "0")})
        reservations[name] = ReservationV1Beta1(
            node=r.get("node", ""), cpu_milli=res.cpu_milli, mem_kib=res.mem_kib
        )
    return ResourceReservationV1Beta1(
        reservations=reservations,
        pods=dict((raw.get("status") or {}).get("pods") or {}),
        **_metadata_fields(raw),
    )


def _parse_transition_time(val) -> float:
    """Accept RFC3339 strings (k8s metav1.Time) or epoch numbers."""
    if val is None:
        return 0.0
    if isinstance(val, (int, float)):
        return float(val)
    import datetime

    try:
        return datetime.datetime.fromisoformat(
            str(val).replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return 0.0


def _format_transition_time(epoch: float) -> str:
    """Epoch seconds -> RFC3339 UTC, the metav1.Time wire encoding
    ("2006-01-02T15:04:05Z")."""
    import datetime

    return (
        datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


# --------------------------------------------------------------- Demand wire
#
# Key names are the reference CRD JSON tags EXACTLY — kebab-case
# (apis/scaler/v1alpha2/types_demand.go:82-122, v1alpha1/types_demand.go:36-62).
# Readers also accept this codebase's round-1 camelCase spellings for
# backward compatibility with already-persisted objects.


def _get(raw: dict, kebab: str, camel: str, default=None):
    if kebab in raw:
        return raw[kebab]
    return raw.get(camel, default)


def demand_v1alpha2_to_wire(d: Demand) -> dict:
    """types_demand.go:71-123 (v1alpha2, status subresource). Fields without
    omitempty (instance-group, is-long-lived, enforce-single-zone-scheduling,
    phase) are always emitted, matching Go json marshaling."""
    spec: dict[str, Any] = {
        "units": [
            {
                "resources": resources_to_quantity_map(u.resources),
                "count": u.count,
                **(
                    {
                        "pod-names-by-namespace": {
                            ns: list(names)
                            for ns, names in u.pod_names_by_namespace.items()
                        }
                    }
                    if u.pod_names_by_namespace
                    else {}
                ),
            }
            for u in d.spec.units
        ],
        "instance-group": d.spec.instance_group,
        "is-long-lived": d.spec.is_long_lived,
        "enforce-single-zone-scheduling": d.spec.enforce_single_zone_scheduling,
    }
    if d.spec.zone:
        spec["zone"] = d.spec.zone
    status: dict[str, Any] = {"phase": d.status.phase}
    if d.status.last_transition_time:
        status["last-transition-time"] = _format_transition_time(
            d.status.last_transition_time
        )
    if d.status.fulfilled_zone:
        status["fulfilled-zone"] = d.status.fulfilled_zone
    return {
        "apiVersion": DEMAND_V1ALPHA2,
        "kind": "Demand",
        "metadata": _metadata_to_wire(d),
        "spec": spec,
        "status": status,
    }


def demand_v1alpha2_from_wire(raw: dict) -> Demand:
    spec_raw = raw.get("spec") or {}
    units = [
        DemandUnit(
            resources=resources_from_quantity_map(u.get("resources")),
            count=int(u.get("count", 0)),
            pod_names_by_namespace={
                ns: list(names)
                for ns, names in (
                    _get(u, "pod-names-by-namespace", "podNamesByNamespace") or {}
                ).items()
            },
        )
        for u in spec_raw.get("units") or []
    ]
    status_raw = raw.get("status") or {}
    return Demand(
        spec=DemandSpec(
            units=units,
            instance_group=_get(spec_raw, "instance-group", "instanceGroup", ""),
            is_long_lived=bool(_get(spec_raw, "is-long-lived", "isLongLived", False)),
            enforce_single_zone_scheduling=bool(
                _get(
                    spec_raw,
                    "enforce-single-zone-scheduling",
                    "enforceSingleZoneScheduling",
                    False,
                )
            ),
            zone=spec_raw.get("zone") or None,
        ),
        status=DemandStatus(
            phase=status_raw.get("phase", ""),
            last_transition_time=_parse_transition_time(
                _get(status_raw, "last-transition-time", "lastTransitionTime")
            ),
            fulfilled_zone=_get(status_raw, "fulfilled-zone", "fulfilledZone") or None,
        ),
        **_metadata_fields(raw, with_annotations=False),
    )


def demand_v1alpha1_to_wire(d1: DemandV1Alpha1) -> dict:
    """v1alpha1 legacy shape (apis/scaler/v1alpha1/types_demand.go:36-62):
    units carry flat cpu/memory/gpu quantities; no zone semantics."""
    status: dict[str, Any] = {"phase": d1.phase}
    if d1.last_transition_time:
        status["last-transition-time"] = _format_transition_time(
            d1.last_transition_time
        )
    return {
        "apiVersion": DEMAND_V1ALPHA1,
        "kind": "Demand",
        "metadata": _metadata_to_wire(d1),
        "spec": {
            "units": [
                {
                    "cpu": format_quantity_milli(u.cpu_milli),
                    "memory": format_quantity_kib(u.mem_kib),
                    **(
                        {"gpu": format_quantity_milli(u.gpu_milli)} if u.gpu_milli else {}
                    ),
                    "count": u.count,
                }
                for u in d1.units
            ],
            "instance-group": d1.instance_group,
            "is-long-lived": d1.is_long_lived,
        },
        "status": status,
    }


def demand_v1alpha1_from_wire(raw: dict) -> DemandV1Alpha1:
    spec_raw = raw.get("spec") or {}
    units = []
    for u in spec_raw.get("units") or []:
        res = resources_from_quantity_map(
            {
                "cpu": u.get("cpu", "0"),
                "memory": u.get("memory", "0"),
                "nvidia.com/gpu": u.get("gpu", "0"),
            }
        )
        units.append(
            DemandUnitV1Alpha1(
                cpu_milli=res.cpu_milli,
                mem_kib=res.mem_kib,
                count=int(u.get("count", 0)),
                gpu_milli=res.gpu_milli,
            )
        )
    status_raw = raw.get("status") or {}
    return DemandV1Alpha1(
        units=units,
        instance_group=_get(spec_raw, "instance-group", "instanceGroup", ""),
        is_long_lived=bool(_get(spec_raw, "is-long-lived", "isLongLived", False)),
        phase=status_raw.get("phase", ""),
        last_transition_time=_parse_transition_time(
            _get(status_raw, "last-transition-time", "lastTransitionTime")
        ),
        **_metadata_fields(raw, with_annotations=False),
    )


# ------------------------------------------------------------- review logic

_DECODERS: dict[str, Callable[[dict], Any]] = {
    RR_V1BETA1: rr_v1beta1_from_wire,
    RR_V1BETA2: rr_v1beta2_from_wire,
    DEMAND_V1ALPHA1: demand_v1alpha1_from_wire,
    DEMAND_V1ALPHA2: demand_v1alpha2_from_wire,
}


def _convert_object(raw: dict, desired: str) -> dict:
    src = raw.get("apiVersion", "")
    decode = _DECODERS.get(src)
    if decode is None:
        raise ValueError(f"unsupported apiVersion {src!r}")
    if desired not in _DECODERS:
        raise ValueError(f"unsupported desiredAPIVersion {desired!r}")
    if src == desired:
        return raw
    obj = decode(raw)

    if src == RR_V1BETA1:
        obj = convert_from_v1beta1(obj)
    elif src == DEMAND_V1ALPHA1:
        obj = convert_demand_from_v1alpha1(obj)
    # obj is now the hub (storage) model: v1beta2 RR or v1alpha2 Demand.

    if desired == RR_V1BETA2:
        return rr_v1beta2_to_wire(obj)
    if desired == RR_V1BETA1:
        return rr_v1beta1_to_wire(convert_to_v1beta1(obj))
    if desired == DEMAND_V1ALPHA2:
        return demand_v1alpha2_to_wire(obj)
    return demand_v1alpha1_to_wire(convert_demand_to_v1alpha1(obj))


def convert_review(review: dict) -> dict:
    """Handle a ConversionReview (conversionwebhook/resource_reservation.go:
    44-98): convert request.objects to request.desiredAPIVersion; any failure
    fails the whole review (the apiserver retries)."""
    if not isinstance(review, dict):
        review = {}
    request = review.get("request")
    if not isinstance(request, dict):
        request = {}
    uid = request.get("uid", "")
    desired = request.get("desiredAPIVersion", "")
    converted = []
    try:
        objects = request.get("objects") or []
        if not isinstance(objects, list):
            raise ValueError("request.objects must be a list")
        for raw in objects:
            if not isinstance(raw, dict):
                raise ValueError("conversion objects must be JSON objects")
            converted.append(_convert_object(raw, desired))
        response: dict[str, Any] = {
            "uid": uid,
            "convertedObjects": converted,
            "result": {"status": "Success"},
        }
    except Exception as exc:
        response = {
            "uid": uid,
            "convertedObjects": [],
            "result": {"status": "Failed", "message": str(exc)},
        }
    return {
        "apiVersion": review.get("apiVersion", "apiextensions.k8s.io/v1"),
        "kind": "ConversionReview",
        "response": response,
    }
