"""Single-threaded event-loop HTTP/1.1 transport.

The round-5 bench showed the served path at 96.6% of the stdlib
`ThreadingHTTPServer` rig ceiling (359.8 bindings/s) while the in-process
executor ladder did 10,297 bindings/s: every marginal request paid a
handler thread, stdlib per-request framing, and GIL-contended JSON work
just to park in `PredicateBatcher.submit` — the batcher's dispatcher
thread was already the serialization point, so the parked threads were
pure overhead. This transport replaces them with ONE event loop:

  - minimal incremental HTTP/1.1 parser over a growing buffer: request
    line + headers split once, Content-Length validated with the same
    RFC 7230 strictness as the threaded stack (differing duplicates,
    non-digit forms, Transfer-Encoding all rejected), pipelined requests
    framed back-to-back from the same buffer;
  - persistent keep-alive connections with in-order response slots, so a
    pipelining client's responses never reorder even though predicate
    decisions complete asynchronously on the batcher's dispatcher thread;
  - precomputed response header blocks per (status, content-type) and ONE
    transport.write per response (headers + body in a single bytes
    object — the writev/sendmsg shape, no per-header syscalls);
  - explicit backpressure instead of unbounded thread spawn: a
    max-connections gate answered with a canned 503 + close, per-request
    max-body-bytes answered 413 with the body drained (keep-alive
    survives), pipelined-slot caps that pause the socket, and queue-depth
    load shedding in the predicate route (routing._shed_response);
  - `foundry.spark.scheduler.server.*` transport telemetry: open
    connections, keep-alive reuse ratio, parse/queue/write phase times,
    shed counts — surfaced through GET /metrics next to the batcher's.

The loop runs in one daemon thread; `routes.handle_nowait` must never
block it (the predicate route hands off to the batcher and responds from
its completion callback via call_soon_threadsafe).

With `server.ingest: native` the Python parser above is replaced per
connection by the C++ incremental framer (native/runtime.cpp IngestConn):
received bytes feed a connection-owned C++ buffer, framed-request events
come back as offset spans, and a POST /predicates body is tokenized
STRAIGHT OUT of that buffer into a predicate arena slot — the ~200 KB
candidate-name bulk never materializes as Python objects; the routing
layer receives the decoded (pod, NativeNodeNames) ticket on the Request.
Framing strictness (RFC 7230 Content-Length/TE rules, 431/400 rejects,
413 drain) is the same by construction — the conformance suite in
tests/test_ingest_native.py runs the same edges against both framers.
"""

from __future__ import annotations

import asyncio
import socket
import threading
import time
from collections import deque
from http import HTTPStatus
from urllib.parse import parse_qs, urlparse

from spark_scheduler_tpu.server.routing import (
    BodyTooLarge,
    Request,
    Response,
    UnframeableBody,
    UnsupportedTransferEncoding,
)
from spark_scheduler_tpu.server.transport_threaded import build_server_ssl_context

_MAX_HEADER_BYTES = 65536
# Pipelined requests a single connection may have awaiting responses
# before its socket is paused (resumed at the low-water mark): one
# misbehaving client cannot queue unbounded work.
_PIPELINE_HIGH_WATER = 64
_PIPELINE_LOW_WATER = 16


def _reason(status: int) -> str:
    try:
        return HTTPStatus(status).phrase
    except ValueError:
        return "Unknown"


class _HeaderBlocks:
    """Precomputed `HTTP/1.1 <code> <reason>\\r\\nContent-Type: ...\\r\\n
    Content-Length: ` prefixes keyed by (status, content_type): the hot
    path assembles a response with one dict hit + two concats."""

    def __init__(self):
        self._blocks: dict[tuple, bytes] = {}

    def get(self, status: int, content_type: str) -> bytes:
        key = (status, content_type)
        block = self._blocks.get(key)
        if block is None:
            block = (
                f"HTTP/1.1 {status} {_reason(status)}\r\n"
                f"Content-Type: {content_type}\r\n"
                "Content-Length: "
            ).encode()
            self._blocks[key] = block
        return block


_BLOCKS = _HeaderBlocks()

_SHED_BODY = b'{"error": "connection limit reached"}'
_SHED_RESPONSE = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Type: application/json\r\n"
    b"Content-Length: " + str(len(_SHED_BODY)).encode() + b"\r\n"
    b"Connection: close\r\n\r\n" + _SHED_BODY
)


class Headers:
    """Case-insensitive multi-value header view with the two lookups the
    routing layer and tracer use (`get`, `get_all`)."""

    __slots__ = ("_items",)

    def __init__(self):
        self._items: list[tuple[str, str]] = []

    def add(self, name: str, value: str) -> None:
        self._items.append((name.lower(), value))

    def get(self, name: str, default=None):
        name = name.lower()
        for k, v in self._items:
            if k == name:
                return v
        return default

    def get_all(self, name: str, default=None):
        name = name.lower()
        found = [v for k, v in self._items if k == name]
        return found if found else default


def _validated_content_length(headers: Headers) -> int:
    """Same RFC 7230 3.3.2 strictness as the threaded transport's
    `_content_length` (differing duplicates = smuggling vector, 1*DIGIT
    only); raises UnframeableBody."""
    raws = headers.get_all("Content-Length") or []
    vals = {r.strip() for r in raws}
    if len(vals) > 1:
        raise UnframeableBody("invalid Content-Length")
    raw = next(iter(vals), None)
    if raw is None:
        return 0
    if raw.isascii() and raw.isdigit():
        return int(raw)
    raise UnframeableBody("invalid Content-Length")


class _Slot:
    """One pipelined request's response slot: responses are written in
    request order, whichever order the routes complete them in."""

    __slots__ = (
        "done", "resp", "close_after", "method", "path", "trace_id",
        "t_start", "t_queued",
    )

    def __init__(self, method, path, trace_id, t_start, close_after):
        self.done = False
        self.resp = None
        self.close_after = close_after
        self.method = method
        self.path = path
        self.trace_id = trace_id
        self.t_start = t_start
        self.t_queued = 0.0


# Parser states.
_HEADERS, _BODY, _DRAIN = 0, 1, 2


class _HTTPProtocol(asyncio.Protocol):
    __slots__ = (
        "_t", "_transport", "_buf", "_state", "_hdr_scan", "_shed",
        "_slots", "_closing", "_paused", "_conn_requests", "_idle_handle",
        # per-request parse state carried from headers into body/drain
        "_method", "_target", "_headers", "_need", "_body_error",
        "_keep_alive", "_close_after", "_req_t0",
        "_nconn",  # native framer connection (server.ingest: native)
    )

    def __init__(self, t: "AsyncTransport"):
        self._t = t
        self._transport = None
        self._buf = bytearray()
        self._nconn = (
            t.ingest_codec.new_conn(t.max_body_bytes, _MAX_HEADER_BYTES)
            if t.ingest_codec is not None
            else None
        )
        self._state = _HEADERS
        self._hdr_scan = 0
        self._shed = False
        self._slots: deque[_Slot] = deque()
        self._closing = False
        self._paused = False
        self._conn_requests = 0
        self._idle_handle = None
        self._method = ""
        self._target = ""
        self._headers = None
        self._need = 0
        self._body_error = None
        self._keep_alive = True
        self._close_after = False
        self._req_t0 = 0.0

    # ------------------------------------------------------------ lifecycle

    def connection_made(self, transport):
        self._transport = transport
        t = self._t
        tel = t.telemetry
        # The live-connection set is transport-owned (not the optional
        # telemetry object), so the cap holds even with metrics off.
        if len(t._protocols) >= t.max_connections:
            # Connection-level load shed: answer with a canned 503 and
            # close instead of queueing unbounded per-connection state —
            # the bounded analogue of the threaded stack's thread spawn.
            self._shed = True
            if tel is not None:
                tel.on_connection_shed()
            transport.write(_SHED_RESPONSE)
            transport.close()
            return
        if tel is not None:
            tel.on_connection_open()
        t._protocols.add(self)
        self._arm_idle_timer()

    def connection_lost(self, exc):
        if self._shed:
            return
        t = self._t
        t._protocols.discard(self)
        if t.telemetry is not None:
            t.telemetry.on_connection_close()
        if self._idle_handle is not None:
            self._idle_handle.cancel()
            self._idle_handle = None
        self._closing = True
        self._slots.clear()  # late responds see done-or-gone slots
        if self._nconn is not None:
            self._nconn.close()  # release the C++ connection buffer now
            self._nconn = None

    def close(self):
        self._closing = True
        if self._transport is not None:
            self._transport.close()

    def _arm_idle_timer(self):
        """Close connections with no COMPLETED request inside the timeout
        (the threaded transport's per-connection socket timeout slot). The
        timer re-arms on every framed request and defers while responses
        are still pending — a long device solve is not idleness."""
        timeout = self._t.request_timeout_s
        if not timeout:
            return
        if self._idle_handle is not None:
            self._idle_handle.cancel()
        self._idle_handle = self._t._loop.call_later(timeout, self._idle_fired)

    def _idle_fired(self):
        self._idle_handle = None
        if self._closing:
            return
        if self._slots:  # response in flight: not idle, re-arm
            self._arm_idle_timer()
            return
        self.close()

    # -------------------------------------------------------------- parsing

    def data_received(self, data: bytes):
        if self._shed or self._closing:
            return  # discard: drain-before-close for error'd connections
        tel = self._t.telemetry
        if tel is not None:
            tel.bytes_in += len(data)
        if self._nconn is not None:
            self._nconn.feed(data)
            self._parse_native()
            return
        self._buf += data
        self._parse()

    # ----------------------------------------------------- native framing

    def _parse_native(self):
        """Drain framed-request events from the C++ framer — the native
        twin of `_parse`. Body bytes are copied out ONLY when the route
        needs them (non-predicate routes, fast-path misses); a predicate
        body decodes in place into an arena slot."""
        from spark_scheduler_tpu import native as _n
        from spark_scheduler_tpu.server.ingest import is_binary_content_type

        conn = self._nconn
        codec = self._t.ingest_codec
        tel = self._t.telemetry
        while not self._closing:
            ev = conn.next()
            if ev.kind == _n.EV_NEED_MORE:
                return
            if ev.kind == _n.EV_REJECT:
                msg = {
                    _n.REJECT_HEADER_TOO_LARGE: "header block too large",
                    _n.REJECT_REQUEST_LINE: "malformed request line",
                    _n.REJECT_HEADER_LINE: "malformed header line",
                }.get(ev.err_code, "malformed request")
                self._reject_connection(ev.status, msg)
                return
            t0 = time.perf_counter()
            head = conn.read(ev.head_off, ev.head_len)
            lines = head.decode("latin-1").split("\r\n")
            headers = Headers()
            for line in lines[1:]:
                if not line:
                    continue
                name, _, value = line.partition(":")
                headers.add(name.strip(), value.strip())
            self._method = conn.read(ev.method_off, ev.method_len).decode(
                "latin-1"
            )
            self._target = conn.read(ev.target_off, ev.target_len).decode(
                "latin-1"
            )
            self._headers = headers
            self._req_t0 = t0
            self._keep_alive = bool(ev.flags & _n.FLAG_KEEP_ALIVE)
            self._close_after = bool(ev.flags & _n.FLAG_CLOSE_AFTER)
            self._body_error = None
            stop_after = False
            if ev.body_error == _n.BODY_ERR_TRANSFER_ENCODING:
                self._body_error = UnsupportedTransferEncoding(
                    "Transfer-Encoding not supported; send Content-Length"
                )
                stop_after = True  # nothing after an unframed body parses
            elif ev.body_error == _n.BODY_ERR_CONTENT_LENGTH:
                self._body_error = UnframeableBody("invalid Content-Length")
                stop_after = True
            elif ev.body_error == _n.BODY_ERR_TOO_LARGE:
                if tel is not None:
                    tel.on_body_rejected()
                self._body_error = BodyTooLarge(
                    f"request body of {ev.declared_len} bytes exceeds "
                    f"max-body-bytes={self._t.max_body_bytes}"
                )
            body = b""
            parsed = None
            attempted = False
            if self._body_error is None and ev.body_len:
                if ev.flags & _n.FLAG_PREDICATE:
                    # Zero-copy hand-off: tokenize the body out of the
                    # connection buffer into a predicate slot; only a
                    # fast-path miss copies the bytes up for json.loads.
                    attempted = True
                    parsed = codec.decode_from_conn(
                        conn,
                        binary=is_binary_content_type(
                            headers.get("Content-Type")
                        ),
                    )
                if parsed is None:
                    body = conn.read(ev.body_off, ev.body_len)
            if tel is not None:
                tel.parse_s += ev.parse_ns / 1e9
                tel.parse_samples += 1
            codec.telemetry.on_parse_ns(ev.parse_ns)
            self._dispatch(body, parsed, attempted)
            if stop_after:
                self._closing = True
                return

    def _parse(self):
        buf = self._buf
        while not self._closing:
            if self._state == _HEADERS:
                if not buf:
                    return
                idx = buf.find(b"\r\n\r\n", max(0, self._hdr_scan - 3))
                if idx < 0:
                    if len(buf) > _MAX_HEADER_BYTES:
                        self._reject_connection(431, "header block too large")
                        return
                    self._hdr_scan = len(buf)
                    return
                t0 = time.perf_counter()
                head = bytes(buf[:idx])
                del buf[: idx + 4]
                self._hdr_scan = 0
                if not self._begin_request(head, t0):
                    return
            elif self._state == _BODY:
                if len(buf) < self._need:
                    return
                body = bytes(buf[: self._need])
                del buf[: self._need]
                self._state = _HEADERS
                self._dispatch(body)
            else:  # _DRAIN: discard an oversized body, then answer 413
                take = min(len(buf), self._need)
                del buf[:take]
                self._need -= take
                if self._need:
                    return
                self._state = _HEADERS
                self._dispatch(b"")

    def _begin_request(self, head: bytes, t0: float) -> bool:
        """Parse request line + headers; set up body framing. Returns False
        when the connection is now closing (parse error)."""
        tel = self._t.telemetry
        try:
            lines = head.decode("latin-1").split("\r\n")
            parts = lines[0].split()
            if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
                raise ValueError(f"malformed request line: {lines[0]!r}")
            self._method, self._target, version = parts
            headers = Headers()
            for line in lines[1:]:
                if not line:
                    continue
                name, sep, value = line.partition(":")
                if not sep:
                    raise ValueError(f"malformed header line: {line!r}")
                headers.add(name.strip(), value.strip())
            self._headers = headers
        except (ValueError, UnicodeDecodeError) as exc:
            self._reject_connection(400, str(exc))
            return False
        self._req_t0 = t0
        conn_tok = (headers.get("Connection") or "").lower()
        if version == "HTTP/1.0":
            self._keep_alive = "keep-alive" in conn_tok
        else:
            self._keep_alive = "close" not in conn_tok
        self._close_after = False
        self._body_error = None
        self._need = 0
        # Body framing — the same contract as the threaded transport:
        # framing failures defer into the Request (the route decides 400
        # vs 404) and flag the connection to close so unread bytes never
        # desync a keep-alive follow-up.
        if headers.get("Transfer-Encoding"):
            self._body_error = UnsupportedTransferEncoding(
                "Transfer-Encoding not supported; send Content-Length"
            )
            self._close_after = True
            self._state = _HEADERS  # body never parsed; connection closes
            self._dispatch(b"")
            # Nothing after the unframed body can be parsed safely: stop
            # reading (the pending slot still flushes; later buffered
            # bytes — e.g. the chunked body itself — are discarded).
            self._closing = True
            return False
        try:
            length = _validated_content_length(headers)
        except UnframeableBody as exc:
            self._body_error = exc
            self._close_after = True
            self._state = _HEADERS
            self._dispatch(b"")
            self._closing = True
            return False
        cap = self._t.max_body_bytes
        if cap is not None and length > cap:
            if tel is not None:
                tel.on_body_rejected()
            self._body_error = BodyTooLarge(
                f"request body of {length} bytes exceeds max-body-bytes={cap}"
            )
            self._state = _DRAIN
            self._need = length
        else:
            self._state = _BODY
            self._need = length
        if tel is not None:
            tel.parse_s += time.perf_counter() - t0
            tel.parse_samples += 1
        return True

    def _reject_connection(self, status: int, message: str):
        """Protocol-level parse failure: nothing later can be framed, so
        stop parsing — but the error response rides the SLOT queue like
        any other, so pipelined responses still flush strictly in request
        order ahead of it (an out-of-band write would desync the client
        and could race a still-solving earlier request's response)."""
        from spark_scheduler_tpu.server.routing import json_response

        self._closing = True  # stop parsing; data_received now discards
        slot = _Slot("-", "-", None, time.perf_counter(), True)
        self._slots.append(slot)
        self._complete(
            slot, json_response(status, {"error": str(message)}, close=True)
        )

    def _delayed_close(self):
        """Close after a short grace so bytes a client is still sending do
        not turn the close into an RST that destroys the in-flight
        response (the threaded transport's bounded drain, event-loop
        shaped: data_received keeps discarding meanwhile)."""
        self._closing = True
        loop = self._t._loop
        loop.call_later(0.05, self.close)

    # ------------------------------------------------------------ dispatch

    def _dispatch(self, body: bytes, predicate_parsed=None,
                  native_decode_attempted=False):
        parsed = urlparse(self._target)
        headers = self._headers
        req = Request(
            method=self._method,
            path=parsed.path,
            query=parse_qs(parsed.query),
            headers=headers,
            body=body,
            body_error=self._body_error,
            predicate_parsed=predicate_parsed,
            native_decode_attempted=native_decode_attempted,
        )
        self._conn_requests += 1
        tel = self._t.telemetry
        if tel is not None:
            tel.on_request(reused=self._conn_requests > 1)
        close_after = self._close_after or not self._keep_alive
        slot = _Slot(
            req.method,
            self._target,
            headers.get("X-B3-TraceId") or headers.get("b3", "").split("-")[0]
            or None,
            self._req_t0,
            close_after,
        )
        slot.t_queued = time.perf_counter()
        self._slots.append(slot)
        self._arm_idle_timer()
        loop = self._t._loop
        loop_thread = self._t._loop_thread_ident

        def respond(resp: Response):
            if threading.get_ident() == loop_thread:
                self._complete(slot, resp)
            else:
                try:
                    loop.call_soon_threadsafe(self._complete, slot, resp)
                except RuntimeError:
                    pass  # loop already closed at shutdown

        def schedule_timeout(delay_s: float, cb):
            # Only ever called from the loop thread (handle_nowait runs
            # inline in _dispatch); .cancel() from other threads is routed
            # back through the loop by the routing layer's claim().
            return _ThreadsafeTimer(loop, loop.call_later(delay_s, cb))

        try:
            self._t.routes.handle_nowait(req, respond, schedule_timeout)
        except Exception as exc:  # a raising route must not kill the loop
            from spark_scheduler_tpu.server.routing import json_response

            respond(json_response(500, {"error": str(exc)}))
        # Pipelining backpressure: cap un-responded slots per connection.
        if len(self._slots) >= _PIPELINE_HIGH_WATER and not self._paused:
            self._paused = True
            try:
                self._transport.pause_reading()
            except Exception:
                pass

    def _complete(self, slot: _Slot, resp: Response):
        if slot.done or self._transport is None:
            return
        slot.done = True
        slot.resp = resp
        tel = self._t.telemetry
        if tel is not None:
            tel.queue_s += time.perf_counter() - slot.t_queued
            tel.queue_samples += 1
        self._flush()

    def _flush(self):
        slots = self._slots
        tel = self._t.telemetry
        while slots and slots[0].done:
            slot = slots.popleft()
            resp = slot.resp
            t0 = time.perf_counter()
            close = slot.close_after or resp.close
            prefix = _BLOCKS.get(resp.status, resp.content_type)
            extra = b""
            if resp.headers:
                extra = b"".join(
                    f"\r\n{name}: {value}".encode()
                    for name, value in resp.headers.items()
                )
            data = (
                prefix
                + str(len(resp.body)).encode()
                + extra
                + (b"\r\nConnection: close\r\n\r\n" if close else b"\r\n\r\n")
                + resp.body
            )
            self._transport.write(data)
            if tel is not None:
                tel.write_s += time.perf_counter() - t0
                tel.write_samples += 1
                tel.bytes_out += len(data)
            if self._t.request_log:
                self._emit_request_log(slot, resp)
            if close:
                self._delayed_close()
                return
        if self._paused and len(slots) <= _PIPELINE_LOW_WATER:
            self._paused = False
            try:
                self._transport.resume_reading()
            except Exception:
                pass

    def _emit_request_log(self, slot: _Slot, resp: Response):
        from spark_scheduler_tpu.tracing import svc1log

        svc1log().request(
            slot.method,
            slot.path,
            resp.status,
            int((time.perf_counter() - slot.t_start) * 1e6),
            protocol="HTTP/1.1",
            trace_id=slot.trace_id or None,
        )


class _ThreadsafeTimer:
    """Wraps an asyncio TimerHandle so `.cancel()` is safe from any thread
    (TimerHandle.cancel is loop-thread-only; completions fire on the
    batcher's dispatcher thread)."""

    __slots__ = ("_loop", "_handle")

    def __init__(self, loop, handle):
        self._loop = loop
        self._handle = handle

    def cancel(self):
        try:
            self._loop.call_soon_threadsafe(self._handle.cancel)
        except RuntimeError:
            pass


class AsyncTransport:
    """Event-loop transport facade: binds its socket at construction
    (ephemeral ports resolve immediately, matching ThreadedTransport),
    runs the loop in one daemon thread on start()."""

    def __init__(
        self,
        routes,
        host: str = "127.0.0.1",
        port: int = 8484,
        *,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        request_log: bool = False,
        max_body_bytes: int | None = None,
        max_connections: int = 512,
        telemetry=None,
        name: str = "scheduler-http-async",
        ingest_codec=None,
    ):
        self.routes = routes
        self.request_timeout_s = request_timeout_s
        self.request_log = request_log
        self.max_body_bytes = max_body_bytes
        self.max_connections = max_connections
        self.telemetry = telemetry
        # Native ingest lane: when set, connections frame via the C++
        # incremental parser and predicate bodies decode into arena slots
        # (see _parse_native); None = the Python parser above.
        self.ingest_codec = ingest_codec
        self._name = name
        self._ssl_ctx = build_server_ssl_context(
            cert_file, key_file, client_ca_files
        )
        self.tls = self._ssl_ctx is not None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread_ident: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._thread: threading.Thread | None = None
        self._protocols: set[_HTTPProtocol] = set()
        self._started = threading.Event()
        self._startup_error: Exception | None = None

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def set_request_log(self, enabled: bool) -> None:
        self.request_log = enabled

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=self._name
        )
        self._thread.start()
        self._started.wait(timeout=10)
        if self._startup_error is not None:
            raise self._startup_error

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        self._loop_thread_ident = threading.get_ident()
        try:
            kw = {}
            if self._ssl_ctx is not None:
                kw["ssl"] = self._ssl_ctx
                kw["ssl_handshake_timeout"] = self.request_timeout_s
            self._server = loop.run_until_complete(
                loop.create_server(
                    lambda: _HTTPProtocol(self), sock=self._sock, **kw
                )
            )
        except Exception as exc:
            self._startup_error = exc
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            for proto in list(self._protocols):
                try:
                    proto.close()
                except Exception:
                    pass
            self._server.close()
            try:
                loop.run_until_complete(self._server.wait_closed())
            except Exception:
                pass
            loop.close()

    def stop(self) -> None:
        loop = self._loop
        if loop is not None and self._startup_error is None:
            # call_soon_threadsafe also covers the start()-raced case: if
            # run_forever has not begun yet the stop callback runs the
            # moment it does, so join() below cannot hang.
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self._sock.close()
        except OSError:
            pass

    def join(self) -> None:
        """Block until the serving thread exits (after start())."""
        if self._thread is not None:
            self._thread.join()
