"""Refreshable runtime configuration (VERDICT r2 #6).

The reference embeds witchcraft Install+Runtime config
(/root/reference/config/config.go:24-47): install config is immutable for
the process lifetime, while RUNTIME config (logging level etc.) reloads
without a restart. This module is that slot: a `RuntimeConfig` read from a
YAML file, re-applied live when the file changes (mtime poll) or on SIGHUP.

Reloadable knobs:
  logging.level                -> svc1log minimum level
  fifo                         -> ExtenderConfig.fifo
  batched-admission            -> ExtenderConfig.batched_admission
  async-client-retry-count     -> write-back retry budget of both caches
  autoscaler.idle-ttl          -> ScaleDownDrainer idle TTL (live resize of
                                  the scale-down window)
  autoscaler.max-cluster-size  -> ElasticAutoscaler provisioning cap

Unknown keys are ignored (forward compatibility); a missing/unparseable
file keeps the last good config (witchcraft behaviour: a bad runtime refresh
must never take down the server).
"""

from __future__ import annotations

import dataclasses
import gc
import os
import signal
import threading
from typing import Optional


def freeze_boot_heap() -> int:
    """Move every object allocated during boot into the GC's permanent
    generation (`gc.freeze`) so steady-state collections never re-scan the
    multi-hundred-MB boot heap — solver tensors, compiled-program wrappers,
    caches. The 1M-node bench showed full gen-2 sweeps over the boot heap
    as a serving-tail spike (ROADMAP item 5: production-tail hardening in
    the server itself, not just the bench). Called once from
    SchedulerApp.start_background() after construction; idempotent — a
    second call freezes only what was allocated since. Returns the number
    of objects now frozen."""
    gc.collect()
    gc.freeze()
    return gc.get_freeze_count()


@dataclasses.dataclass
class RuntimeConfig:
    """The reloadable subset (config.go:24-47 Runtime embed)."""

    log_level: Optional[str] = None
    fifo: Optional[bool] = None
    batched_admission: Optional[bool] = None
    async_client_retry_count: Optional[int] = None
    autoscaler_idle_ttl_s: Optional[float] = None
    autoscaler_max_cluster_size: Optional[int] = None

    @classmethod
    def from_dict(cls, raw: dict) -> "RuntimeConfig":
        logging_block = raw.get("logging") or {}
        level = logging_block.get("level", raw.get("log-level"))
        fifo = raw.get("fifo")
        batched = raw.get("batched-admission")
        retries = raw.get("async-client-retry-count")
        autoscaler_block = raw.get("autoscaler") or {}
        idle_ttl = autoscaler_block.get("idle-ttl")
        max_cluster = autoscaler_block.get("max-cluster-size")
        if idle_ttl is not None:
            from spark_scheduler_tpu.server.config import _parse_duration

            idle_ttl = _parse_duration(idle_ttl)
        return cls(
            log_level=str(level) if level is not None else None,
            fifo=bool(fifo) if fifo is not None else None,
            batched_admission=bool(batched) if batched is not None else None,
            async_client_retry_count=int(retries) if retries is not None else None,
            autoscaler_idle_ttl_s=idle_ttl,
            autoscaler_max_cluster_size=(
                int(max_cluster) if max_cluster is not None else None
            ),
        )


class RuntimeConfigManager:
    """Watches a runtime-config YAML and applies changes to a live app.

    `check_now()` is the reload primitive (used by the file-watch thread,
    the SIGHUP handler, and tests); `start()` begins the watch thread and
    installs the SIGHUP handler when running on the main thread."""

    def __init__(self, app, path: str, poll_interval_s: float = 2.0):
        self._app = app
        self._path = path
        self._poll_interval_s = poll_interval_s
        self._mtime: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.current = RuntimeConfig()
        self.reloads = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.check_now()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="runtime-config-watch"
        )
        self._thread.start()
        try:
            signal.signal(signal.SIGHUP, lambda *_: self.check_now(force=True))
        except ValueError:
            pass  # not the main thread (embedded/test use) — file watch only

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_interval_s + 1)

    def _watch(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            self.check_now()

    # -- reload --------------------------------------------------------------

    def check_now(self, force: bool = False) -> bool:
        """Reload if the file changed (or `force`). Returns True when a new
        config was applied."""
        try:
            mtime = os.stat(self._path).st_mtime
        except OSError:
            return False
        if not force and mtime == self._mtime:
            return False
        self._mtime = mtime
        try:
            import yaml

            with open(self._path) as f:
                raw = yaml.safe_load(f) or {}
            cfg = RuntimeConfig.from_dict(raw)
        except Exception as exc:  # bad refresh keeps the last good config
            from spark_scheduler_tpu.tracing import svc1log

            svc1log().warn(
                "runtime config refresh failed; keeping previous",
                path=self._path,
                error=repr(exc),
            )
            return False
        self.apply(cfg)
        return True

    def apply(self, cfg: RuntimeConfig) -> None:
        from spark_scheduler_tpu.tracing import svc1log

        app = self._app
        if cfg.log_level is not None:
            svc1log().set_level(cfg.log_level)
        if cfg.fifo is not None:
            app.extender._config.fifo = cfg.fifo
        if cfg.batched_admission is not None:
            app.extender._config.batched_admission = cfg.batched_admission
        if cfg.async_client_retry_count is not None:
            for cache in (app.rr_cache, app.demand_cache):
                setter = getattr(cache, "set_max_retries", None)
                if setter is not None:
                    setter(cfg.async_client_retry_count)
        autoscaler = getattr(app, "autoscaler", None)
        if autoscaler is not None:
            if cfg.autoscaler_idle_ttl_s is not None:
                autoscaler.drainer.idle_ttl_s = cfg.autoscaler_idle_ttl_s
            if cfg.autoscaler_max_cluster_size is not None:
                autoscaler.max_cluster_size = cfg.autoscaler_max_cluster_size
        self.current = cfg
        self.reloads += 1
        svc1log().info(
            "runtime config applied",
            log_level=cfg.log_level,
            fifo=cfg.fifo,
            batched_admission=cfg.batched_admission,
            async_client_retry_count=cfg.async_client_retry_count,
            autoscaler_idle_ttl_s=cfg.autoscaler_idle_ttl_s,
            autoscaler_max_cluster_size=cfg.autoscaler_max_cluster_size,
        )
