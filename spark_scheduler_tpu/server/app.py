"""Dependency wiring — the initServer DI graph (cmd/server.go:56-266).

`build_scheduler_app` assembles every component of the scheduler around a
ClusterBackend: caches with async write-back, soft-reservation store,
reservation manager, overhead computer, demand manager + GC, failover
reconciler, placement solver, the extender, and the unschedulable-pod
marker. The same builder serves tests (sync writes, in-memory backend) and
the HTTP server (async write-back, background loops).
"""

from __future__ import annotations

import dataclasses

from spark_scheduler_tpu.core.binpacker import select_binpacker
from spark_scheduler_tpu.core.demands import DemandManager, start_demand_gc
from spark_scheduler_tpu.core.extender import ExtenderConfig, SparkSchedulerExtender
from spark_scheduler_tpu.core.failover import FailoverReconciler
from spark_scheduler_tpu.core.overhead import OverheadComputer
from spark_scheduler_tpu.core.reservation_manager import ResourceReservationManager
from spark_scheduler_tpu.core.solver import PlacementSolver
from spark_scheduler_tpu.core.soft_reservations import SoftReservationStore
from spark_scheduler_tpu.core.sparkpods import SparkPodLister
from spark_scheduler_tpu.core.unschedulable import UnschedulablePodMarker
from spark_scheduler_tpu.core.usage_tracker import ReservedUsageTracker
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.store.backend import ClusterBackend, DEMAND_CRD
from spark_scheduler_tpu.store.cache import ResourceReservationCache, SafeDemandCache
from spark_scheduler_tpu.store.crd import (
    LazyDemandCRDWatcher,
    ensure_resource_reservations_crd,
)


@dataclasses.dataclass
class SchedulerApp:
    backend: ClusterBackend
    config: InstallConfig
    rr_cache: ResourceReservationCache
    demand_cache: SafeDemandCache
    soft_store: SoftReservationStore
    pod_lister: SparkPodLister
    reservation_manager: ResourceReservationManager
    overhead_computer: OverheadComputer
    demand_manager: DemandManager
    reconciler: FailoverReconciler
    solver: PlacementSolver
    extender: SparkSchedulerExtender
    unschedulable_marker: UnschedulablePodMarker
    demand_crd_watcher: LazyDemandCRDWatcher
    ingestion: object | None = None  # KubeIngestion when kube_api_url is set
    runtime_manager: object | None = None  # RuntimeConfigManager when configured
    autoscaler: object | None = None  # ElasticAutoscaler when enabled
    recorder: object | None = None  # FlightRecorder when flight_recorder is on
    trace_writer: object | None = None  # replay.TraceWriter when trace_path set
    _background_started: bool = False

    def start_background(self) -> None:
        """Async write-back workers + background loops (cmd/server.go:239-247).
        Ingestion reflectors start first so WaitForCacheSync-style readiness
        can observe them (cmd/server.go:111-147). Idempotent: the CLI calls
        it before reconciliation and SchedulerHTTPServer.start() calls it
        again."""
        if self._background_started:
            return
        self._background_started = True
        # Boot-heap freeze (ROADMAP item 5): everything constructed by
        # build_scheduler_app is long-lived; freezing it keeps steady-state
        # gen-2 collections from re-scanning the boot heap on the serving
        # tail.
        from spark_scheduler_tpu.server.runtime import freeze_boot_heap

        freeze_boot_heap()
        if self.ingestion is not None:
            self.ingestion.start()
        self.rr_cache.start()
        self.unschedulable_marker.start()
        self.demand_crd_watcher.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        if self.runtime_manager is not None:
            self.runtime_manager.start()

    def stop(self) -> None:
        if self.runtime_manager is not None:
            self.runtime_manager.stop()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.ingestion is not None:
            self.ingestion.stop()
        self.demand_crd_watcher.stop()
        self.unschedulable_marker.stop()
        self.rr_cache.flush()
        self.rr_cache.stop()
        self.demand_cache.flush()
        self.demand_cache.stop()
        if self.trace_writer is not None:
            self.trace_writer.close()
        self.solver.close()


def build_scheduler_app(
    backend: ClusterBackend,
    config: InstallConfig | None = None,
    metrics=None,
    events=None,
    waste=None,
    clock=None,
) -> SchedulerApp:
    import time as _time

    config = config or InstallConfig()
    clock = clock or _time.time
    if config.jax_compilation_cache_dir:
        InstallConfig.enable_jax_compile_cache(
            config.jax_compilation_cache_dir
        )

    # The scheduler owns its reservation CRD: create-or-upgrade + verify
    # Established before anything consumes it (cmd/server.go:103-109); the
    # full manifest (schemas + conversion strategy) is registered.
    ensure_resource_reservations_crd(
        backend, webhook_url=config.conversion_webhook_url
    )

    # Shared retry ladder (ISSUE 9): ONE policy shape for every kube
    # write-back consumer, with a per-kind circuit breaker so a down
    # backend is probed instead of hammered. `async_client_retry_count`
    # remains the attempt budget exactly as before.
    from spark_scheduler_tpu.faults.retry import CircuitBreaker, RetryPolicy
    from spark_scheduler_tpu.observability.telemetry import RetryTelemetry

    retry_policy = RetryPolicy(
        max_attempts=config.async_client_retry_count + 1,
        base_delay_s=config.retry_base_delay_s,
        multiplier=config.retry_multiplier,
        max_delay_s=config.retry_max_delay_s,
    )
    retry_telemetry = RetryTelemetry(
        metrics.registry if metrics is not None else None
    )

    def _breaker(consumer: str):
        if config.breaker_failure_threshold <= 0:
            return None
        return CircuitBreaker(
            failure_threshold=config.breaker_failure_threshold,
            reset_timeout_s=config.breaker_reset_timeout_s,
            on_transition=retry_telemetry.breaker_hook(consumer),
            name=consumer,
        )

    rr_cache = ResourceReservationCache(
        backend,
        max_retries=config.async_client_retry_count,
        sync_writes=config.sync_writes,
        retry_policy=retry_policy,
        breaker=_breaker("rr-write-back"),
        on_retry=lambda n, pause: retry_telemetry.on_retry(
            "rr-write-back", n, pause
        ),
    )
    demand_cache = SafeDemandCache(
        backend,
        max_retries=config.async_client_retry_count,
        sync_writes=config.sync_writes,
        retry_policy=retry_policy,
        breaker=_breaker("demand-write-back"),
        on_retry=lambda n, pause: retry_telemetry.on_retry(
            "demand-write-back", n, pause
        ),
    )
    soft_store = SoftReservationStore(backend)
    pod_lister = SparkPodLister(backend, config.instance_group_label)
    reservation_manager = ResourceReservationManager(
        backend, rr_cache, soft_store, pod_lister
    )
    overhead_computer = OverheadComputer(backend, reservation_manager)
    binpacker = select_binpacker(config.binpack_algo)
    demand_manager = DemandManager(
        backend,
        demand_cache,
        config.instance_group_label,
        is_single_az_binpacker=binpacker.is_single_az,
        events=events,
        waste=waste,
        clock=clock,
    )
    # Demand features activate only once the Demand CRD exists — it belongs
    # to the external autoscaler and may appear any time after startup
    # (demand_informer.go:75-138). SafeDemandCache additionally gates every
    # operation; the watcher wires the push-style consumers (GC, waste).
    demand_crd_watcher = LazyDemandCRDWatcher(backend, DEMAND_CRD)
    demand_crd_watcher.on_ready(lambda: start_demand_gc(backend, demand_manager))

    # Waste / retry-state lifecycle hooks (waste.go:90-146 informer hookup):
    # pod scheduled -> close out waste phases; pod deleted -> drop state.
    if waste is not None or metrics is not None:

        def _on_pod_update(old, new):
            if waste is not None and not old.node_name and new.node_name:
                waste.on_pod_scheduled(new)

        def _on_pod_delete(pod):
            if waste is not None:
                waste.on_pod_deleted(pod)
            if metrics is not None and hasattr(metrics, "forget_pod"):
                metrics.forget_pod(pod)

        backend.subscribe("pods", on_update=_on_pod_update, on_delete=_on_pod_delete)
    if waste is not None:
        from spark_scheduler_tpu.models.demands import DEMAND_NAME_PREFIX

        def _on_demand_update(old, new):
            # The autoscaler flips the phase to fulfilled — the in-process
            # ElasticAutoscaler when enabled, the external one otherwise
            # (waste.go:235-243 OnDemandFulfilled). Either way it arrives
            # here as a backend demand update.
            if new.is_fulfilled() and not old.is_fulfilled():
                pod_name = new.name[len(DEMAND_NAME_PREFIX):]
                waste.on_demand_fulfilled((new.namespace, pod_name))

        demand_crd_watcher.on_ready(
            lambda: backend.subscribe("demands", on_update=_on_demand_update)
        )
    # Multi-device window-solve engine: `solver.mesh {groups, node-shards}`
    # wins over the `solver.device-pool` shorthand when both are set.
    mesh = None
    if config.solver_mesh_groups or config.solver_mesh_node_shards:
        mesh = (
            config.solver_mesh_groups or 1,
            config.solver_mesh_node_shards or 1,
        )
    solver = PlacementSolver(
        driver_label_priority=(
            config.driver_prioritized_node_label.as_tuple()
            if config.driver_prioritized_node_label
            else None
        ),
        executor_label_priority=(
            config.executor_prioritized_node_label.as_tuple()
            if config.executor_prioritized_node_label
            else None
        ),
        device_pool=config.solver_device_pool,
        mesh=mesh,
        quarantine_probe_s=config.quarantine_probe_s,
        prune_top_k=config.solver_prune_top_k,
        prune_slack=config.solver_prune_slack,
        delta_statics=config.solver_delta_statics,
        scale_tier=config.solver_scale_tier,
        build_oracle=config.solver_build_oracle,
        lazy_warm_start=config.solver_lazy_warm_start,
    )
    recorder = None
    if config.flight_recorder:
        # Flight recorder + solver telemetry: decision explainability
        # (GET /debug/decisions) and foundry.spark.scheduler.solver.*
        # series. Telemetry lands in the caller's registry when metrics
        # are wired so GET /metrics exposes it; otherwise it keeps a
        # private registry (still drives compile hit/miss on records).
        from spark_scheduler_tpu.observability import (
            FlightRecorder,
            SolverTelemetry,
        )

        recorder = FlightRecorder(
            capacity=config.flight_recorder_capacity, clock=clock
        )
        solver.telemetry = SolverTelemetry(
            metrics.registry if metrics is not None else None
        )
    trace_writer = None
    if config.trace_path:
        if recorder is None:
            import warnings

            warnings.warn(
                "trace.path set but the flight recorder is disabled — "
                "decision tracing requires flight-recorder: true; "
                "no trace will be written",
                RuntimeWarning,
                stacklevel=2,
            )
        else:
            # Durable decision trace (ISSUE 17): header (config
            # fingerprint) -> bootstrap journal of the pre-existing world
            # -> live event hooks. The sink rides the recorder, so the
            # extender's capture wrappers cost one attribute check when
            # tracing is off.
            from spark_scheduler_tpu.replay.trace import TraceWriter

            trace_writer = TraceWriter(
                config.trace_path,
                clock=clock,
                decisions=config.trace_decisions,
                epoch_fn=lambda: getattr(backend, "nodes_version", None),
            )
            trace_writer.write_header(config)
            trace_writer.bootstrap(backend)
            recorder.attach_sink(trace_writer)
            backend.subscribe(
                "nodes",
                on_add=trace_writer.on_node_add,
                on_update=trace_writer.on_node_update,
                on_delete=trace_writer.on_node_delete,
            )
            backend.subscribe(
                "pods",
                on_add=trace_writer.on_pod_add,
                on_update=trace_writer.on_pod_update,
                on_delete=trace_writer.on_pod_delete,
            )
    # Degraded-mode controller (ISSUE 9): when no device slot can serve,
    # the solver consults this policy — host greedy fallback or
    # 503+Retry-After shedding. Readiness and /debug/state reflect it.
    from spark_scheduler_tpu.faults.degraded import DegradedModeController

    solver.degraded = DegradedModeController(
        policy=config.degraded_mode,
        retry_after_s=config.degraded_retry_after_s,
        clock=clock,
        on_change=(
            solver.telemetry.on_degraded
            if solver.telemetry is not None
            else None
        ),
    )
    # Delta-maintained reserved-usage aggregate over the solver's node-index
    # space: the hot path reads a dense array instead of walking every
    # reservation slot per request (SURVEY.md §7 latency budget).
    reservation_manager.attach_usage_tracker(
        ReservedUsageTracker(solver.registry, rr_cache, soft_store)
    )
    reconciler = FailoverReconciler(
        backend,
        pod_lister,
        rr_cache,
        soft_store,
        demand_manager,
        overhead_computer,
        config.instance_group_label,
    )
    # Policy engine (ISSUE 16): constructed ONLY when enabled — with
    # policy=None every extender hook takes the exact pre-policy branch,
    # keeping the default FIFO path byte-identical.
    policy = None
    if config.policy_enabled:
        from spark_scheduler_tpu.policy import PolicyConfig, PolicyEngine

        policy = PolicyEngine(
            PolicyConfig(
                ordering=config.policy_ordering,
                preemption=config.policy_preemption,
                max_evictions=config.policy_max_evictions,
                promote_after_s=config.policy_promote_after_s,
                defrag=config.policy_defrag,
                defrag_interval_s=config.policy_defrag_interval_s,
                defrag_budget=config.policy_defrag_budget,
                protected_class=config.policy_protected_class,
            ),
            backend=backend,
            rr_cache=rr_cache,
            pod_lister=pod_lister,
            soft_store=soft_store,
            reservation_manager=reservation_manager,
            solver=solver,
            clock=clock,
            metrics_registry=(
                metrics.registry if metrics is not None else None
            ),
        )
    extender = SparkSchedulerExtender(
        backend,
        pod_lister,
        reservation_manager,
        demand_manager,
        overhead_computer,
        binpacker,
        solver,
        config=ExtenderConfig(
            fifo=config.fifo,
            fifo_config=config.fifo_config,
            instance_group_label=config.instance_group_label,
            schedule_dynamically_allocated_executors_in_same_az=(
                config.should_schedule_dynamically_allocated_executors_in_same_az
            ),
            batched_admission=config.batched_admission,
            resync_gap_seconds=config.resync_gap_seconds,
        ),
        reconciler=reconciler,
        metrics=metrics,
        events=events,
        waste=waste,
        recorder=recorder,
        clock=clock,
        policy=policy,
    )
    marker = UnschedulablePodMarker(
        backend,
        overhead_computer,
        binpacker,
        solver,
        timeout_s=config.unschedulable_pod_timeout_s,
        clock=clock,
    )
    ingestion = None
    if config.kube_api_url == "in-cluster":
        # Serviceaccount CA + rotating bearer token against
        # https://kubernetes.default.svc (rest.InClusterConfig slot,
        # cmd/server.go:57-75 "kube-config-type: in-cluster").
        from spark_scheduler_tpu.kube.reflector import in_cluster_ingestion

        ingestion = in_cluster_ingestion(backend, metrics=metrics, clock=clock)
    elif config.kube_api_url:
        from spark_scheduler_tpu.kube.reflector import KubeIngestion

        ingestion = KubeIngestion(
            backend,
            config.kube_api_url,
            metrics=metrics,
            clock=clock,
            insecure_skip_tls_verify=config.kube_api_insecure_skip_tls_verify,
        )
    autoscaler = None
    if config.autoscaler_enabled:
        # In-process elastic autoscaler: consumes the pending demands this
        # scheduler emits, provisions simulated nodes through the same
        # backend, and drains idle ones — replacing the external cluster
        # autoscaler (and the hand-rolled phase flips tests used to do).
        from spark_scheduler_tpu.autoscaler import (
            AutoscalerMetrics,
            ElasticAutoscaler,
            NodeProvisioner,
            ScaleDownDrainer,
        )
        from spark_scheduler_tpu.autoscaler.provisioner import (
            PROVISIONED_BY_LABEL,
            PROVISIONER_NAME,
        )
        from spark_scheduler_tpu.core.census import ClusterCensus
        from spark_scheduler_tpu.models.resources import Resources

        # Event-maintained control-loop census: the autoscaler's cluster
        # size and the drainer's busy/never-drain sets become resident
        # O(changed) state instead of per-pass full walks — the control
        # loops' million-node-tier fix (ROADMAP item 4).
        census = ClusterCensus(
            backend,
            rr_cache,
            soft_store,
            eligible_label=(PROVISIONED_BY_LABEL, PROVISIONER_NAME),
        )
        autoscaler = ElasticAutoscaler(
            backend,
            provisioner=NodeProvisioner(
                backend,
                config.instance_group_label,
                Resources.from_quantities(
                    config.autoscaler_node_cpu,
                    config.autoscaler_node_memory,
                    config.autoscaler_node_gpu,
                    round_up=False,
                ),
                zones=config.autoscaler_zones,
                clock=clock,
            ),
            drainer=ScaleDownDrainer(
                backend,
                rr_cache,
                soft_store,
                idle_ttl_s=config.autoscaler_idle_ttl_s,
                clock=clock,
                census=census,
            ),
            census=census,
            max_cluster_size=config.autoscaler_max_cluster_size,
            poll_interval_s=config.autoscaler_poll_interval_s,
            metrics=AutoscalerMetrics(
                metrics.registry if metrics is not None else None
            ),
            recorder=recorder,
            clock=clock,
        )
        # The demand-add wakeup waits for the Demand CRD like every other
        # demand consumer.
        demand_crd_watcher.on_ready(autoscaler.attach)
    # A pre-existing Demand CRD (registered before the app was built)
    # activates demand features synchronously; otherwise the background
    # poll in start_background() picks it up.
    demand_crd_watcher.check_now()
    app = SchedulerApp(
        backend=backend,
        config=config,
        rr_cache=rr_cache,
        demand_cache=demand_cache,
        soft_store=soft_store,
        pod_lister=pod_lister,
        reservation_manager=reservation_manager,
        overhead_computer=overhead_computer,
        demand_manager=demand_manager,
        reconciler=reconciler,
        solver=solver,
        extender=extender,
        unschedulable_marker=marker,
        demand_crd_watcher=demand_crd_watcher,
        ingestion=ingestion,
        autoscaler=autoscaler,
        recorder=recorder,
        trace_writer=trace_writer,
    )
    if config.runtime_config_path:
        from spark_scheduler_tpu.server.runtime import RuntimeConfigManager

        app.runtime_manager = RuntimeConfigManager(app, config.runtime_config_path)
    return app
