r"""HTTP front-end — the witchcraft-server slot (cmd/server.go, cmd/endpoints.go).

Routes (all JSON):

  POST /predicates            kube-scheduler extender filter call
                              (ExtenderArgs -> ExtenderFilterResult,
                              cmd/endpoints.go:28-42)
  POST /convert               CRD version-conversion webhook
                              (ConversionReview, SURVEY.md L9; also served
                              standalone by ConversionWebhookServer)
  GET  /status/liveness       200 when the process is up
  GET  /status/readiness      200 once cluster state has been synced
                              (at least one node known to the backend)
  GET  /metrics               metric-registry snapshot
  PUT  /state/nodes           upsert a k8s Node object   \  informer-watch
  PUT  /state/pods            upsert a k8s Pod object     } substitute: the
  DELETE /state/pods/{ns}/{n} remove a pod               /  state-sync API

The reference learns cluster state through apiserver watch streams
(cmd/server.go:111-147); in environments without one, the state-sync routes
carry the same information. Threaded stdlib server: the predicate handler is
serialized by the extender's internal ordering, matching the reference's
single Predicate goroutine assumption (SURVEY.md §0).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.server.conversion import convert_review
from spark_scheduler_tpu.server.kube_io import (
    extender_args_from_k8s,
    filter_result_to_k8s,
    node_from_k8s,
    pod_from_k8s,
)


class _JSONHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing + the routes both servers serve
    (liveness, POST /convert)."""

    def log_message(self, *args):  # quiet
        pass

    def _write(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        return json.loads(self.rfile.read(length) or b"{}")

    def _handle_liveness(self) -> None:
        self._write(200, {"status": "up"})

    def _handle_convert(self) -> None:
        try:
            review = self._body()
        except Exception as exc:
            self._write(400, {"error": str(exc)})
            return
        self._write(200, convert_review(review))


def _run_threaded(server: ThreadingHTTPServer, name: str) -> threading.Thread:
    thread = threading.Thread(target=server.serve_forever, daemon=True, name=name)
    thread.start()
    return thread


def _maybe_wrap_tls(
    server: ThreadingHTTPServer,
    cert_file: str | None,
    key_file: str | None,
    client_ca_files=None,
    handshake_timeout_s: float = 30.0,
) -> bool:
    """Serve HTTPS when a cert/key pair is configured — the witchcraft
    server slot (reference config server.cert-file/key-file/client-ca-files,
    examples/extender.yml:75-80). `client_ca_files` (str or list) requires
    client certificates signed by ANY of the given CAs (mTLS). Returns True
    if TLS was enabled.

    The TLS handshake runs PER CONNECTION in the worker thread (via a
    finish_request override), never in the accept loop: a client that
    stalls mid-handshake ties up one bounded-timeout worker, not the whole
    server."""
    if not cert_file:
        return False
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file or cert_file)
    if isinstance(client_ca_files, str):
        client_ca_files = [client_ca_files]
    for ca in client_ca_files or []:
        ctx.load_verify_locations(ca)
    if client_ca_files:
        ctx.verify_mode = ssl.CERT_REQUIRED

    orig_finish_request = server.finish_request

    def finish_request(request, client_address):
        # ThreadingMixIn calls finish_request from the per-connection worker
        # thread; the handshake happens here under a timeout.
        try:
            request.settimeout(handshake_timeout_s)
            tls_request = ctx.wrap_socket(request, server_side=True)
        except (OSError, ssl.SSLError):
            try:
                request.close()
            except OSError:
                pass
            return
        orig_finish_request(tls_request, client_address)

    server.finish_request = finish_request
    return True


class SchedulerHTTPServer:
    def __init__(
        self,
        app,
        registry=None,
        host: str = "127.0.0.1",
        port: int = 8484,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        debug_routes: bool = False,
    ):
        self.app = app
        self.registry = registry
        # /debug/* (trace dump, JAX profiler control) is an explicit opt-in:
        # on the cluster-exposed extender port it would let any peer start
        # profiler writes to server-side paths.
        self.debug_routes = debug_routes
        self.ready = threading.Event()
        self._shutdown = threading.Event()
        # One predicate at a time — the serialization point for mutable
        # scheduling state (SURVEY.md §7 "Mutable-state races").
        self._predicate_lock = threading.Lock()
        outer = self

        class Handler(_JSONHandler):
            def do_GET(self):
                if self.path == "/status/liveness":
                    self._handle_liveness()
                elif self.path == "/status/readiness":
                    code = 200 if outer.ready.is_set() else 503
                    self._write(code, {"ready": outer.ready.is_set()})
                elif self.path == "/metrics":
                    snap = outer.registry.snapshot() if outer.registry else {}
                    self._write(200, snap)
                elif self.path == "/debug/traces" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import tracer

                    self._write(200, {"spans": tracer().finished_spans()})
                else:
                    self._write(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/predicates":
                    from spark_scheduler_tpu.tracing import (
                        pod_safe_params,
                        svc1log,
                        tracer,
                    )

                    try:
                        pod, node_names = extender_args_from_k8s(self._body())
                    except Exception as exc:
                        self._write(500, {"Error": str(exc)})
                        return
                    # Root span continues the caller's b3 trace context
                    # (the witchcraft tracing middleware slot).
                    with tracer().root_from_headers(
                        self.headers, "predicate", pod=f"{pod.namespace}/{pod.name}"
                    ) as root:
                        try:
                            with outer._predicate_lock:
                                result = outer.app.extender.predicate(
                                    ExtenderArgs(pod=pod, node_names=node_names)
                                )
                        except Exception as exc:
                            # Internal errors ride the protocol's Error
                            # channel (ExtenderFilterResult.Error) so
                            # kube-scheduler gets a well-formed response
                            # instead of a dropped connection.
                            root.tag("outcome", "failure-internal")
                            svc1log().error(
                                "predicate failed",
                                error=repr(exc),
                                **pod_safe_params(pod),
                            )
                            self._write(
                                200,
                                {"NodeNames": [], "FailedNodes": {}, "Error": str(exc)},
                            )
                            return
                        root.tag("outcome", result.outcome)
                        svc1log().info(
                            "predicate",
                            outcome=result.outcome,
                            nodes=list(result.node_names),
                            **pod_safe_params(pod),
                        )
                    self._write(200, filter_result_to_k8s(result))
                elif self.path == "/convert":
                    self._handle_convert()
                elif self.path == "/debug/profile/start" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import start_jax_profile

                    try:
                        body = self._body()
                    except Exception:
                        body = {}
                    if not isinstance(body, dict):
                        body = {}
                    log_dir = body.get("dir") or "/tmp/spark-scheduler-jax-trace"
                    try:
                        started = start_jax_profile(log_dir)
                    except Exception as exc:  # unwritable dir etc.
                        self._write(500, {"profiling": False, "error": str(exc)})
                        return
                    self._write(
                        200 if started else 409,
                        {"profiling": started, "dir": log_dir},
                    )
                elif self.path == "/debug/profile/stop" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import stop_jax_profile

                    try:
                        out_dir = stop_jax_profile()
                    except Exception as exc:
                        self._write(500, {"profiling": False, "error": str(exc)})
                        return
                    self._write(
                        200 if out_dir else 409,
                        {"profiling": False, "dir": out_dir},
                    )
                else:
                    self._write(404, {"error": "not found"})

            def do_PUT(self):
                try:
                    if self.path == "/state/nodes":
                        node = node_from_k8s(self._body())
                        existing = outer.app.backend.get_node(node.name)
                        if existing is None:
                            outer.app.backend.add_node(node)
                        else:
                            outer.app.backend.update("nodes", node)
                        outer.ready.set()  # first synced node => ready
                        self._write(200, {"applied": node.name})
                    elif self.path == "/state/pods":
                        pod = pod_from_k8s(self._body())
                        if outer.app.backend.get("pods", pod.namespace, pod.name) is None:
                            outer.app.backend.add_pod(pod)
                        else:
                            outer.app.backend.update_pod(pod)
                        self._write(200, {"applied": pod.name})
                    else:
                        self._write(404, {"error": "not found"})
                except Exception as exc:
                    self._write(500, {"error": str(exc)})

            def do_DELETE(self):
                try:
                    parts = self.path.strip("/").split("/")
                    if len(parts) == 4 and parts[:2] == ["state", "pods"]:
                        ns, name = parts[2], parts[3]
                        pod = outer.app.backend.get("pods", ns, name)
                        if pod is None:
                            self._write(404, {"error": "pod not found"})
                        else:
                            outer.app.backend.delete_pod(pod)
                            self._write(200, {"deleted": name})
                    else:
                        self._write(404, {"error": "not found"})
                except Exception as exc:  # e.g. concurrent-delete race
                    self._write(500, {"error": str(exc)})

        # Socket read timeout per connection: a stalled client cannot pin a
        # handler thread forever (the extender protocol budget is 30 s,
        # examples/extender.yml:59).
        Handler.timeout = request_timeout_s
        self._server = ThreadingHTTPServer((host, port), Handler)
        self.tls = _maybe_wrap_tls(
            self._server, cert_file, key_file, client_ca_files,
            handshake_timeout_s=request_timeout_s,
        )
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self.app.start_background()
        self._thread = _run_threaded(self._server, "scheduler-http")
        # Ready only once cluster state exists; pre-seeded backends (tests,
        # embedded use) are ready at once, otherwise the first successful
        # PUT /state/nodes — or watch-ingestion cache sync
        # (WaitForCacheSync, cmd/server.go:140-147) — flips it.
        if self.app.backend.list_nodes():
            self.ready.set()
        elif getattr(self.app, "ingestion", None) is not None:
            def _ready_on_sync():
                # Wait as long as it takes (WaitForCacheSync blocks until
                # sync or shutdown) — a slow apiserver must not leave the
                # server permanently not-ready.
                while not self.ready.is_set():
                    if self.app.ingestion.wait_synced(timeout=30.0):
                        self.ready.set()
                        return
                    if self._shutdown.is_set():
                        return

            threading.Thread(
                target=_ready_on_sync, daemon=True, name="ingestion-sync-ready"
            ).start()

    def stop(self) -> None:
        self._shutdown.set()
        self.ready.clear()
        # shutdown() blocks on serve_forever()'s exit handshake — only call
        # it if serving actually started (Ctrl-C can land before start()
        # finished, e.g. during the pre-start cache-sync wait).
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
        self._server.server_close()
        self.app.stop()

    def join(self) -> None:
        """Block until the serving thread exits (after start())."""
        if self._thread is not None:
            self._thread.join()

    def serve_forever(self) -> None:
        self.start()
        try:
            self.join()
        except KeyboardInterrupt:
            self.stop()


class ConversionWebhookServer:
    """Standalone conversion-webhook service (the reference ships this as a
    second binary: spark-scheduler-conversion-webhook/cmd/server.go:39-54).
    Serves only POST /convert + liveness; no scheduler state."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8485,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
    ):
        class Handler(_JSONHandler):
            def do_GET(self):
                if self.path == "/status/liveness":
                    self._handle_liveness()
                else:
                    self._write(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/convert":
                    self._handle_convert()
                else:
                    self._write(404, {"error": "not found"})

        Handler.timeout = request_timeout_s
        self._server = ThreadingHTTPServer((host, port), Handler)
        self.tls = _maybe_wrap_tls(
            self._server, cert_file, key_file, client_ca_files,
            handshake_timeout_s=request_timeout_s,
        )
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = _run_threaded(self._server, "conversion-http")

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
        self._server.server_close()

    def serve_forever(self) -> None:
        self.start()
        try:
            self._thread.join()
        except KeyboardInterrupt:
            self.stop()
