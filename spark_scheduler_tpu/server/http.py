r"""HTTP front-end — the witchcraft-server slot (cmd/server.go, cmd/endpoints.go).

Routes (all JSON):

  POST /predicates            kube-scheduler extender filter call
                              (ExtenderArgs -> ExtenderFilterResult,
                              cmd/endpoints.go:28-42)
  POST /convert               CRD version-conversion webhook
                              (ConversionReview, SURVEY.md L9; also served
                              standalone by ConversionWebhookServer)
  GET  /status/liveness       200 when the process is up
  GET  /status/readiness      200 once cluster state has been synced
                              (at least one node known to the backend)
  GET  /metrics               metric-registry snapshot: JSON by default,
                              Prometheus text exposition when the Accept
                              header prefers text/plain (or
                              ?format=prometheus) — the pull surface for
                              scrape stacks
  GET  /debug/decisions       flight-recorder query (?app=&verdict=&role=
                              &limit=), gated on debug-routes
  GET  /debug/state           point-in-time scheduler state (hard/soft
                              reservations, FIFO queue, unschedulable set,
                              node fleet), gated on debug-routes
  PUT  /state/nodes           upsert a k8s Node object   \  informer-watch
  PUT  /state/pods            upsert a k8s Pod object     } substitute: the
  DELETE /state/pods/{ns}/{n} remove a pod               /  state-sync API

The reference learns cluster state through apiserver watch streams
(cmd/server.go:111-147); in environments without one, the state-sync routes
carry the same information.

This module is the SERVING CORE: the PredicateBatcher (the serialization
point for mutable scheduling state) and the server facades that wire a
route table (server/routing.py) onto a transport. Two transports exist,
selected by the `server.transport` install knob:

  threaded (default)  server/transport_threaded.py — the stdlib
                      thread-per-connection stack; simplest to debug, but
                      its ceiling is the stdlib's own (round-5: the served
                      path reached 96.6% of its null-handler rig ceiling).
  async               server/transport_async.py — a single-threaded event
                      loop with an incremental HTTP/1.1 parser, pipelined
                      keep-alive framing, one-write responses, and explicit
                      backpressure (max-connections 503, max-body-bytes
                      413, batcher-queue-depth load shedding). Requests
                      hand straight to the PredicateBatcher; the handler
                      threads it replaces were pure overhead.
"""

from __future__ import annotations

import threading

# Back-compat re-exports: these lived here before the transport split
# (kube/apiserver.py wraps its listener with _maybe_wrap_tls; tests import
# the framing exceptions from server.http).
from spark_scheduler_tpu.server.routing import (  # noqa: F401
    BodyTooLarge,
    ConversionRoutes,
    SchedulerRoutes,
    UnframeableBody,
    UnsupportedTransferEncoding,
)
from spark_scheduler_tpu.server.transport_threaded import (  # noqa: F401
    ThreadedTransport,
    _maybe_wrap_tls,
    build_server_ssl_context,
)

TRANSPORTS = ("threaded", "async")
# Ingest lanes (`server.ingest`): how a framed predicate body becomes
# ExtenderArgs — "python" (json.loads + dict walk) or "native" (the C++
# framer/decoder in native/runtime.cpp emitting zero-copy tickets). See
# server/ingest.py. The native lane composes with BOTH transports: the
# async transport swaps its Python parser for the native framer, the
# threaded transport keeps stdlib framing and routes predicate bodies
# through the native decoder.
INGESTS = ("python", "native")


class _CallbackEvent:
    """Event-shaped completion hook for `PredicateBatcher.submit_nowait`:
    the dispatcher's `entry[1].set()` fires the registered callback exactly
    once (set is idempotent under races between the dispatcher and
    `stop()`), so the dispatcher code path is identical for blocking and
    callback entries."""

    __slots__ = ("_cb", "_fired", "_lock")

    def __init__(self, cb):
        self._cb = cb
        self._fired = False
        self._lock = threading.Lock()

    def set(self) -> None:
        with self._lock:
            if self._fired:
                return
            self._fired = True
            cb, self._cb = self._cb, None
        try:
            cb()
        except Exception:
            # A failing responder (e.g. a client that vanished) must never
            # kill the dispatcher thread mid-window.
            pass

    def is_set(self) -> bool:
        return self._fired

    def wait(self, timeout=None) -> bool:  # Event-interface parity
        return self._fired


class PredicateBatcher:
    """Coalesces concurrent POST /predicates calls into windowed
    `extender.predicate_batch` solves (VERDICT r2 #1).

    A single dispatcher thread drains the queue: whatever arrived while the
    previous window was being served forms the next window, plus — during
    busy periods only — a short accumulation hold (`hold_ms`) so clients
    answering the previous window can rejoin and windows stay near the
    concurrency level. An idle server serves a lone request immediately
    (window of 1 = the solo path); a loaded server amortizes one device
    solve over every queued request. The dispatcher thread is ALSO the
    serialization point for mutable scheduling state, replacing the
    per-request lock (SURVEY.md §7 "Mutable-state races")."""

    # Debug log of claim decisions is HARD-BOUNDED: recording stops at this
    # many entries (tests/test_predicate_batcher.py pins the bound).
    CLAIM_LOG_CAP = 4096

    def __init__(
        self, extender, max_window: int = 32, hold_ms: float = 25.0,
        registry=None, pipeline_depth: int = 3, fuse_windows: int = 1,
    ):
        self._extender = extender
        self._max_window = max_window
        # How many dispatched windows may be awaiting their decision pull
        # at once. Concurrent device_get RPCs overlap (the fetch pool), so
        # depth N divides the per-window round-trip cost by up to N.
        # With fusion, depth counts DISPATCHES (a fused batch of K windows
        # is one round trip) — see _run's inflight_dispatches.
        self._pipeline_depth = max(1, pipeline_depth)
        # Fused multi-window dispatch (`solver.fuse-windows`): when the
        # backlog holds more than one window's worth of requests, claim up
        # to fuse_windows x max_window of them and dispatch the sub-windows
        # as ONE fused device program (extender.predicate_windows_dispatch)
        # — K windows share one h2d + dispatch + d2h round trip instead of
        # paying one each. 1 = today's one-window-per-dispatch behavior.
        self._fuse_windows = max(1, fuse_windows)
        # Window-size histogram + wait time in the tagged registry (the
        # reference's metric discipline for every serving subsystem,
        # metrics/metrics.go:29-76).
        self._registry = registry
        # Adaptive accumulation: when the PREVIOUS window was coalesced
        # (>1 request — i.e. we are in a busy period), hold up to hold_ms
        # for stragglers before solving, so clients answering the previous
        # window have time to submit their next request and windows stay
        # near the concurrency level instead of oscillating small. A lone
        # request on an idle server is never held.
        self._hold_s = hold_ms / 1e3
        self._last_window = 1
        # Whether the previous window dispatched a DEVICE solve. The hold
        # exists to amortize one device program over more requests; an
        # executor-only window is pure host work and holding for
        # stragglers just adds their wait to everyone's latency.
        self._last_had_solve = False
        # The hold engages only while a busy period is LIVE: within this
        # TTL of the previous coalesced window. A lone request on a
        # since-idle server is served immediately.
        self._busy_ttl_s = 2.0
        self._busy_until = 0.0
        self._cv = threading.Condition()
        self._queue: list[list] = []  # [args, event, result, exception, trace]
        # Entries the dispatcher has claimed whose events may not be set
        # yet — what stop() fails when the dispatcher thread is stalled in
        # a blocking fetch against a dead tunnel (join times out but
        # in-flight HTTP handlers must not hang until request timeout).
        # Entries are REMOVED on completion (_finish_entries), so a
        # timed-out-then-completed request never leaves a slot behind.
        self._claimed: list[list] = []
        self._stopped = False
        # Serving stats (surfaced at GET /metrics).
        self.windows_served = 0
        self.requests_served = 0
        self.max_window_seen = 0
        # Debug log of claim decisions:
        # (window, queue_after, pending, hold_ms). Cheap appends; recording
        # stops at the CLAIM_LOG_CAP bound; stats() exposes the tail for
        # serving-dynamics forensics.
        self.claim_log: list[tuple] = []
        # Windows dispatched while another window was still in flight (the
        # dispatch-before-fetch overlap actually engaging).
        self.pipelined_windows = 0
        # Fused claims actually taken (>1 sub-window in one dispatch) and
        # the largest fused batch seen.
        self.fused_dispatches = 0
        self.max_fused_k = 1
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="predicate-batcher"
        )
        self._thread.start()

    def submit(self, args, timeout: float | None = None):
        from spark_scheduler_tpu.tracing import tracer

        # Carry the handler thread's trace context to the dispatcher.
        entry = [args, threading.Event(), None, None, tracer().current()]
        with self._cv:
            if self._stopped:
                raise RuntimeError("scheduler is shutting down")
            self._queue.append(entry)
            self._cv.notify()
        if not entry[1].wait(timeout):
            # Shed the abandoned request: if the dispatcher has not claimed
            # it yet, remove it so no window slot is burned solving for a
            # client that already got an error (overload would otherwise
            # spiral: dead entries crowd out live ones). If it WAS claimed,
            # the solve proceeds harmlessly and _finish_entries clears the
            # claimed slot at completion.
            self.abandon(entry)
            raise TimeoutError("predicate window timed out")
        if entry[3] is not None:
            raise entry[3]
        return entry[2]

    def submit_nowait(self, args, done, trace_span=None):
        """Callback-mode submission for event-loop transports: no thread
        parks. `done(result, exc)` is invoked exactly once — from the
        dispatcher thread on completion, or from the stopping thread at
        shutdown. Returns the queue entry for use with `abandon`."""
        entry = [args, None, None, None, trace_span]

        def _fire():
            done(entry[2], entry[3])

        entry[1] = _CallbackEvent(_fire)
        with self._cv:
            if self._stopped:
                raise RuntimeError("scheduler is shutting down")
            self._queue.append(entry)
            self._cv.notify()
        return entry

    def abandon(self, entry) -> bool:
        """Remove a not-yet-claimed entry (client timed out / went away).
        True when removed — its event/callback will never fire. False when
        the dispatcher already claimed it: the solve proceeds and the
        caller's completion hook must tolerate (or dedup) the late fire."""
        with self._cv:
            try:
                self._queue.remove(entry)
                return True
            except ValueError:
                return False

    def queue_depth(self) -> int:
        """Current un-claimed backlog — what 503 load shedding keys on."""
        with self._cv:
            return len(self._queue)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        # Fail every claimed/queued entry whose event is still unset so
        # in-flight handlers return instead of hanging until their own
        # request timeout — covers a dispatcher STALLED in a decision pull
        # against a dead tunnel (join timed out) and one that DIED with a
        # batch's events unset. No-op on a clean exit (everything is set);
        # a late set() by a stalled thread is harmless (set is idempotent
        # for both entry kinds).
        err = RuntimeError("scheduler is shutting down")
        with self._cv:
            leftovers = self._claimed + self._queue
            self._queue.clear()
        for entry in leftovers:
            if not entry[1].is_set():
                entry[3] = err
                entry[1].set()

    def _run(self) -> None:
        """PIPELINED serving loop: dispatch the next window (host build +
        async device dispatch) while up to `pipeline_depth` earlier windows
        are still awaiting their decision pulls. Each window's pull starts
        eagerly on the solver's fetch pool at dispatch, and concurrent
        pulls overlap on the wire, so steady-state cycle time approaches
        max(host work, RTT / depth) instead of host + RTT. Windows complete
        strictly in dispatch order. Decisions are unchanged: the solver
        threads the committed base availability device-side across
        in-flight windows (build_tensors_pipelined), an app whose admission
        is still in flight is deferred to its own window's post-apply solo
        loop (extender in-flight set), and a ticket with no dispatched
        solve (the solo path) drains the pipeline before serving."""
        import time as _time
        from collections import deque

        from spark_scheduler_tpu.core.solver import PipelineDrainRequired

        pending: deque = deque()  # (ticket, batch) in dispatch order

        def complete_head():
            ok = self._complete_window(pending.popleft())
            if not ok and pending:
                # A failed fetch dropped the solver's pipelined state; the
                # remaining in-flight windows' gangs exist only in their
                # (still valid) device decisions. Apply them ALL before any
                # new dispatch — a fresh full upload from the host view
                # would otherwise lack their capacity debits and the next
                # window could double-book.
                while pending:
                    self._complete_window(pending.popleft())

        def complete_all():
            while pending:
                complete_head()

        def head_ready() -> bool:
            t = pending[0][0]
            if t.handle is None:
                return False
            # WindowHandle.fetch_ready covers both the single-device eager
            # pull and the multi-device engine's per-partition futures;
            # fall back to the bare blob_future for handle stubs (tests).
            ready = getattr(t.handle, "fetch_ready", None)
            if ready is not None:
                return ready()
            fut = getattr(t.handle, "blob_future", None)
            return fut is not None and fut.done()

        def eager_futures(handle) -> list:
            parts = getattr(handle, "parts", None)
            if parts:
                return [p.future for p in parts]
            fut = getattr(handle, "blob_future", None)
            return [fut] if fut is not None else []

        def inflight_dispatches() -> int:
            """Pipeline depth in DEVICE ROUND TRIPS: every sub-window of
            one fused dispatch shares its umbrella's dispatch_id, so a
            fused batch of K windows counts ONCE against pipeline_depth —
            K tickets, one in-flight decision pull."""
            ids = set()
            for t, _ in pending:
                did = getattr(t.handle, "dispatch_id", None)
                ids.add(did if did is not None else id(t))
            return len(ids)

        while True:
            with self._cv:
                while not self._queue and not self._stopped and not pending:
                    self._cv.wait()
                busy = (
                    self._last_window > 1
                    and _time.monotonic() < self._busy_until
                )
                if (
                    not self._stopped
                    and self._queue
                    and not pending
                    and self._hold_s > 0
                    and busy
                    and self._last_had_solve
                ):
                    # Accumulation hold, only when nothing is in flight — a
                    # pending window's fetch IS the accumulation period
                    # otherwise: requests arriving during it dispatch as
                    # the next window and their solve overlaps the fetch
                    # (measured: under a GIL-bound lockstep cohort this
                    # staggered-subgroup pipelining beats holding for the
                    # full cohort, whose resubmission takes tens of ms —
                    # holds serialize RTTs that the overlap hides).
                    # Deliberately NO stopped-growing early exit: arrival
                    # gaps of several ms mid-resubmission made it claim
                    # straggler subgroups that then ratcheted the window
                    # size down. Cost: after a cohort SHRINKS, the first
                    # window waits the full hold once; the target then
                    # adapts to the new cohort size.
                    hold_t0 = _time.monotonic()
                    target = min(self._last_window, self._max_window)
                    deadline = hold_t0 + self._hold_s
                    while (
                        len(self._queue) < target and not self._stopped
                    ):
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    hold_ms = (_time.monotonic() - hold_t0) * 1e3
                else:
                    hold_ms = 0.0
                if self._stopped:
                    err = RuntimeError("scheduler is shutting down")
                    for _, entries in pending:
                        for entry in entries:
                            entry[3] = err
                            entry[1].set()
                    pending.clear()
                    for entry in self._queue:
                        entry[3] = err
                        entry[1].set()
                    self._queue.clear()
                    return
                # Fused claim: take up to fuse-windows x max-window of the
                # backlog; anything past one window's worth splits into
                # sub-windows dispatched as ONE fused device program.
                claim = self._max_window * self._fuse_windows
                batch = self._queue[:claim]
                del self._queue[:claim]
                if batch and len(self.claim_log) < self.CLAIM_LOG_CAP:
                    self.claim_log.append((
                        len(batch), len(self._queue), len(pending),
                        round(hold_ms, 1),
                    ))
                self._claimed = [
                    e for e in self._claimed if not e[1].is_set()
                ]
                self._claimed.extend(batch)
                if batch:
                    self._last_window = len(batch)
                    if len(batch) > 1:
                        self._busy_until = (
                            _time.monotonic() + self._busy_ttl_s
                        )
            dispatched: list = []
            if batch:
                sub_batches = [
                    batch[i : i + self._max_window]
                    for i in range(0, len(batch), self._max_window)
                ]
                try:
                    dispatched = self._dispatch_batches(sub_batches)
                except PipelineDrainRequired:
                    # Topology changed under in-flight windows: apply them
                    # first, then the fresh full upload is safe.
                    complete_all()
                    try:
                        dispatched = self._dispatch_batches(sub_batches)
                    except Exception as exc:
                        self._fail_batch(batch, exc)
                except Exception as exc:
                    self._fail_batch(batch, exc)
            if dispatched:
                self._last_had_solve = any(
                    t.handle is not None for t, _ in dispatched
                )
            for new_ticket, sub in dispatched:
                if new_ticket.handle is None:
                    # No dispatched device solve (lone request -> solo path,
                    # or a batch that didn't window): its serve must observe
                    # every earlier window's reservations, and there is no
                    # fetch to overlap — drain, then serve now. (Inside a
                    # fused claim this drains the group's earlier views —
                    # one umbrella fetch — before the solo serve.)
                    complete_all()
                    self._complete_window((new_ticket, sub))
                else:
                    if pending:
                        self.pipelined_windows += 1
                    pending.append((new_ticket, sub))
                    # Wake the loop the moment this window's decision pulls
                    # land (every partition's, on the multi-device engine),
                    # so its complete never waits on a cv timeout.
                    for fut in eager_futures(new_ticket.handle):
                        fut.add_done_callback(lambda _f: self._notify())
            # Heads whose pull already landed complete at zero cost, and
            # the depth bound backpressures (blocking complete) when the
            # pipeline is full — counted in DISPATCHES, so a fused batch
            # of K windows occupies one depth slot, not K.
            while pending and head_ready():
                complete_head()
            while pending and inflight_dispatches() >= self._pipeline_depth:
                complete_head()
            if not batch and pending and not self._queue:
                head = pending[0][0]
                if head.handle is None or not eager_futures(head.handle):
                    # No in-flight pull to overlap with (no eager fetch was
                    # started): complete now, blocking fetch and all.
                    complete_head()
                else:
                    # The head's pull is still in flight: sleep until it
                    # lands OR a request shows up. NEVER block in result()
                    # here — requests arriving during the fetch must
                    # dispatch the next window first so their solve
                    # overlaps this fetch (blocking the dispatcher on an
                    # un-ready head serializes the pipeline whenever all
                    # clients cluster into one window cohort).
                    with self._cv:
                        while (
                            not self._queue
                            and not self._stopped
                            and pending
                            and not head_ready()
                        ):
                            self._cv.wait(0.005)

    def _notify(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def _dispatch_batches(self, sub_batches):
        """Dispatch one claim: a single window (the classic path), or a
        FUSED group of K sub-windows solved by one device dispatch
        (extender.predicate_windows_dispatch). Returns [(ticket, batch)]
        in dispatch order — completions stay strictly FIFO."""
        if len(sub_batches) == 1:
            return [(self._dispatch_window(sub_batches[0]), sub_batches[0])]
        from spark_scheduler_tpu.tracing import tracer

        with tracer().span(
            "predicate-window-fused",
            windows=len(sub_batches),
            requests=sum(len(s) for s in sub_batches),
        ):
            tickets = self._extender.predicate_windows_dispatch(
                [[e[0] for e in sub] for sub in sub_batches]
            )
        # Stats AFTER the dispatch landed: a PipelineDrainRequired retry
        # re-enters this method for the same claim and must not count the
        # aborted attempt as a served fused dispatch.
        self.fused_dispatches += 1
        self.max_fused_k = max(self.max_fused_k, len(sub_batches))
        if self._registry is not None:
            self._registry.histogram(
                "foundry.spark.scheduler.predicate.fused.windows"
            ).update(len(sub_batches))
        return list(zip(tickets, sub_batches))

    def _dispatch_window(self, batch):
        from spark_scheduler_tpu.tracing import tracer

        args_list = [e[0] for e in batch]
        if len(batch) == 1 and batch[0][4] is not None:
            # Lone request: its work continues the caller's b3 trace
            # exactly as the pre-batcher serving path did.
            with tracer().attach(batch[0][4]):
                return self._extender.predicate_window_dispatch(args_list)
        # Coalesced window: one solve serves many traces — emit a window
        # span linking every request trace (zipkin span-link style).
        with tracer().span(
            "predicate-window",
            window=len(batch),
            request_traces=[e[4].trace_id for e in batch if e[4] is not None],
        ):
            return self._extender.predicate_window_dispatch(args_list)

    def _finish_entries(self, batch) -> None:
        """Clear completed entries out of the claimed set immediately: a
        request that timed out client-side while its window was in flight
        must not leave its slot in `_claimed` until the next claim's lazy
        rebuild happens to run (on an idle server that could be never)."""
        with self._cv:
            claimed = self._claimed
            for entry in batch:
                try:
                    claimed.remove(entry)
                except ValueError:
                    pass

    def _complete_window(self, pending) -> bool:
        """Returns False when the window failed (entries got the error) —
        the serving loop then drains the rest of the pipeline before
        dispatching anything new."""
        from spark_scheduler_tpu.tracing import tracer

        ticket, batch = pending
        try:
            if len(batch) == 1 and batch[0][4] is not None:
                with tracer().attach(batch[0][4]):
                    results = self._extender.predicate_window_complete(ticket)
            else:
                with tracer().span(
                    "predicate-window-complete", window=len(batch)
                ):
                    results = self._extender.predicate_window_complete(ticket)
        except Exception as exc:  # whole-window failure
            self._fail_batch(batch, exc)
            return False
        self.windows_served += 1
        self.requests_served += len(batch)
        self.max_window_seen = max(self.max_window_seen, len(batch))
        if self._registry is not None:
            self._registry.histogram(
                "foundry.spark.scheduler.predicate.window"
            ).update(len(batch))
        for entry, result in zip(batch, results):
            entry[2] = result
            entry[1].set()
        self._finish_entries(batch)
        return True

    def _fail_batch(self, batch, exc) -> None:
        for entry in batch:
            entry[3] = exc
            entry[1].set()
        self._finish_entries(batch)

    def stats(self) -> dict:
        return {
            "windows_served": self.windows_served,
            "requests_served": self.requests_served,
            "max_window_seen": self.max_window_seen,
            "pipelined_windows": self.pipelined_windows,
            "fuse_windows": self._fuse_windows,
            "fused_dispatches": self.fused_dispatches,
            "max_fused_k": self.max_fused_k,
            "queue_depth": self.queue_depth(),
            "mean_window": (
                round(self.requests_served / self.windows_served, 2)
                if self.windows_served
                else 0.0
            ),
            # (window, queue_after, pending, hold_ms) for recent claims.
            "claim_log_tail": self.claim_log[-32:],
        }


def _build_transport(
    transport: str,
    routes,
    host: str,
    port: int,
    *,
    cert_file,
    key_file,
    client_ca_files,
    request_timeout_s,
    request_log,
    max_body_bytes,
    max_connections,
    telemetry,
    name: str,
    ingest_codec=None,
):
    if transport == "async":
        from spark_scheduler_tpu.server.transport_async import AsyncTransport

        return AsyncTransport(
            routes,
            host,
            port,
            cert_file=cert_file,
            key_file=key_file,
            client_ca_files=client_ca_files,
            request_timeout_s=request_timeout_s,
            request_log=request_log,
            max_body_bytes=max_body_bytes,
            max_connections=max_connections,
            telemetry=telemetry,
            name=name,
            ingest_codec=ingest_codec,
        )
    if transport != "threaded":
        raise ValueError(
            f"unknown server transport {transport!r}; expected one of {TRANSPORTS}"
        )
    return ThreadedTransport(
        routes,
        host,
        port,
        cert_file=cert_file,
        key_file=key_file,
        client_ca_files=client_ca_files,
        request_timeout_s=request_timeout_s,
        request_log=request_log,
        max_body_bytes=max_body_bytes,
        telemetry=telemetry,
        name=name,
    )


class SchedulerHTTPServer:
    def __init__(
        self,
        app,
        registry=None,
        host: str = "127.0.0.1",
        port: int = 8484,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        debug_routes: bool = False,
        request_log: bool = False,
        transport: str | None = None,
        ingest: str | None = None,
        max_body_bytes: int | None = None,
        max_connections: int | None = None,
        shed_queue_depth: int | None = None,
        ha=None,
        fleet=None,
    ):
        from spark_scheduler_tpu.observability import TransportTelemetry

        self.app = app
        self.registry = registry
        self.request_timeout_s = request_timeout_s
        self._request_timeout_s = request_timeout_s  # legacy alias
        self.request_log = request_log
        # /debug/* (trace dump, JAX profiler control) is an explicit opt-in:
        # on the cluster-exposed extender port it would let any peer start
        # profiler writes to server-side paths.
        self.debug_routes = debug_routes
        # HA replica runtime (ha/replica.ReplicaRuntime) when this server
        # is one replica of an elected group: readiness then ALSO requires
        # a serving role (leader/active), GET /debug/ha exposes the role /
        # lease / tailer state, and start()/stop() run the heartbeat.
        self.ha = ha
        # FleetFacade (fleet/facade.py) when this endpoint fronts F
        # per-cluster stacks: GET /debug/fleet exposes router/spillover/
        # aggregate state and predicates accept a ?cluster=N tag (which
        # cluster endpoint kube-scheduler thinks it hit — wrong-cluster
        # calls are forwarded, counted, and byte-identical either way).
        self.fleet = fleet
        self.ready = threading.Event()
        self._shutdown = threading.Event()
        cfg = getattr(app, "config", None)
        # Transport + backpressure knobs resolve explicit args first, then
        # the install config, then defaults — so embedded uses (tests,
        # bench) can A/B without a config object.
        self.transport_name = transport or getattr(
            cfg, "server_transport", "threaded"
        )
        # Ingest lane: native requested + native runtime unavailable =>
        # DEGRADE to the python lane with a startup RuntimeWarning (and a
        # telemetry flag) — never an exception; a toolchain-less host still
        # serves, just without the zero-copy path.
        self.ingest_name = ingest or getattr(cfg, "server_ingest", "python")
        if self.ingest_name not in INGESTS:
            raise ValueError(
                f"unknown server ingest {self.ingest_name!r}; "
                f"expected one of {INGESTS}"
            )
        self.ingest_codec = None
        self._ingest_telemetry = None
        if self.ingest_name == "native":
            from spark_scheduler_tpu.server.ingest import try_native_codec

            self.ingest_codec = try_native_codec()
            if self.ingest_codec is None:
                import warnings

                from spark_scheduler_tpu import native as _native

                from spark_scheduler_tpu.server.ingest import IngestTelemetry

                warnings.warn(
                    "server.ingest: native requested but the native runtime "
                    f"is unavailable ({_native.load_error() or 'not built'}); "
                    "degrading to the python ingest lane",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self.ingest_name = "python"
                self._ingest_telemetry = IngestTelemetry("python")
                self._ingest_telemetry.degraded = True
        self.max_body_bytes = (
            max_body_bytes
            if max_body_bytes is not None
            else getattr(cfg, "max_body_bytes", 16 * 1024 * 1024)
        )
        self.max_connections = (
            max_connections
            if max_connections is not None
            else getattr(cfg, "max_connections", 512)
        )
        self.shed_queue_depth = (
            shed_queue_depth
            if shed_queue_depth is not None
            else getattr(cfg, "shed_queue_depth", 256)
        )
        # Concurrent predicates coalesce into windowed batch solves; the
        # batcher's dispatcher thread is the serialization point for mutable
        # scheduling state (SURVEY.md §7 "Mutable-state races").
        self.batcher = PredicateBatcher(
            app.extender,
            max_window=getattr(cfg, "predicate_max_window", 32),
            hold_ms=getattr(cfg, "predicate_hold_ms", 25.0),
            registry=registry,
            # With a device pool, keep at least pool-size windows in
            # flight so every slot can hold work.
            pipeline_depth=max(3, getattr(app.solver, "pool_size", 1)),
            # Fused multi-window dispatch (`solver.fuse-windows` /
            # --fuse-windows): deep backlogs ride one device round trip
            # per K windows instead of one each.
            fuse_windows=getattr(cfg, "solver_fuse_windows", 1),
        )
        self.telemetry = TransportTelemetry(
            self.transport_name, ingest=self.ingest_name
        )
        self.routes = SchedulerRoutes(self)
        self._transport = _build_transport(
            self.transport_name,
            self.routes,
            host,
            port,
            cert_file=cert_file,
            key_file=key_file,
            client_ca_files=client_ca_files,
            request_timeout_s=request_timeout_s,
            request_log=request_log,
            max_body_bytes=self.max_body_bytes,
            max_connections=self.max_connections,
            telemetry=self.telemetry,
            name=f"scheduler-http-{self.transport_name}",
            ingest_codec=self.ingest_codec,
        )
        self.tls = self._transport.tls

    # Hooks the route table calls back into -------------------------------

    def transport_stats(self) -> dict:
        return self.telemetry.stats()

    def ingest_stats(self) -> dict:
        """`foundry.spark.scheduler.server.ingest.*` snapshot: the codec's
        live counters on the native lane, a degraded/zeroed record when
        native was requested but unavailable, a plain lane marker on the
        python lane."""
        if self.ingest_codec is not None:
            return self.ingest_codec.stats()
        if self._ingest_telemetry is not None:
            return self._ingest_telemetry.stats()
        return {"ingest": self.ingest_name, "degraded": 0}

    def on_queue_shed(self) -> None:
        self.telemetry.on_queue_shed()

    # ----------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._transport.port

    def set_request_log(self, enabled: bool) -> None:
        """Toggle the per-request access log on the running transport (the
        runtime-config reload slot; also what the tests flip)."""
        self.request_log = enabled
        self._transport.set_request_log(enabled)

    def start(self) -> None:
        self.app.start_background()
        if self.ha is not None:
            self.ha.start()
        self._transport.start()
        # Ready only once cluster state exists; pre-seeded backends (tests,
        # embedded use) are ready at once, otherwise the first successful
        # PUT /state/nodes — or watch-ingestion cache sync
        # (WaitForCacheSync, cmd/server.go:140-147) — flips it.
        if self.app.backend.list_nodes():
            self.ready.set()
        elif getattr(self.app, "ingestion", None) is not None:
            def _ready_on_sync():
                # Wait as long as it takes (WaitForCacheSync blocks until
                # sync or shutdown) — a slow apiserver must not leave the
                # server permanently not-ready.
                while not self.ready.is_set():
                    if self.app.ingestion.wait_synced(timeout=30.0):
                        self.ready.set()
                        return
                    if self._shutdown.is_set():
                        return

            threading.Thread(
                target=_ready_on_sync, daemon=True, name="ingestion-sync-ready"
            ).start()

    def stop(self) -> None:
        self._shutdown.set()
        self.ready.clear()
        if self.ha is not None:
            # Release the lease FIRST: a clean shutdown lets the standby
            # promote immediately instead of waiting out the TTL.
            self.ha.stop()
        # Batcher first: pending entries fail fast (and their event-loop
        # callbacks flush) while the transport is still able to write the
        # error responses.
        self.batcher.stop()
        self._transport.stop()
        self.app.stop()

    def join(self) -> None:
        """Block until the serving thread exits (after start())."""
        self._transport.join()

    def serve_forever(self) -> None:
        self.start()
        try:
            self.join()
        except KeyboardInterrupt:
            self.stop()


class ConversionWebhookServer:
    """Standalone conversion-webhook service (the reference ships this as a
    second binary: spark-scheduler-conversion-webhook/cmd/server.go:39-54).
    Serves only POST /convert + liveness; no scheduler state."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8485,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        request_log: bool = False,
        max_body_bytes: int = 16 * 1024 * 1024,
    ):
        self._transport = ThreadedTransport(
            ConversionRoutes(),
            host,
            port,
            cert_file=cert_file,
            key_file=key_file,
            client_ca_files=client_ca_files,
            request_timeout_s=request_timeout_s,
            request_log=request_log,
            max_body_bytes=max_body_bytes,
            name="conversion-http",
        )
        self.tls = self._transport.tls

    @property
    def port(self) -> int:
        return self._transport.port

    def start(self) -> None:
        self._transport.start()

    def stop(self) -> None:
        self._transport.stop()

    def serve_forever(self) -> None:
        self.start()
        try:
            self._transport.join()
        except KeyboardInterrupt:
            self.stop()
