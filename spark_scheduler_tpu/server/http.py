r"""HTTP front-end — the witchcraft-server slot (cmd/server.go, cmd/endpoints.go).

Routes (all JSON):

  POST /predicates            kube-scheduler extender filter call
                              (ExtenderArgs -> ExtenderFilterResult,
                              cmd/endpoints.go:28-42)
  POST /convert               CRD version-conversion webhook
                              (ConversionReview, SURVEY.md L9; also served
                              standalone by ConversionWebhookServer)
  GET  /status/liveness       200 when the process is up
  GET  /status/readiness      200 once cluster state has been synced
                              (at least one node known to the backend)
  GET  /metrics               metric-registry snapshot: JSON by default,
                              Prometheus text exposition when the Accept
                              header prefers text/plain (or
                              ?format=prometheus) — the pull surface for
                              scrape stacks
  GET  /debug/decisions       flight-recorder query (?app=&verdict=&role=
                              &limit=), gated on debug-routes
  GET  /debug/state           point-in-time scheduler state (hard/soft
                              reservations, FIFO queue, unschedulable set,
                              node fleet), gated on debug-routes
  PUT  /state/nodes           upsert a k8s Node object   \  informer-watch
  PUT  /state/pods            upsert a k8s Pod object     } substitute: the
  DELETE /state/pods/{ns}/{n} remove a pod               /  state-sync API

The reference learns cluster state through apiserver watch streams
(cmd/server.go:111-147); in environments without one, the state-sync routes
carry the same information. Threaded stdlib server: the predicate handler is
serialized by the extender's internal ordering, matching the reference's
single Predicate goroutine assumption (SURVEY.md §0).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from spark_scheduler_tpu.core.extender import ExtenderArgs
from spark_scheduler_tpu.server.conversion import convert_review
from spark_scheduler_tpu.server.kube_io import (
    extender_args_from_k8s,
    filter_result_to_k8s,
    node_from_k8s,
    pod_from_k8s,
)


class PredicateBatcher:
    """Coalesces concurrent POST /predicates calls into windowed
    `extender.predicate_batch` solves (VERDICT r2 #1).

    A single dispatcher thread drains the queue: whatever arrived while the
    previous window was being served forms the next window, plus — during
    busy periods only — a short accumulation hold (`hold_ms`) so clients
    answering the previous window can rejoin and windows stay near the
    concurrency level. An idle server serves a lone request immediately
    (window of 1 = the solo path); a loaded server amortizes one device
    solve over every queued request. The dispatcher thread is ALSO the
    serialization point for mutable scheduling state, replacing the
    per-request lock (SURVEY.md §7 "Mutable-state races")."""

    def __init__(
        self, extender, max_window: int = 32, hold_ms: float = 25.0,
        registry=None, pipeline_depth: int = 3,
    ):
        self._extender = extender
        self._max_window = max_window
        # How many dispatched windows may be awaiting their decision pull
        # at once. Concurrent device_get RPCs overlap (the fetch pool), so
        # depth N divides the per-window round-trip cost by up to N.
        self._pipeline_depth = max(1, pipeline_depth)
        # Window-size histogram + wait time in the tagged registry (the
        # reference's metric discipline for every serving subsystem,
        # metrics/metrics.go:29-76).
        self._registry = registry
        # Adaptive accumulation: when the PREVIOUS window was coalesced
        # (>1 request — i.e. we are in a busy period), hold up to hold_ms
        # for stragglers before solving, so clients answering the previous
        # window have time to submit their next request and windows stay
        # near the concurrency level instead of oscillating small. A lone
        # request on an idle server is never held.
        self._hold_s = hold_ms / 1e3
        self._last_window = 1
        # Whether the previous window dispatched a DEVICE solve. The hold
        # exists to amortize one device program over more requests; an
        # executor-only window is pure host work and holding for
        # stragglers just adds their wait to everyone's latency.
        self._last_had_solve = False
        # The hold engages only while a busy period is LIVE: within this
        # TTL of the previous coalesced window. A lone request on a
        # since-idle server is served immediately.
        self._busy_ttl_s = 2.0
        self._busy_until = 0.0
        self._cv = threading.Condition()
        self._queue: list[list] = []  # [args, event, result, exception]
        # Entries the dispatcher has claimed whose events may not be set
        # yet — what stop() fails when the dispatcher thread is stalled in
        # a blocking fetch against a dead tunnel (join times out but
        # in-flight HTTP handlers must not hang until request timeout).
        self._claimed: list[list] = []
        self._stopped = False
        # Serving stats (surfaced at GET /metrics).
        self.windows_served = 0
        self.requests_served = 0
        self.max_window_seen = 0
        # Debug log of claim decisions:
        # (window, queue_after, pending, hold_ms). Cheap appends; recording
        # stops at the 4096-entry bound; stats() exposes the tail for
        # serving-dynamics forensics.
        self.claim_log: list[tuple] = []
        # Windows dispatched while another window was still in flight (the
        # dispatch-before-fetch overlap actually engaging).
        self.pipelined_windows = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="predicate-batcher"
        )
        self._thread.start()

    def submit(self, args, timeout: float | None = None):
        from spark_scheduler_tpu.tracing import tracer

        # Carry the handler thread's trace context to the dispatcher.
        entry = [args, threading.Event(), None, None, tracer().current()]
        with self._cv:
            if self._stopped:
                raise RuntimeError("scheduler is shutting down")
            self._queue.append(entry)
            self._cv.notify()
        if not entry[1].wait(timeout):
            # Shed the abandoned request: if the dispatcher has not claimed
            # it yet, remove it so no window slot is burned solving for a
            # client that already got an error (overload would otherwise
            # spiral: dead entries crowd out live ones).
            with self._cv:
                try:
                    self._queue.remove(entry)
                except ValueError:
                    pass  # already claimed — the solve proceeds harmlessly
            raise TimeoutError("predicate window timed out")
        if entry[3] is not None:
            raise entry[3]
        return entry[2]

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        # Fail every claimed/queued entry whose event is still unset so
        # in-flight handlers return instead of hanging until their own
        # request timeout — covers a dispatcher STALLED in a decision pull
        # against a dead tunnel (join timed out) and one that DIED with a
        # batch's events unset. No-op on a clean exit (everything is set);
        # a late set() by a stalled thread is harmless.
        err = RuntimeError("scheduler is shutting down")
        with self._cv:
            leftovers = self._claimed + self._queue
            self._queue.clear()
        for entry in leftovers:
            if not entry[1].is_set():
                entry[3] = err
                entry[1].set()

    def _run(self) -> None:
        """PIPELINED serving loop: dispatch the next window (host build +
        async device dispatch) while up to `pipeline_depth` earlier windows
        are still awaiting their decision pulls. Each window's pull starts
        eagerly on the solver's fetch pool at dispatch, and concurrent
        pulls overlap on the wire, so steady-state cycle time approaches
        max(host work, RTT / depth) instead of host + RTT. Windows complete
        strictly in dispatch order. Decisions are unchanged: the solver
        threads the committed base availability device-side across
        in-flight windows (build_tensors_pipelined), an app whose admission
        is still in flight is deferred to its own window's post-apply solo
        loop (extender in-flight set), and a ticket with no dispatched
        solve (the solo path) drains the pipeline before serving."""
        import time as _time
        from collections import deque

        from spark_scheduler_tpu.core.solver import PipelineDrainRequired

        pending: deque = deque()  # (ticket, batch) in dispatch order

        def complete_head():
            ok = self._complete_window(pending.popleft())
            if not ok and pending:
                # A failed fetch dropped the solver's pipelined state; the
                # remaining in-flight windows' gangs exist only in their
                # (still valid) device decisions. Apply them ALL before any
                # new dispatch — a fresh full upload from the host view
                # would otherwise lack their capacity debits and the next
                # window could double-book.
                while pending:
                    self._complete_window(pending.popleft())

        def complete_all():
            while pending:
                complete_head()

        def head_ready() -> bool:
            t = pending[0][0]
            return (
                t.handle is not None
                and t.handle.blob_future is not None
                and t.handle.blob_future.done()
            )

        while True:
            with self._cv:
                while not self._queue and not self._stopped and not pending:
                    self._cv.wait()
                busy = (
                    self._last_window > 1
                    and _time.monotonic() < self._busy_until
                )
                if (
                    not self._stopped
                    and self._queue
                    and not pending
                    and self._hold_s > 0
                    and busy
                    and self._last_had_solve
                ):
                    # Accumulation hold, only when nothing is in flight — a
                    # pending window's fetch IS the accumulation period
                    # otherwise: requests arriving during it dispatch as
                    # the next window and their solve overlaps the fetch
                    # (measured: under a GIL-bound lockstep cohort this
                    # staggered-subgroup pipelining beats holding for the
                    # full cohort, whose resubmission takes tens of ms —
                    # holds serialize RTTs that the overlap hides).
                    # Deliberately NO stopped-growing early exit: arrival
                    # gaps of several ms mid-resubmission made it claim
                    # straggler subgroups that then ratcheted the window
                    # size down. Cost: after a cohort SHRINKS, the first
                    # window waits the full hold once; the target then
                    # adapts to the new cohort size.
                    hold_t0 = _time.monotonic()
                    target = min(self._last_window, self._max_window)
                    deadline = hold_t0 + self._hold_s
                    while (
                        len(self._queue) < target and not self._stopped
                    ):
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    hold_ms = (_time.monotonic() - hold_t0) * 1e3
                else:
                    hold_ms = 0.0
                if self._stopped:
                    err = RuntimeError("scheduler is shutting down")
                    for _, entries in pending:
                        for entry in entries:
                            entry[3] = err
                            entry[1].set()
                    pending.clear()
                    for entry in self._queue:
                        entry[3] = err
                        entry[1].set()
                    self._queue.clear()
                    return
                batch = self._queue[: self._max_window]
                del self._queue[: self._max_window]
                if batch and len(self.claim_log) < 4096:
                    self.claim_log.append((
                        len(batch), len(self._queue), len(pending),
                        round(hold_ms, 1),
                    ))
                self._claimed = [
                    e for e in self._claimed if not e[1].is_set()
                ]
                self._claimed.extend(batch)
                if batch:
                    self._last_window = len(batch)
                    if len(batch) > 1:
                        self._busy_until = (
                            _time.monotonic() + self._busy_ttl_s
                        )
            new_ticket = None
            if batch:
                try:
                    new_ticket = self._dispatch_window(batch)
                except PipelineDrainRequired:
                    # Topology changed under in-flight windows: apply them
                    # first, then the fresh full upload is safe.
                    complete_all()
                    try:
                        new_ticket = self._dispatch_window(batch)
                    except Exception as exc:
                        self._fail_batch(batch, exc)
                except Exception as exc:
                    self._fail_batch(batch, exc)
            if new_ticket is not None:
                self._last_had_solve = new_ticket.handle is not None
                if new_ticket.handle is None:
                    # No dispatched device solve (lone request -> solo path,
                    # or a batch that didn't window): its serve must observe
                    # every earlier window's reservations, and there is no
                    # fetch to overlap — drain, then serve now.
                    complete_all()
                    self._complete_window((new_ticket, batch))
                else:
                    if pending:
                        self.pipelined_windows += 1
                    pending.append((new_ticket, batch))
                    # Wake the loop the moment this window's decision pull
                    # lands, so its complete never waits on a cv timeout.
                    fut = new_ticket.handle.blob_future
                    if fut is not None:
                        fut.add_done_callback(lambda _f: self._notify())
            # Heads whose pull already landed complete at zero cost, and
            # the depth bound backpressures (blocking complete) when the
            # pipeline is full.
            while pending and head_ready():
                complete_head()
            if len(pending) >= self._pipeline_depth:
                complete_head()
            if not batch and pending and not self._queue:
                head = pending[0][0]
                if head.handle is None or head.handle.blob_future is None:
                    # No in-flight pull to overlap with (no eager fetch was
                    # started): complete now, blocking fetch and all.
                    complete_head()
                else:
                    # The head's pull is still in flight: sleep until it
                    # lands OR a request shows up. NEVER block in result()
                    # here — requests arriving during the fetch must
                    # dispatch the next window first so their solve
                    # overlaps this fetch (blocking the dispatcher on an
                    # un-ready head serializes the pipeline whenever all
                    # clients cluster into one window cohort).
                    with self._cv:
                        while (
                            not self._queue
                            and not self._stopped
                            and pending
                            and not head_ready()
                        ):
                            self._cv.wait(0.005)

    def _notify(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def _dispatch_window(self, batch):
        from spark_scheduler_tpu.tracing import tracer

        args_list = [e[0] for e in batch]
        if len(batch) == 1 and batch[0][4] is not None:
            # Lone request: its work continues the caller's b3 trace
            # exactly as the pre-batcher serving path did.
            with tracer().attach(batch[0][4]):
                return self._extender.predicate_window_dispatch(args_list)
        # Coalesced window: one solve serves many traces — emit a window
        # span linking every request trace (zipkin span-link style).
        with tracer().span(
            "predicate-window",
            window=len(batch),
            request_traces=[e[4].trace_id for e in batch if e[4] is not None],
        ):
            return self._extender.predicate_window_dispatch(args_list)

    def _complete_window(self, pending) -> bool:
        """Returns False when the window failed (entries got the error) —
        the serving loop then drains the rest of the pipeline before
        dispatching anything new."""
        from spark_scheduler_tpu.tracing import tracer

        ticket, batch = pending
        try:
            if len(batch) == 1 and batch[0][4] is not None:
                with tracer().attach(batch[0][4]):
                    results = self._extender.predicate_window_complete(ticket)
            else:
                with tracer().span(
                    "predicate-window-complete", window=len(batch)
                ):
                    results = self._extender.predicate_window_complete(ticket)
        except Exception as exc:  # whole-window failure
            self._fail_batch(batch, exc)
            return False
        self.windows_served += 1
        self.requests_served += len(batch)
        self.max_window_seen = max(self.max_window_seen, len(batch))
        if self._registry is not None:
            self._registry.histogram(
                "foundry.spark.scheduler.predicate.window"
            ).update(len(batch))
        for entry, result in zip(batch, results):
            entry[2] = result
            entry[1].set()
        return True

    def _fail_batch(self, batch, exc) -> None:
        for entry in batch:
            entry[3] = exc
            entry[1].set()

    def stats(self) -> dict:
        return {
            "windows_served": self.windows_served,
            "requests_served": self.requests_served,
            "max_window_seen": self.max_window_seen,
            "pipelined_windows": self.pipelined_windows,
            "mean_window": (
                round(self.requests_served / self.windows_served, 2)
                if self.windows_served
                else 0.0
            ),
            # (window, queue_after, pending, hold_ms) for recent claims.
            "claim_log_tail": self.claim_log[-32:],
        }


class UnframeableBody(ValueError):
    """The request body's length cannot be determined safely (client
    framing error — mapped to a 400, and the connection is closed)."""


class UnsupportedTransferEncoding(UnframeableBody):
    """Request body uses Transfer-Encoding (no chunked decoder here)."""


class _JSONHandler(BaseHTTPRequestHandler):
    """Shared JSON plumbing + the routes both servers serve
    (liveness, POST /convert)."""

    # Keep-alive: without this the stdlib default (HTTP/1.0) closes the
    # connection after EVERY response, so each request pays TCP connect +
    # a fresh handler thread — measured ~6 ms/call on loopback, dwarfing
    # the actual handler work. Every _write sets Content-Length, which
    # HTTP/1.1 persistent connections require.
    protocol_version = "HTTP/1.1"

    # Per-request structured access log (the witchcraft req2log slot,
    # middleware/route.go:28-48). Opt-in per server via config
    # `request-log` — flipped onto the Handler subclass at construction.
    request_log = False

    def log_message(self, *args):  # stdlib's unstructured stderr lines: quiet
        pass

    def log_request(self, code="-", size="-"):
        # Called by send_response mid-request; capture the status and defer
        # the log line to handle_one_request so it carries the FULL
        # duration (handler + response write).
        self._log_status = code

    def _content_length(self) -> int:
        """Validated Content-Length. Raises UnframeableBody — after flagging
        the connection for drain+close — on negative or non-numeric values
        (int() would raise / read(-1) would block to EOF) and on duplicate
        headers with differing values (RFC 7230 3.3.2: reading only the
        first would leave the rest of the body to desync the next keep-alive
        request — request smuggling)."""
        raws = self.headers.get_all("Content-Length") or []
        vals = {r.strip() for r in raws}
        length = None
        if len(vals) <= 1:
            raw = next(iter(vals), None)
            if raw is None:
                return 0
            # RFC 7230: 1*DIGIT only. Bare int() also accepts '1_6', '+16'
            # and Unicode digits — forms an RFC-strict proxy in front of us
            # would frame differently (the smuggling vector again).
            if raw.isascii() and raw.isdigit():
                length = int(raw)
            else:
                length = None
        if length is None or length < 0:
            self.close_connection = True
            self._drain_on_close = True
            raise UnframeableBody("invalid Content-Length")
        return length

    @staticmethod
    def _error_code(exc: Exception) -> int:
        # Client framing errors are 4xx, not server failures (a 500 would
        # count against server error budgets and invite pointless retries).
        return 400 if isinstance(exc, UnframeableBody) else 500

    def _consume_body_for_response(self) -> None:
        # Keep-alive discipline: a handler that answers without reading the
        # request body (404s, gated debug routes) would leave those bytes
        # in rfile and desync the NEXT request on this persistent
        # connection — drain them first.
        if not getattr(self, "_body_consumed", False):
            if self.headers.get("Transfer-Encoding"):
                # Unframeable (and Content-Length may lie alongside it) —
                # don't block in read(); close after this response instead.
                self.close_connection = True
                self._drain_on_close = True
            else:
                try:
                    length = self._content_length()
                except UnframeableBody:
                    length = 0  # flagged: drained + closed after response
                if length:
                    self.rfile.read(length)
            self._body_consumed = True

    def _write_raw(self, code: int, body: bytes, content_type: str) -> None:
        self._consume_body_for_response()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            # Advertise the close so a pipelining client doesn't race its
            # next request onto a socket we're about to shut.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _write(self, code: int, payload) -> None:
        self._write_raw(code, json.dumps(payload).encode(), "application/json")

    def _write_text(self, code: int, text: str, content_type: str) -> None:
        self._write_raw(code, text.encode(), content_type)

    def parse_request(self):
        # Request-log clock: started AFTER the request line arrived, so a
        # keep-alive connection's idle wait for the client's next request
        # never counts into the logged duration.
        self._req_start = time.monotonic()
        return super().parse_request()

    def handle_one_request(self):
        self._body_consumed = False  # per-request, before any handler runs
        self._drain_on_close = False
        self._log_status = None
        self._req_start = None
        super().handle_one_request()
        start = self._req_start
        if self.request_log and self._log_status is not None and start is not None:
            from spark_scheduler_tpu.tracing import svc1log

            headers = getattr(self, "headers", None)
            try:
                status = int(self._log_status)
            except (TypeError, ValueError):  # send_error's "-" placeholder
                status = 0
            svc1log().request(
                getattr(self, "command", "-") or "-",
                getattr(self, "path", "-") or "-",
                status,
                int((time.monotonic() - start) * 1e6),
                protocol=self.protocol_version,
                trace_id=(
                    headers.get("X-B3-TraceId") or headers.get("x-b3-traceid")
                )
                if headers
                else None,
            )
        # An unframeable body (Transfer-Encoding, garbage Content-Length)
        # was answered without being read; close the connection so the
        # unread bytes can never desync a subsequent request on the
        # persistent socket.
        if self._drain_on_close:
            self.close_connection = True
            # Drain the unread body so close() sends FIN, not RST (unread
            # receive data at close resets the connection on Linux and can
            # destroy the in-flight response). The body usually rode in
            # with the headers and sits read-ahead in rfile's user-space
            # buffer — invisible to connection.recv — so consume that
            # first, non-blocking.
            try:
                self.connection.setblocking(False)
                while self.rfile.read1(65536):
                    pass
            except (OSError, ValueError):
                pass
            # Then a short timed kernel drain for bytes still in flight,
            # bounded in bytes and wall time so a client streaming forever
            # can't pin the handler thread.
            try:
                self.connection.settimeout(0.05)
                budget = 1 << 18
                deadline = time.monotonic() + 1.0
                while budget > 0 and time.monotonic() < deadline:
                    got = self.connection.recv(65536)
                    if not got:
                        break
                    budget -= len(got)
            except OSError:
                pass

    def _body(self):
        if self.headers.get("Transfer-Encoding"):
            # No chunked decoder here — without this, a chunked POST would
            # parse as an empty body and be answered with a confidently
            # wrong success. Callers turn this into an error response;
            # the connection closes after it (advertised by _write).
            self.close_connection = True
            self._drain_on_close = True
            self._body_consumed = True
            raise UnsupportedTransferEncoding(
                "Transfer-Encoding not supported; send Content-Length"
            )
        try:
            length = self._content_length()
        except UnframeableBody:
            self._body_consumed = True  # never read; drained at close
            raise
        self._body_consumed = True
        return json.loads(self.rfile.read(length) or b"{}")

    def _handle_liveness(self) -> None:
        self._write(200, {"status": "up"})

    def _handle_convert(self) -> None:
        try:
            review = self._body()
        except Exception as exc:
            self._write(400, {"error": str(exc)})
            return
        self._write(200, convert_review(review))


class _Server(ThreadingHTTPServer):
    # Default listen backlog (5) resets connections under a concurrent
    # client burst — exactly the load the predicate batcher exists for.
    request_queue_size = 128


def _run_threaded(server: ThreadingHTTPServer, name: str) -> threading.Thread:
    thread = threading.Thread(target=server.serve_forever, daemon=True, name=name)
    thread.start()
    return thread


def _maybe_wrap_tls(
    server: ThreadingHTTPServer,
    cert_file: str | None,
    key_file: str | None,
    client_ca_files=None,
    handshake_timeout_s: float = 30.0,
) -> bool:
    """Serve HTTPS when a cert/key pair is configured — the witchcraft
    server slot (reference config server.cert-file/key-file/client-ca-files,
    examples/extender.yml:75-80). `client_ca_files` (str or list) requires
    client certificates signed by ANY of the given CAs (mTLS). Returns True
    if TLS was enabled.

    The TLS handshake runs PER CONNECTION in the worker thread (via a
    finish_request override), never in the accept loop: a client that
    stalls mid-handshake ties up one bounded-timeout worker, not the whole
    server."""
    if not cert_file:
        return False
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file or cert_file)
    if isinstance(client_ca_files, str):
        client_ca_files = [client_ca_files]
    for ca in client_ca_files or []:
        ctx.load_verify_locations(ca)
    if client_ca_files:
        ctx.verify_mode = ssl.CERT_REQUIRED

    orig_finish_request = server.finish_request

    def finish_request(request, client_address):
        # ThreadingMixIn calls finish_request from the per-connection worker
        # thread; the handshake happens here under a timeout.
        try:
            request.settimeout(handshake_timeout_s)
            tls_request = ctx.wrap_socket(request, server_side=True)
        except (OSError, ssl.SSLError):
            try:
                request.close()
            except OSError:
                pass
            return
        orig_finish_request(tls_request, client_address)

    server.finish_request = finish_request
    return True


class SchedulerHTTPServer:
    def __init__(
        self,
        app,
        registry=None,
        host: str = "127.0.0.1",
        port: int = 8484,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        debug_routes: bool = False,
        request_log: bool = False,
    ):
        self.app = app
        self.registry = registry
        self._request_timeout_s = request_timeout_s
        self.request_log = request_log
        # /debug/* (trace dump, JAX profiler control) is an explicit opt-in:
        # on the cluster-exposed extender port it would let any peer start
        # profiler writes to server-side paths.
        self.debug_routes = debug_routes
        self.ready = threading.Event()
        self._shutdown = threading.Event()
        # Concurrent predicates coalesce into windowed batch solves; the
        # batcher's dispatcher thread is the serialization point for mutable
        # scheduling state (SURVEY.md §7 "Mutable-state races").
        cfg = getattr(app, "config", None)
        self.batcher = PredicateBatcher(
            app.extender,
            max_window=getattr(cfg, "predicate_max_window", 32),
            hold_ms=getattr(cfg, "predicate_hold_ms", 25.0),
            registry=registry,
        )
        outer = self

        class Handler(_JSONHandler):
            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                path, query = parsed.path, parse_qs(parsed.query)
                if path == "/status/liveness":
                    self._handle_liveness()
                elif path == "/status/readiness":
                    code = 200 if outer.ready.is_set() else 503
                    self._write(code, {"ready": outer.ready.is_set()})
                elif path == "/metrics":
                    # Compile gauges are pull-synced: the jax.monitoring
                    # listener feeds process totals, the scrape publishes.
                    telemetry = getattr(outer.app.solver, "telemetry", None)
                    if telemetry is not None:
                        telemetry.sync_compile_gauges()
                    snap = outer.registry.snapshot() if outer.registry else {}
                    fmt = (query.get("format") or [""])[0]
                    accept = self.headers.get("Accept", "") or ""
                    from spark_scheduler_tpu.observability import (
                        prefers_prometheus,
                        render_prometheus,
                    )

                    if fmt == "prometheus" or (
                        fmt != "json" and prefers_prometheus(accept)
                    ):
                        # Prometheus text exposition: the pull surface for
                        # scrape stacks (a Prometheus scraper's Accept
                        # header selects it by q-value preference;
                        # `?format=` forces either way).

                        batcher = {
                            f"foundry.spark.scheduler.predicate.batcher.{k}": v
                            for k, v in outer.batcher.stats().items()
                            if isinstance(v, (int, float))
                        }
                        self._write_text(
                            200,
                            render_prometheus(snap, extra_gauges=batcher),
                            "text/plain; version=0.0.4",
                        )
                    else:
                        snap["predicate_batcher"] = outer.batcher.stats()
                        self._write(200, snap)
                elif path == "/debug/traces" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import tracer

                    self._write(200, {"spans": tracer().finished_spans()})
                elif path == "/debug/decisions" and outer.debug_routes:
                    recorder = getattr(outer.app, "recorder", None)
                    if recorder is None:
                        self._write(
                            404, {"error": "flight recorder disabled"}
                        )
                        return

                    def q(name):
                        vals = query.get(name)
                        return vals[0] if vals else None

                    try:
                        limit = int(q("limit") or 100)
                    except ValueError:
                        self._write(400, {"error": "bad limit"})
                        return
                    self._write(
                        200,
                        {
                            "decisions": recorder.query(
                                app=q("app"),
                                verdict=q("verdict"),
                                role=q("role"),
                                namespace=q("namespace"),
                                limit=limit,
                            ),
                            "recorder": recorder.stats(),
                        },
                    )
                elif path == "/debug/state" and outer.debug_routes:
                    from spark_scheduler_tpu.observability import (
                        debug_state_snapshot,
                    )

                    self._write(200, debug_state_snapshot(outer.app))
                else:
                    self._write(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/predicates":
                    from spark_scheduler_tpu.tracing import (
                        pod_safe_params,
                        svc1log,
                        tracer,
                    )

                    try:
                        pod, node_names = extender_args_from_k8s(self._body())
                    except Exception as exc:
                        self._write(self._error_code(exc), {"Error": str(exc)})
                        return
                    # Root span continues the caller's b3 trace context
                    # (the witchcraft tracing middleware slot).
                    with tracer().root_from_headers(
                        self.headers, "predicate", pod=f"{pod.namespace}/{pod.name}"
                    ) as root:
                        try:
                            result = outer.batcher.submit(
                                ExtenderArgs(pod=pod, node_names=node_names),
                                timeout=outer._request_timeout_s,
                            )
                        except Exception as exc:
                            # Internal errors ride the protocol's Error
                            # channel (ExtenderFilterResult.Error) so
                            # kube-scheduler gets a well-formed response
                            # instead of a dropped connection.
                            root.tag("outcome", "failure-internal")
                            svc1log().error(
                                "predicate failed",
                                error=repr(exc),
                                **pod_safe_params(pod),
                            )
                            self._write(
                                200,
                                {"NodeNames": [], "FailedNodes": {}, "Error": str(exc)},
                            )
                            return
                        root.tag("outcome", result.outcome)
                        svc1log().info(
                            "predicate",
                            outcome=result.outcome,
                            nodes=list(result.node_names),
                            **pod_safe_params(pod),
                        )
                    self._write(200, filter_result_to_k8s(result))
                elif self.path == "/convert":
                    self._handle_convert()
                elif self.path == "/debug/profile/start" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import start_jax_profile

                    try:
                        body = self._body()
                    except UnframeableBody as exc:
                        # The body (with its would-be "dir") was never
                        # read — reject rather than silently profiling
                        # into the default dir.
                        self._write(400, {"error": str(exc)})
                        return
                    except Exception:
                        body = {}  # empty/garbage body: defaults are fine
                    if not isinstance(body, dict):
                        body = {}
                    log_dir = body.get("dir") or "/tmp/spark-scheduler-jax-trace"
                    try:
                        started = start_jax_profile(log_dir)
                    except Exception as exc:  # unwritable dir etc.
                        self._write(500, {"profiling": False, "error": str(exc)})
                        return
                    self._write(
                        200 if started else 409,
                        {"profiling": started, "dir": log_dir},
                    )
                elif self.path == "/debug/profile/stop" and outer.debug_routes:
                    from spark_scheduler_tpu.tracing import stop_jax_profile

                    try:
                        out_dir = stop_jax_profile()
                    except Exception as exc:
                        self._write(500, {"profiling": False, "error": str(exc)})
                        return
                    self._write(
                        200 if out_dir else 409,
                        {"profiling": False, "dir": out_dir},
                    )
                else:
                    self._write(404, {"error": "not found"})

            def do_PUT(self):
                try:
                    if self.path == "/state/nodes":
                        node = node_from_k8s(self._body())
                        existing = outer.app.backend.get_node(node.name)
                        if existing is None:
                            outer.app.backend.add_node(node)
                        else:
                            outer.app.backend.update("nodes", node)
                        outer.ready.set()  # first synced node => ready
                        self._write(200, {"applied": node.name})
                    elif self.path == "/state/pods":
                        pod = pod_from_k8s(self._body())
                        if outer.app.backend.get("pods", pod.namespace, pod.name) is None:
                            outer.app.backend.add_pod(pod)
                        else:
                            outer.app.backend.update_pod(pod)
                        self._write(200, {"applied": pod.name})
                    else:
                        self._write(404, {"error": "not found"})
                except Exception as exc:
                    self._write(self._error_code(exc), {"error": str(exc)})

            def do_DELETE(self):
                try:
                    parts = self.path.strip("/").split("/")
                    if len(parts) == 4 and parts[:2] == ["state", "pods"]:
                        ns, name = parts[2], parts[3]
                        pod = outer.app.backend.get("pods", ns, name)
                        if pod is None:
                            self._write(404, {"error": "pod not found"})
                        else:
                            outer.app.backend.delete_pod(pod)
                            self._write(200, {"deleted": name})
                    else:
                        self._write(404, {"error": "not found"})
                except Exception as exc:  # e.g. concurrent-delete race
                    self._write(500, {"error": str(exc)})

        # Socket read timeout per connection: a stalled client cannot pin a
        # handler thread forever (the extender protocol budget is 30 s,
        # examples/extender.yml:59).
        Handler.timeout = request_timeout_s
        Handler.request_log = request_log
        self._server = _Server((host, port), Handler)
        self.tls = _maybe_wrap_tls(
            self._server, cert_file, key_file, client_ca_files,
            handshake_timeout_s=request_timeout_s,
        )
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self.app.start_background()
        self._thread = _run_threaded(self._server, "scheduler-http")
        # Ready only once cluster state exists; pre-seeded backends (tests,
        # embedded use) are ready at once, otherwise the first successful
        # PUT /state/nodes — or watch-ingestion cache sync
        # (WaitForCacheSync, cmd/server.go:140-147) — flips it.
        if self.app.backend.list_nodes():
            self.ready.set()
        elif getattr(self.app, "ingestion", None) is not None:
            def _ready_on_sync():
                # Wait as long as it takes (WaitForCacheSync blocks until
                # sync or shutdown) — a slow apiserver must not leave the
                # server permanently not-ready.
                while not self.ready.is_set():
                    if self.app.ingestion.wait_synced(timeout=30.0):
                        self.ready.set()
                        return
                    if self._shutdown.is_set():
                        return

            threading.Thread(
                target=_ready_on_sync, daemon=True, name="ingestion-sync-ready"
            ).start()

    def stop(self) -> None:
        self._shutdown.set()
        self.ready.clear()
        self.batcher.stop()
        # shutdown() blocks on serve_forever()'s exit handshake — only call
        # it if serving actually started (Ctrl-C can land before start()
        # finished, e.g. during the pre-start cache-sync wait).
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
        self._server.server_close()
        self.app.stop()

    def join(self) -> None:
        """Block until the serving thread exits (after start())."""
        if self._thread is not None:
            self._thread.join()

    def serve_forever(self) -> None:
        self.start()
        try:
            self.join()
        except KeyboardInterrupt:
            self.stop()


class ConversionWebhookServer:
    """Standalone conversion-webhook service (the reference ships this as a
    second binary: spark-scheduler-conversion-webhook/cmd/server.go:39-54).
    Serves only POST /convert + liveness; no scheduler state."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8485,
        cert_file: str | None = None,
        key_file: str | None = None,
        client_ca_files=None,
        request_timeout_s: float = 30.0,
        request_log: bool = False,
    ):
        class Handler(_JSONHandler):
            def do_GET(self):
                if self.path == "/status/liveness":
                    self._handle_liveness()
                else:
                    self._write(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/convert":
                    self._handle_convert()
                else:
                    self._write(404, {"error": "not found"})

        Handler.timeout = request_timeout_s
        Handler.request_log = request_log
        self._server = _Server((host, port), Handler)
        self.tls = _maybe_wrap_tls(
            self._server, cert_file, key_file, client_ca_files,
            handshake_timeout_s=request_timeout_s,
        )
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = _run_threaded(self._server, "conversion-http")

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
        self._server.server_close()

    def serve_forever(self) -> None:
        self.start()
        try:
            self._thread.join()
        except KeyboardInterrupt:
            self.stop()
