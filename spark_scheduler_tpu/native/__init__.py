"""Native (C++) runtime bindings.

Loads `libsched_runtime.so` (built from native/runtime.cpp), compiling it
with g++ on first use and caching the artifact under native/build/. Exposes:

  ClusterArena       — incremental dense cluster state + one-call snapshot
                       (feeds ClusterTensors without a per-request Python
                       walk over every node).
  NativeShardedQueue — the write-back queue of store/queue.py with the
                       dedup/shard/blocking semantics implemented in C++
                       (store/queue.go:22-144 parity).
  IngestConn         — incremental HTTP/1.1 request framer over a
                       connection-owned C++ buffer (the async transport's
                       `server.ingest: native` lane).
  PredicateSlot      — reusable arena slot a predicate body decodes into
                       (pod JSON span + '\0'-separated candidate-name blob
                       with offsets and an FNV-1a 64 digest) — the
                       zero-copy ticket server/ingest.py wraps.

`available()` reports whether the library could be built/loaded; all
consumers fall back to the pure-Python implementations when it is False, so
the framework works on toolchain-less hosts. A build/load failure is logged
ONCE (svc1log warn) and remembered in `load_error()` — never raised from
import or from `available()` — so a missing toolchain degrades the native
lanes instead of taking the server down.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "runtime.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libsched_runtime.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False
_load_error: str | None = None


def _note_failure(message: str) -> None:
    """Remember + log the first build/load failure exactly once. Consumers
    keep working on the pure-Python lanes; `load_error()` lets the server
    explain WHY `server.ingest: native` degraded."""
    global _load_failed, _load_error
    _load_failed = True
    if _load_error is not None:
        return
    _load_error = message
    try:
        from spark_scheduler_tpu.tracing import svc1log

        svc1log().warn(
            "native runtime unavailable; pure-Python fallbacks in use",
            error=message,
        )
    except Exception:
        pass


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-o",
        _SO,
        _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except FileNotFoundError:
        _note_failure(f"compiler not found: {cmd[0]}")
        return False
    except subprocess.CalledProcessError as exc:
        tail = (exc.stderr or b"")[-500:].decode(errors="replace")
        _note_failure(f"native build failed: {tail}")
        return False
    except Exception as exc:
        _note_failure(f"native build failed: {exc!r}")
        return False


class IngestEvent(ctypes.Structure):
    """Mirror of native/runtime.cpp's IngestEvent: one framed request (or
    reject / need-more) from the incremental HTTP/1.1 framer. Offsets index
    the connection buffer (`IngestConn.ptr`), valid until the next
    `next()` call."""

    _fields_ = [
        ("kind", ctypes.c_int32),
        ("status", ctypes.c_int32),
        ("flags", ctypes.c_int32),
        ("body_error", ctypes.c_int32),
        ("err_code", ctypes.c_int32),
        ("pad_", ctypes.c_int32),
        ("method_off", ctypes.c_int64),
        ("method_len", ctypes.c_int64),
        ("target_off", ctypes.c_int64),
        ("target_len", ctypes.c_int64),
        ("head_off", ctypes.c_int64),
        ("head_len", ctypes.c_int64),
        ("body_off", ctypes.c_int64),
        ("body_len", ctypes.c_int64),
        ("declared_len", ctypes.c_int64),
        ("parse_ns", ctypes.c_int64),
    ]


# Event kinds.
EV_NEED_MORE, EV_REQUEST, EV_REJECT = 0, 1, 2
# Deferred body-error codes (mapped to the routing layer's exceptions).
BODY_ERR_TRANSFER_ENCODING, BODY_ERR_CONTENT_LENGTH, BODY_ERR_TOO_LARGE = (
    1, 2, 3,
)
# Reject detail codes.
REJECT_HEADER_TOO_LARGE, REJECT_REQUEST_LINE, REJECT_HEADER_LINE = 1, 2, 3
# Request flags.
FLAG_KEEP_ALIVE, FLAG_CLOSE_AFTER, FLAG_PREDICATE = 1, 2, 4


def _bind(lib) -> None:
    i64, i32, u64, u8 = (
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_uint64,
        ctypes.c_uint8,
    )
    p = ctypes.POINTER
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_upsert.argtypes = [
        ctypes.c_void_p, i64, p(i64), i32, i32, i32, i32, i32,
    ]
    lib.arena_remove.argtypes = [ctypes.c_void_p, i64]
    lib.arena_set_name_ranks.argtypes = [ctypes.c_void_p, p(i64), i64]
    lib.arena_set_name_rank_values.argtypes = [
        ctypes.c_void_p, p(i64), p(i32), i64,
    ]
    lib.arena_snapshot.argtypes = [
        ctypes.c_void_p, i64, p(i64), p(i64), p(i32), p(i32), p(i32), p(i32),
        p(i32), p(i32), p(u8), p(u8), p(u8),
    ]
    lib.arena_snapshot_rows.argtypes = [
        ctypes.c_void_p, p(i64), i64, i64, p(i64), p(i64), p(i32), p(i32),
        p(i32), p(i32), p(i32), p(i32), p(u8), p(u8), p(u8),
    ]
    lib.arena_capacity.argtypes = [ctypes.c_void_p]
    lib.arena_capacity.restype = i64
    lib.queue_create.argtypes = [i64, i64]
    lib.queue_create.restype = ctypes.c_void_p
    lib.queue_destroy.argtypes = [ctypes.c_void_p]
    lib.queue_bucket.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.queue_bucket.restype = i64
    lib.queue_add_if_absent.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64, u64, i32,
    ]
    lib.queue_add_if_absent.restype = i32
    lib.queue_try_add_if_absent.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64, u64, i32,
    ]
    lib.queue_try_add_if_absent.restype = i32
    lib.queue_pop.argtypes = [ctypes.c_void_p, i64, i64, p(u64)]
    lib.queue_pop.restype = i32
    lib.queue_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.queue_len.argtypes = [ctypes.c_void_p, i64]
    lib.queue_len.restype = i64
    lib.queue_num_buckets.argtypes = [ctypes.c_void_p]
    lib.queue_num_buckets.restype = i64
    # ---- ingest lane (predicate slots + HTTP framer) ----
    lib.pslot_create.restype = ctypes.c_void_p
    lib.pslot_destroy.argtypes = [ctypes.c_void_p]
    lib.ingest_live_slots.restype = i64
    lib.predicate_decode_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.predicate_decode_json.restype = i32
    lib.predicate_decode_binary.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64,
    ]
    lib.predicate_decode_binary.restype = i32
    lib.pslot_pod_ptr.argtypes = [ctypes.c_void_p]
    lib.pslot_pod_ptr.restype = ctypes.c_void_p
    lib.pslot_pod_len.argtypes = [ctypes.c_void_p]
    lib.pslot_pod_len.restype = i64
    lib.pslot_blob_ptr.argtypes = [ctypes.c_void_p]
    lib.pslot_blob_ptr.restype = ctypes.c_void_p
    lib.pslot_blob_len.argtypes = [ctypes.c_void_p]
    lib.pslot_blob_len.restype = i64
    lib.pslot_offs_ptr.argtypes = [ctypes.c_void_p]
    lib.pslot_offs_ptr.restype = ctypes.c_void_p
    lib.pslot_names_count.argtypes = [ctypes.c_void_p]
    lib.pslot_names_count.restype = i64
    lib.pslot_digest.argtypes = [ctypes.c_void_p]
    lib.pslot_digest.restype = u64
    lib.pslot_decode_ns.argtypes = [ctypes.c_void_p]
    lib.pslot_decode_ns.restype = i64
    lib.pslot_blob_equal.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.pslot_blob_equal.restype = i32
    lib.ingest_conn_create.argtypes = [i64, i64]
    lib.ingest_conn_create.restype = ctypes.c_void_p
    lib.ingest_conn_destroy.argtypes = [ctypes.c_void_p]
    lib.ingest_conn_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.ingest_conn_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IngestEvent),
    ]
    lib.ingest_conn_next.restype = i32
    lib.ingest_conn_ptr.argtypes = [ctypes.c_void_p]
    lib.ingest_conn_ptr.restype = ctypes.c_void_p
    lib.ingest_conn_decode_json.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ingest_conn_decode_json.restype = i32
    lib.ingest_conn_decode_binary.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.ingest_conn_decode_binary.restype = i32


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            # Source may be absent in installed artifacts with a cached .so;
            # only rebuild when the source exists and is newer.
            stale = not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
        except OSError:
            stale = not os.path.exists(_SO)
        if stale:
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
            _lib = lib
        except OSError as exc:
            _note_failure(f"failed to load {_SO}: {exc}")
    return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> str | None:
    """Why the native runtime is unavailable (None when loaded or not yet
    attempted)."""
    _load()
    return _load_error


def live_slot_count() -> int:
    """Live predicate arena slots (the ingest telemetry's arena-occupancy
    gauge); 0 when the native runtime is unavailable."""
    lib = _load()
    return int(lib.ingest_live_slots()) if lib is not None else 0


def _i64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class ClusterArena:
    """Incremental cluster-state arena (see native/runtime.cpp)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.arena_create()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.arena_destroy(self._h)
            self._h = None

    def upsert(
        self,
        idx: int,
        alloc,  # length-3 int array (cpu_milli, mem_kib, gpu_milli)
        zone_id: int,
        unschedulable: bool,
        ready: bool,
        lr_driver: int,
        lr_executor: int,
    ) -> None:
        buf = np.ascontiguousarray(alloc, dtype=np.int64)
        self._lib.arena_upsert(
            self._h, idx, _i64p(buf), zone_id, int(unschedulable), int(ready),
            lr_driver, lr_executor,
        )

    def remove(self, idx: int) -> None:
        self._lib.arena_remove(self._h, idx)

    def set_name_ranks(self, sorted_indices) -> None:
        buf = np.ascontiguousarray(sorted_indices, dtype=np.int64)
        self._lib.arena_set_name_ranks(self._h, _i64p(buf), len(buf))

    def set_name_rank_values(self, indices, ranks) -> None:
        """Scatter explicit (gapped) rank VALUES onto slots; unlisted
        slots keep theirs. The O(changed) twin of set_name_ranks — see
        arena_set_name_rank_values in native/runtime.cpp."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        val = np.ascontiguousarray(ranks, dtype=np.int32)
        self._lib.arena_set_name_rank_values(
            self._h, _i64p(idx), _i32p(val), len(idx)
        )

    def capacity(self) -> int:
        return int(self._lib.arena_capacity(self._h))

    def snapshot_raw(self, n: int, usage: np.ndarray, overhead: np.ndarray):
        """snapshot() but returning the three mask fields as their uint8
        BACKING buffers (callers expose `.view(np.bool_)` of the same
        memory) — the solver's resident tensor build keeps these buffers
        and patches them in place via snapshot_rows."""
        usage = np.ascontiguousarray(usage, dtype=np.int64)
        overhead = np.ascontiguousarray(overhead, dtype=np.int64)
        available = np.empty((n, 3), dtype=np.int32)
        schedulable = np.empty((n, 3), dtype=np.int32)
        zone_id = np.empty(n, dtype=np.int32)
        name_rank = np.empty(n, dtype=np.int32)
        lr_driver = np.empty(n, dtype=np.int32)
        lr_executor = np.empty(n, dtype=np.int32)
        unschedulable = np.empty(n, dtype=np.uint8)
        ready = np.empty(n, dtype=np.uint8)
        valid = np.empty(n, dtype=np.uint8)
        self._lib.arena_snapshot(
            self._h, n, _i64p(usage), _i64p(overhead), _i32p(available),
            _i32p(schedulable), _i32p(zone_id), _i32p(name_rank),
            _i32p(lr_driver), _i32p(lr_executor), _u8p(unschedulable),
            _u8p(ready), _u8p(valid),
        )
        return (
            available,
            schedulable,
            zone_id,
            name_rank,
            lr_driver,
            lr_executor,
            unschedulable,
            ready,
            valid,
        )

    def snapshot(self, n: int, usage: np.ndarray, overhead: np.ndarray):
        """Materialize ClusterTensors fields for slots [0, n).

        usage/overhead: [n, 3] int64 (caller scatters the sparse maps).
        Returns the 9 arrays in ClusterTensors field order.
        """
        fields = self.snapshot_raw(n, usage, overhead)
        return fields[:6] + tuple(f.astype(bool) for f in fields[6:])

    def snapshot_rows(
        self,
        rows: np.ndarray,
        usage: np.ndarray,
        overhead: np.ndarray,
        available: np.ndarray,
        schedulable: np.ndarray,
        zone_id: np.ndarray,
        name_rank: np.ndarray,
        lr_driver: np.ndarray,
        lr_executor: np.ndarray,
        unschedulable: np.ndarray,
        ready: np.ndarray,
        valid: np.ndarray,
    ) -> None:
        """Recompute ONLY `rows` into the caller's RESIDENT field buffers
        (the solver's O(K + changed) tensor build). Buffers must be the
        C-contiguous arrays of one prior full `snapshot` materialization;
        unschedulable/ready/valid are the uint8 backing stores (callers
        expose bool views of the same memory). usage/overhead are the FULL
        [n, 3] int64 inputs — only their `rows` entries are read."""
        idx = np.ascontiguousarray(rows, dtype=np.int64)
        usage = np.ascontiguousarray(usage, dtype=np.int64)
        overhead = np.ascontiguousarray(overhead, dtype=np.int64)
        self._lib.arena_snapshot_rows(
            self._h, _i64p(idx), len(idx), available.shape[0], _i64p(usage),
            _i64p(overhead), _i32p(available), _i32p(schedulable),
            _i32p(zone_id), _i32p(name_rank), _i32p(lr_driver),
            _i32p(lr_executor), _u8p(unschedulable), _u8p(ready), _u8p(valid),
        )


class NativeShardedQueue:
    """C++-backed ShardedUniqueQueue (store/queue.py interface parity).

    Tickets (u64) index a Python-side table carrying the Request payloads;
    the C++ side owns dedup, sharding, buffering, and blocking.
    """

    def __init__(self, buckets: int, buffer_size: int = 100):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.queue_create(buckets, buffer_size)
        self._payloads: dict[int, object] = {}
        self._next_ticket = 0
        self._lock = threading.Lock()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.queue_destroy(self._h)
            self._h = None

    def _ticket_for(self, payload) -> int:
        with self._lock:
            self._next_ticket += 1
            t = self._next_ticket
            self._payloads[t] = payload
        return t

    @staticmethod
    def _key_bytes(key) -> bytes:
        return f"{key[0]}/{key[1]}".encode() if isinstance(key, tuple) else str(key).encode()

    def add_if_absent(self, req) -> None:
        kb = self._key_bytes(req.key)
        is_delete = 1 if req.type.name == "DELETE" else 0
        t = self._ticket_for(req)
        if not self._lib.queue_add_if_absent(self._h, kb, len(kb), t, is_delete):
            with self._lock:
                self._payloads.pop(t, None)  # deduped: drop the ticket

    def try_add_if_absent(self, req) -> bool:
        kb = self._key_bytes(req.key)
        is_delete = 1 if req.type.name == "DELETE" else 0
        t = self._ticket_for(req)
        rc = self._lib.queue_try_add_if_absent(self._h, kb, len(kb), t, is_delete)
        if rc != 1:
            with self._lock:
                self._payloads.pop(t, None)
        # Deduped (0) counts as success — a pending request already covers
        # this key; only a full buffer (-1) reports failure (queue.go:73-88).
        return rc != -1

    def pop(self, bucket: int, timeout_s: float | None):
        """Blocking pop for consumer `bucket`; None on timeout. Releases the
        key from the inflight set so later writes re-enqueue
        (queue.go:90-104)."""
        ms = int((timeout_s if timeout_s is not None else 3600.0) * 1000)
        out = ctypes.c_uint64()
        if not self._lib.queue_pop(self._h, bucket, ms, ctypes.byref(out)):
            return None
        with self._lock:
            req = self._payloads.pop(out.value)
        kb = self._key_bytes(req.key)
        self._lib.queue_release(self._h, kb, len(kb))
        return req

    def queue_lengths(self) -> list[int]:
        n = self._lib.queue_num_buckets(self._h)
        return [int(self._lib.queue_len(self._h, b)) for b in range(n)]

    @property
    def num_buckets(self) -> int:
        return int(self._lib.queue_num_buckets(self._h))


class PredicateSlot:
    """One reusable arena slot a predicate body decodes into. The slot owns
    the tokenized candidate-name blob and the pod JSON span; it is the
    TICKET the serving path carries (server/ingest.py wraps it in a
    NativeNodeNames) — freed when the last reference drops."""

    __slots__ = ("_lib", "_h")

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pslot_create()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pslot_destroy(self._h)
            self._h = None

    def decode_json(self, body: bytes) -> bool:
        return bool(
            self._lib.predicate_decode_json(self._h, body, len(body))
        )

    def decode_binary(self, body: bytes) -> bool:
        return bool(
            self._lib.predicate_decode_binary(self._h, body, len(body))
        )

    @property
    def names_count(self) -> int:
        return int(self._lib.pslot_names_count(self._h))

    @property
    def digest(self) -> int:
        return int(self._lib.pslot_digest(self._h))

    @property
    def decode_ns(self) -> int:
        return int(self._lib.pslot_decode_ns(self._h))

    def pod_json(self) -> bytes:
        n = self._lib.pslot_pod_len(self._h)
        if not n:
            return b"{}"
        return ctypes.string_at(self._lib.pslot_pod_ptr(self._h), n)

    def names_blob(self) -> bytes:
        n = self._lib.pslot_blob_len(self._h)
        if not n:
            return b""
        return ctypes.string_at(self._lib.pslot_blob_ptr(self._h), n)

    def name_at(self, i: int) -> str:
        count = self.names_count
        if not 0 <= i < count:
            raise IndexError(i)
        offs = ctypes.cast(
            self._lib.pslot_offs_ptr(self._h),
            ctypes.POINTER(ctypes.c_int32),
        )
        start, end = offs[i], offs[i + 1] - 1  # exclude the '\0'
        return ctypes.string_at(
            self._lib.pslot_blob_ptr(self._h) + start, end - start
        ).decode("utf-8")

    def blob_equal(self, other: "PredicateSlot") -> bool:
        return bool(self._lib.pslot_blob_equal(self._h, other._h))


class IngestConn:
    """Per-connection incremental HTTP/1.1 framer (the native ingest lane's
    transport half). `feed` appends received bytes; `next` returns the next
    IngestEvent — offsets valid until the FOLLOWING `next` call, which
    reclaims the consumed prefix. `decode_into` tokenizes the last framed
    request's body straight from the connection buffer into a slot (the
    body bytes never materialize as a Python object)."""

    __slots__ = ("_lib", "_h", "_ev")

    def __init__(self, max_body_bytes: int | None, max_header_bytes: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.ingest_conn_create(
            -1 if max_body_bytes is None else int(max_body_bytes),
            int(max_header_bytes),
        )
        self._ev = IngestEvent()

    def __del__(self):
        self.close()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ingest_conn_destroy(self._h)
            self._h = None

    def feed(self, data: bytes) -> None:
        self._lib.ingest_conn_feed(self._h, data, len(data))

    def next(self) -> IngestEvent:
        self._lib.ingest_conn_next(self._h, ctypes.byref(self._ev))
        return self._ev

    def read(self, off: int, length: int) -> bytes:
        if not length:
            return b""
        return ctypes.string_at(self._lib.ingest_conn_ptr(self._h) + off, length)

    def decode_into(self, slot: PredicateSlot, *, binary: bool) -> bool:
        fn = (
            self._lib.ingest_conn_decode_binary
            if binary
            else self._lib.ingest_conn_decode_json
        )
        return bool(fn(self._h, slot._h))
