"""Native (C++) runtime bindings.

Loads `libsched_runtime.so` (built from native/runtime.cpp), compiling it
with g++ on first use and caching the artifact under native/build/. Exposes:

  ClusterArena       — incremental dense cluster state + one-call snapshot
                       (feeds ClusterTensors without a per-request Python
                       walk over every node).
  NativeShardedQueue — the write-back queue of store/queue.py with the
                       dedup/shard/blocking semantics implemented in C++
                       (store/queue.go:22-144 parity).

`available()` reports whether the library could be built/loaded; all
consumers fall back to the pure-Python implementations when it is False, so
the framework works on toolchain-less hosts.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "runtime.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libsched_runtime.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-o",
        _SO,
        _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _bind(lib) -> None:
    i64, i32, u64, u8 = (
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_uint64,
        ctypes.c_uint8,
    )
    p = ctypes.POINTER
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_upsert.argtypes = [
        ctypes.c_void_p, i64, p(i64), i32, i32, i32, i32, i32,
    ]
    lib.arena_remove.argtypes = [ctypes.c_void_p, i64]
    lib.arena_set_name_ranks.argtypes = [ctypes.c_void_p, p(i64), i64]
    lib.arena_snapshot.argtypes = [
        ctypes.c_void_p, i64, p(i64), p(i64), p(i32), p(i32), p(i32), p(i32),
        p(i32), p(i32), p(u8), p(u8), p(u8),
    ]
    lib.arena_capacity.argtypes = [ctypes.c_void_p]
    lib.arena_capacity.restype = i64
    lib.queue_create.argtypes = [i64, i64]
    lib.queue_create.restype = ctypes.c_void_p
    lib.queue_destroy.argtypes = [ctypes.c_void_p]
    lib.queue_bucket.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.queue_bucket.restype = i64
    lib.queue_add_if_absent.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64, u64, i32,
    ]
    lib.queue_add_if_absent.restype = i32
    lib.queue_try_add_if_absent.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64, u64, i32,
    ]
    lib.queue_try_add_if_absent.restype = i32
    lib.queue_pop.argtypes = [ctypes.c_void_p, i64, i64, p(u64)]
    lib.queue_pop.restype = i32
    lib.queue_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i64]
    lib.queue_len.argtypes = [ctypes.c_void_p, i64]
    lib.queue_len.restype = i64
    lib.queue_num_buckets.argtypes = [ctypes.c_void_p]
    lib.queue_num_buckets.restype = i64


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            # Source may be absent in installed artifacts with a cached .so;
            # only rebuild when the source exists and is newer.
            stale = not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
        except OSError:
            stale = not os.path.exists(_SO)
        if stale:
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
            _lib = lib
        except OSError:
            _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def _i64p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class ClusterArena:
    """Incremental cluster-state arena (see native/runtime.cpp)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.arena_create()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.arena_destroy(self._h)
            self._h = None

    def upsert(
        self,
        idx: int,
        alloc,  # length-3 int array (cpu_milli, mem_kib, gpu_milli)
        zone_id: int,
        unschedulable: bool,
        ready: bool,
        lr_driver: int,
        lr_executor: int,
    ) -> None:
        buf = np.ascontiguousarray(alloc, dtype=np.int64)
        self._lib.arena_upsert(
            self._h, idx, _i64p(buf), zone_id, int(unschedulable), int(ready),
            lr_driver, lr_executor,
        )

    def remove(self, idx: int) -> None:
        self._lib.arena_remove(self._h, idx)

    def set_name_ranks(self, sorted_indices) -> None:
        buf = np.ascontiguousarray(sorted_indices, dtype=np.int64)
        self._lib.arena_set_name_ranks(self._h, _i64p(buf), len(buf))

    def capacity(self) -> int:
        return int(self._lib.arena_capacity(self._h))

    def snapshot(self, n: int, usage: np.ndarray, overhead: np.ndarray):
        """Materialize ClusterTensors fields for slots [0, n).

        usage/overhead: [n, 3] int64 (caller scatters the sparse maps).
        Returns the 9 arrays in ClusterTensors field order.
        """
        usage = np.ascontiguousarray(usage, dtype=np.int64)
        overhead = np.ascontiguousarray(overhead, dtype=np.int64)
        available = np.empty((n, 3), dtype=np.int32)
        schedulable = np.empty((n, 3), dtype=np.int32)
        zone_id = np.empty(n, dtype=np.int32)
        name_rank = np.empty(n, dtype=np.int32)
        lr_driver = np.empty(n, dtype=np.int32)
        lr_executor = np.empty(n, dtype=np.int32)
        unschedulable = np.empty(n, dtype=np.uint8)
        ready = np.empty(n, dtype=np.uint8)
        valid = np.empty(n, dtype=np.uint8)
        self._lib.arena_snapshot(
            self._h, n, _i64p(usage), _i64p(overhead), _i32p(available),
            _i32p(schedulable), _i32p(zone_id), _i32p(name_rank),
            _i32p(lr_driver), _i32p(lr_executor), _u8p(unschedulable),
            _u8p(ready), _u8p(valid),
        )
        return (
            available,
            schedulable,
            zone_id,
            name_rank,
            lr_driver,
            lr_executor,
            unschedulable.astype(bool),
            ready.astype(bool),
            valid.astype(bool),
        )


class NativeShardedQueue:
    """C++-backed ShardedUniqueQueue (store/queue.py interface parity).

    Tickets (u64) index a Python-side table carrying the Request payloads;
    the C++ side owns dedup, sharding, buffering, and blocking.
    """

    def __init__(self, buckets: int, buffer_size: int = 100):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.queue_create(buckets, buffer_size)
        self._payloads: dict[int, object] = {}
        self._next_ticket = 0
        self._lock = threading.Lock()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.queue_destroy(self._h)
            self._h = None

    def _ticket_for(self, payload) -> int:
        with self._lock:
            self._next_ticket += 1
            t = self._next_ticket
            self._payloads[t] = payload
        return t

    @staticmethod
    def _key_bytes(key) -> bytes:
        return f"{key[0]}/{key[1]}".encode() if isinstance(key, tuple) else str(key).encode()

    def add_if_absent(self, req) -> None:
        kb = self._key_bytes(req.key)
        is_delete = 1 if req.type.name == "DELETE" else 0
        t = self._ticket_for(req)
        if not self._lib.queue_add_if_absent(self._h, kb, len(kb), t, is_delete):
            with self._lock:
                self._payloads.pop(t, None)  # deduped: drop the ticket

    def try_add_if_absent(self, req) -> bool:
        kb = self._key_bytes(req.key)
        is_delete = 1 if req.type.name == "DELETE" else 0
        t = self._ticket_for(req)
        rc = self._lib.queue_try_add_if_absent(self._h, kb, len(kb), t, is_delete)
        if rc != 1:
            with self._lock:
                self._payloads.pop(t, None)
        # Deduped (0) counts as success — a pending request already covers
        # this key; only a full buffer (-1) reports failure (queue.go:73-88).
        return rc != -1

    def pop(self, bucket: int, timeout_s: float | None):
        """Blocking pop for consumer `bucket`; None on timeout. Releases the
        key from the inflight set so later writes re-enqueue
        (queue.go:90-104)."""
        ms = int((timeout_s if timeout_s is not None else 3600.0) * 1000)
        out = ctypes.c_uint64()
        if not self._lib.queue_pop(self._h, bucket, ms, ctypes.byref(out)):
            return None
        with self._lock:
            req = self._payloads.pop(out.value)
        kb = self._key_bytes(req.key)
        self._lib.queue_release(self._h, kb, len(kb))
        return req

    def queue_lengths(self) -> list[int]:
        n = self._lib.queue_num_buckets(self._h)
        return [int(self._lib.queue_len(self._h, b)) for b in range(n)]

    @property
    def num_buckets(self) -> int:
        return int(self._lib.queue_num_buckets(self._h))
