"""Fenced leader lease.

One lease record arbitrates which replica is the leader. The record
carries a monotonically increasing **epoch** that bumps on every takeover
(never on renewal); reservation/demand writes are gated on the writer's
acquired epoch still being the live one (see fencing.FencedBackend), so a
deposed leader's in-flight commit is rejected instead of double-placing —
the classic fencing-token discipline the reference never needed because
its leader was a Kubernetes lease + a whole process.

Two stores back the record:

  BackendLeaseStore  the lease lives as a backend object of kind
                     "leases"; compare-and-swap rides the backend's
                     optimistic concurrency (resourceVersion conflicts).
                     The in-process replica group and the kube-backend
                     deployment (apiserver CAS) use this.
  FileLeaseStore     a JSON sidecar next to the WAL, every mutation under
                     an exclusive flock on `<path>.lock` with a
                     read-check-write inside the critical section — the
                     multi-process DurableBackend deployment's arbiter
                     (the WAL itself has no cross-process CAS).

Expiry is wall-clock based (`renewed_at + ttl`), evaluated by readers: a
leader that misses heartbeats for a TTL is take-over-able; its next
fenced write then sees the bumped epoch and fails.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from spark_scheduler_tpu.store.backend import AlreadyExistsError, ConflictError

LEASE_NAME = "scheduler-leader"


class FencingError(RuntimeError):
    """A write carried a stale fencing epoch (the writer was deposed)."""


@dataclasses.dataclass
class LeaseRecord:
    """The lease object. `epoch` bumps on takeover only; `renewed_at` is
    seconds on the shared clock; `holder` is the replica id ('' after a
    clean release — epoch survives so fencing stays monotonic)."""

    holder: str
    epoch: int
    renewed_at: float
    ttl_s: float
    name: str = LEASE_NAME
    namespace: str = ""
    resource_version: int = 0

    def expired(self, now: float) -> bool:
        return not self.holder or now > self.renewed_at + self.ttl_s

    def to_wire(self) -> dict:
        return {
            "holder": self.holder,
            "epoch": self.epoch,
            "renewed_at": self.renewed_at,
            "ttl_s": self.ttl_s,
            "name": self.name,
        }

    @classmethod
    def from_wire(cls, raw: dict) -> "LeaseRecord":
        return cls(
            holder=raw.get("holder", ""),
            epoch=int(raw.get("epoch", 0)),
            renewed_at=float(raw.get("renewed_at", 0.0)),
            ttl_s=float(raw.get("ttl_s", 0.0)),
            name=raw.get("name", LEASE_NAME),
        )


class BackendLeaseStore:
    """Lease record as a backend object; CAS via resourceVersion."""

    def __init__(self, backend):
        self._backend = backend

    def read(self) -> Optional[LeaseRecord]:
        return self._backend.get("leases", "", LEASE_NAME)

    def compare_and_swap(self, expect: Optional[LeaseRecord], record: LeaseRecord) -> bool:
        """Write `record` iff the stored lease is still `expect` (None =
        must not exist). Returns False when another replica won the race."""
        try:
            if expect is None:
                record.resource_version = 0
                self._backend.create("leases", record)
            else:
                record.resource_version = expect.resource_version
                self._backend.update("leases", record)
            return True
        except (ConflictError, AlreadyExistsError):
            return False


class FileLeaseStore:
    """Lease record in a JSON sidecar file; mutations under an exclusive
    flock on `<path>.lock`, with the read re-done INSIDE the lock so the
    compare half of the CAS cannot race another process."""

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def _read_unlocked(self) -> Optional[LeaseRecord]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return LeaseRecord.from_wire(json.load(f))
        except (OSError, ValueError):
            return None

    def read(self) -> Optional[LeaseRecord]:
        return self._read_unlocked()

    def _flock(self):
        import fcntl

        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR)
        fcntl.flock(fd, fcntl.LOCK_EX)
        return fd

    def compare_and_swap(self, expect: Optional[LeaseRecord], record: LeaseRecord) -> bool:
        import fcntl

        fd = self._flock()
        try:
            cur = self._read_unlocked()
            if (cur is None) != (expect is None):
                return False
            if cur is not None and (
                cur.epoch != expect.epoch
                or cur.holder != expect.holder
                # Renewals move ONLY renewed_at: without comparing it, a
                # standby's takeover CAS (read just as the TTL lapsed)
                # would overwrite a renewal that landed in between —
                # deposing a healthy leader mid-term. json round-trips
                # floats exactly (repr), so equality is sound.
                or cur.renewed_at != expect.renewed_at
            ):
                return False
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record.to_wire(), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return True
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)


class LeaseManager:
    """One replica's view of the lease: acquisition, renewal, and the
    fencing checks the write path and the extender's resync heuristic key
    on. Thread-safe — the heartbeat thread renews while request threads
    check the fence."""

    def __init__(
        self,
        store,
        holder: str,
        ttl_s: float = 3.0,
        clock=time.time,
        retry_policy=None,
        breaker=None,
    ):
        self._store = store
        self.holder = holder
        self.ttl_s = ttl_s
        self._clock = clock
        # Lease-store IO rides the shared retry ladder (ISSUE 9): a
        # transient store blip must not read as "deposed" — the renew
        # retries inside the heartbeat's budget. Defaults keep total
        # retry time well under the TTL (a renew that outlives the TTL
        # is worse than one that fails: the next tick re-elects). The
        # breaker stops a dead store being hammered at heartbeat rate.
        from spark_scheduler_tpu.faults.retry import RetryPolicy

        self._retry_policy = retry_policy or RetryPolicy(
            max_attempts=3,
            base_delay_s=min(0.05, ttl_s / 30.0),
            multiplier=2.0,
            max_delay_s=max(0.05, ttl_s / 6.0),
        )
        self._breaker = breaker
        self._lock = threading.Lock()
        # The epoch THIS replica acquired (0 = never held). Fenced writes
        # compare it against the live record's epoch.
        self.acquired_epoch = 0
        self.fenced_rejects = 0
        # Clock time of the last successful acquire/renew: while it is
        # fresher than the TTL no other replica CAN have taken over (a
        # takeover requires the record we renewed to expire first), so
        # is_held() answers from memory — keeping the per-request resync
        # heuristic off the lease store (for FileLeaseStore that read is
        # open+parse of the sidecar on the predicate hot path).
        self._last_affirmed = float("-inf")

    # -- store IO (retry ladder) -------------------------------------------

    def _read(self):
        return self._retry_policy.call(
            self._store.read, breaker=self._breaker
        )

    def _cas(self, expect, record) -> bool:
        # Only the STORE-level failure retries; a lost CAS returns False
        # immediately (someone else won — retrying would be a livelock).
        return self._retry_policy.call(
            lambda: self._store.compare_and_swap(expect, record),
            breaker=self._breaker,
        )

    # -- election ----------------------------------------------------------

    def try_acquire(self) -> bool:
        """Acquire or re-affirm leadership. Takeover of an absent/expired
        lease bumps the epoch (the fencing token); holding it already just
        renews. False when another holder's lease is live or the CAS lost."""
        now = self._clock()
        cur = self._read()
        if cur is None:
            ok = self._cas(None,
                LeaseRecord(self.holder, 1, now, self.ttl_s),
            )
            if ok:
                with self._lock:
                    self.acquired_epoch = 1
                    self._last_affirmed = now
            return ok
        if cur.holder == self.holder and cur.epoch == self.acquired_epoch:
            return self.renew()
        if not cur.expired(now):
            return False
        ok = self._cas(cur,
            LeaseRecord(self.holder, cur.epoch + 1, now, self.ttl_s),
        )
        if ok:
            with self._lock:
                self.acquired_epoch = cur.epoch + 1
                self._last_affirmed = now
        return ok

    def renew(self) -> bool:
        """Heartbeat: extend the lease without changing the epoch. False =
        deposed (the record moved under us) — the caller must stop serving."""
        with self._lock:
            epoch = self.acquired_epoch
        if not epoch:
            return False
        cur = self._read()
        if cur is None or cur.holder != self.holder or cur.epoch != epoch:
            return False
        now = self._clock()
        ok = self._cas(cur,
            LeaseRecord(self.holder, epoch, now, self.ttl_s),
        )
        if ok:
            with self._lock:
                self._last_affirmed = now
        return ok

    def release(self) -> None:
        """Clean shutdown: expire the lease NOW (holder cleared, epoch kept
        so the next takeover still bumps past every fenced write we made)."""
        with self._lock:
            epoch = self.acquired_epoch
            self.acquired_epoch = 0
            self._last_affirmed = float("-inf")
        if not epoch:
            return
        cur = self._read()
        if cur is not None and cur.holder == self.holder and cur.epoch == epoch:
            self._cas(cur, LeaseRecord("", epoch, 0.0, self.ttl_s)
            )

    # -- fencing -----------------------------------------------------------

    def is_held(self) -> bool:
        """Local view: we acquired the lease and our epoch is still the
        live one and unexpired. The extender's >gap resync heuristic keys
        on this (a held lease means no silent leader change can have
        happened during a request gap). Answered from memory while the
        last successful acquire/renew is fresher than the TTL — within
        that window the record we wrote cannot have expired, so no
        takeover can have happened; the store is consulted only when the
        heartbeat has gone stale."""
        with self._lock:
            epoch = self.acquired_epoch
            last = self._last_affirmed
        if not epoch:
            return False
        if self._clock() - last < self.ttl_s:
            return True
        cur = self._read()
        return (
            cur is not None
            and cur.holder == self.holder
            and cur.epoch == epoch
            and not cur.expired(self._clock())
        )

    def check_fence(self) -> None:
        """Raise FencingError unless this replica's acquired epoch is the
        live lease epoch. Called by FencedBackend INSIDE the mutation path
        of reservation/demand writes — the read is one dict get (backend
        store) or one small file read (WAL sidecar)."""
        with self._lock:
            epoch = self.acquired_epoch
        cur = self._read()
        if (
            not epoch
            or cur is None
            or cur.holder != self.holder
            or cur.epoch != epoch
        ):
            with self._lock:
                self.fenced_rejects += 1
            live = "none" if cur is None else f"{cur.holder}@{cur.epoch}"
            raise FencingError(
                f"fenced write rejected: {self.holder}@{epoch} is not the "
                f"live lease ({live})"
            )

    # -- introspection -----------------------------------------------------

    def state(self) -> dict:
        cur = self._read()
        now = self._clock()
        return {
            "holder": self.holder,
            "acquired_epoch": self.acquired_epoch,
            "lease_holder": cur.holder if cur is not None else None,
            "lease_epoch": cur.epoch if cur is not None else 0,
            "lease_age_s": (
                round(now - cur.renewed_at, 3) if cur is not None else None
            ),
            "lease_ttl_s": self.ttl_s,
            "lease_expired": cur.expired(now) if cur is not None else True,
            "fenced_rejects": self.fenced_rejects,
        }
