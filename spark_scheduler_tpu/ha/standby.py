"""StandbyTailer — keep a replica's caches hot from backend events.

A WriteThroughCache deliberately ignores external creates/updates (its
owner is the sole writer, cache.go:96-118) — correct for ONE process, but
a warm standby must absorb the leader's reservation/demand commits or its
promotion pays a full cold rebuild. The tailer subscribes to the shared
backend's event bus and applies every event it did NOT originate into the
replica's own caches via `apply_external_upsert` / `apply_external_delete`
— which fire the caches' mutation listeners, so the ReservedUsageTracker's
dense usage array (and through it the HostFeatureStore's snapshot) stays
warm too. Promotion then costs one failover reconcile, not a state
rebuild.

Self-write dedup: the owner's writes also fire backend events back at the
tailer. rv equality CANNOT be the signal — the cache's own
watch-subscription (registered first) fast-forwards the stored object's
resourceVersion to the committed one without touching content, so by the
time the tailer runs, an EXTERNAL update's rv matches too and rv-dedup
would drop the leader's new content forever. Content equality is the
correct signal: for an own write the stored object IS the committed
content (the owner wrote it, and the rv was just fast-forwarded), so
`stored == obj` holds; an external update differs somewhere or there is
nothing to absorb. This makes the tailer safe to leave running in EVERY
role: on a standby all events are external; on the leader all are own
writes; on an active-active shard member both mix.
"""

from __future__ import annotations


class StandbyTailer:
    def __init__(self, app):
        self._app = app
        self.enabled = True
        self.applied = 0
        self.skipped_own = 0
        backend = app.backend
        backend.subscribe(
            "resourcereservations",
            on_add=lambda obj: self._upsert(self._rr_cache(), obj),
            on_update=lambda old, new: self._upsert(self._rr_cache(), new),
            on_delete=lambda obj: self._delete(self._rr_cache(), obj),
        )
        backend.subscribe(
            "demands",
            on_add=lambda obj: self._upsert(self._demand_cache(), obj),
            on_update=lambda old, new: self._upsert(self._demand_cache(), new),
            on_delete=lambda obj: self._delete(self._demand_cache(), obj),
        )

    def _rr_cache(self):
        return self._app.rr_cache

    def _demand_cache(self):
        # SafeDemandCache: the inner cache exists only once the Demand CRD
        # does; before that, demand events have nothing to warm.
        safe = self._app.demand_cache
        return safe._cache if safe.crd_exists() else None

    def _upsert(self, cache, obj) -> None:
        if not self.enabled or cache is None:
            return
        stored = cache.get(obj.namespace, obj.name)
        if stored is not None and stored == obj:
            self.skipped_own += 1  # own write (or an absorbed no-op)
            return
        # Store a copy when the model supports it: backend and cache must
        # not alias one mutable object across replicas.
        cache.apply_external_upsert(obj.copy() if hasattr(obj, "copy") else obj)
        self.applied += 1

    def _delete(self, cache, obj) -> None:
        if not self.enabled or cache is None:
            return
        if cache.get(obj.namespace, obj.name) is None:
            self.skipped_own += 1  # own delete already removed it
            return
        cache.apply_external_delete(obj.namespace, obj.name)
        self.applied += 1

    def stats(self) -> dict:
        return {"applied": self.applied, "skipped_own": self.skipped_own}
