"""ShardMap — instance group -> owning replica.

The active-active traffic partition: live predicate traffic is sharded by
the pod's instance group, the same boundary PR 4's domain partitioning
proved commutes (a group's gangs only ever place on that group's nodes,
so per-group solves are independent and order-free across groups). The
map is a pure function of (group, replica count) — stable CRC32 — so
every replica computes the same ownership with no coordination, and
kube-scheduler can hit any replica: non-owners forward to the owner
(in-process delegation or an HTTP redirect) instead of failing.
"""

from __future__ import annotations

import zlib


class ShardMap:
    def __init__(self, n_replicas: int):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.n_replicas = n_replicas
        # Live membership: removing a member remaps its groups onto the
        # survivors (modulo over the live list — every replica computes
        # the same map from the same membership, no coordination beyond
        # agreeing on who is live).
        self._live = list(range(n_replicas))

    def remove(self, index: int) -> None:
        if len(self._live) <= 1:
            raise ValueError("cannot remove the last live replica")
        if index in self._live:
            self._live.remove(index)

    def owner(self, instance_group: str) -> int:
        """Owning replica index for a group — stable across processes and
        runs (CRC32, not Python's salted hash). Assignment is over the
        ORIGINAL slot space: removing a member moves only ITS groups onto
        survivors — a surviving member's groups never change owner, so an
        in-flight window on a survivor cannot silently lose ownership
        mid-commit (only the removed member moves, and it is fenced)."""
        h = zlib.crc32(instance_group.encode("utf-8"))
        idx = h % self.n_replicas
        live = self._live  # never empty: remove() refuses the last member
        if idx in live:
            return idx
        return live[h % len(live)]

    def owned_by(self, index: int, groups) -> list[str]:
        return [g for g in groups if self.owner(g) == index]

    def describe(self, groups=()) -> dict:
        return {
            "replicas": self.n_replicas,
            "live": list(self._live),
            "assignments": {g: self.owner(g) for g in groups},
        }
