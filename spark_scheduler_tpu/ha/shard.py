"""ShardMap — instance group -> owning replica.

The active-active traffic partition: live predicate traffic is sharded by
the pod's instance group, the same boundary PR 4's domain partitioning
proved commutes (a group's gangs only ever place on that group's nodes,
so per-group solves are independent and order-free across groups). The
map is a pure function of (group, replica count) — stable CRC32 — so
every replica computes the same ownership with no coordination, and
kube-scheduler can hit any replica: non-owners forward to the owner
(in-process delegation or an HTTP redirect) instead of failing.

The membership/remap mechanics live in core/membership.py
(StableMembership), shared with the fleet-level ClusterMap so the two
layers cannot fork the remap logic.
"""

from __future__ import annotations

from spark_scheduler_tpu.core.membership import StableMembership


class ShardMap:
    def __init__(self, n_replicas: int):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        # Live membership: removing a member remaps its groups onto the
        # survivors (modulo over the live list — every replica computes
        # the same map from the same membership, no coordination beyond
        # agreeing on who is live).
        self._members = StableMembership(n_replicas)

    @property
    def n_replicas(self) -> int:
        return self._members.n_slots

    @property
    def _live(self) -> list[int]:
        return self._members._live

    def remove(self, index: int) -> None:
        if len(self._members._live) <= 1:
            raise ValueError("cannot remove the last live replica")
        self._members.remove(index)

    def owner(self, instance_group: str) -> int:
        """Owning replica index for a group — stable across processes and
        runs (CRC32, not Python's salted hash). Assignment is over the
        ORIGINAL slot space: removing a member moves only ITS groups onto
        survivors — a surviving member's groups never change owner, so an
        in-flight window on a survivor cannot silently lose ownership
        mid-commit (only the removed member moves, and it is fenced)."""
        return self._members.owner(instance_group)

    def owned_by(self, index: int, groups) -> list[str]:
        return self._members.owned_by(index, groups)

    def describe(self, groups=()) -> dict:
        return {
            "replicas": self.n_replicas,
            "live": self._members.live(),
            "assignments": {g: self.owner(g) for g in groups},
        }
