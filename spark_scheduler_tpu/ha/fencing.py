"""FencedBackend — the fenced commit path.

A replica's SchedulerApp is built over this proxy instead of the shared
backend: every mutation of a FENCED kind (reservations, demands — the
durable scheduling decisions) first validates the replica's fencing gate,
raising `FencingError` when the replica is no longer entitled to write.
Reservation writes are async and fire-and-forget in the reference
(failover.go:35-41), so a deposed leader can have commits in flight at the
moment a standby takes over; without the fence those commits land AFTER
the new leader reconciled and double-place gangs. With it they fail
internal, the client retries against the new leader, and the invariant
soak's zero-double-placement assertion holds through leader kills.

Reads and pod/node writes (observed cluster state, not scheduling
decisions) pass through unfenced — every replica must keep ingesting
watch state to stay warm.

The gate is a callable so the two HA modes share the proxy:
leader/standby passes `LeaseManager.check_fence` (epoch comparison
against the live lease); the active-active sharded group passes its
membership check (a removed member's writes fail).
"""

from __future__ import annotations

from typing import Any

FENCED_KINDS = frozenset({"resourcereservations", "demands"})


class FencedBackend:
    """Delegating proxy over a ClusterBackend. Only the generic mutation
    verbs are intercepted — reservation/demand traffic flows exclusively
    through the write-through caches, which call these verbs; pod/node
    conveniences (add_pod, bind_pod, ...) delegate untouched."""

    def __init__(self, inner, gate, on_reject=None):
        # Object.__setattr__ not needed: we define real attributes and
        # forward the rest via __getattr__.
        self._inner = inner
        self._gate = gate
        self._on_reject = on_reject

    def _check(self, kind: str) -> None:
        if kind in FENCED_KINDS:
            try:
                self._gate()
            except Exception:
                if self._on_reject is not None:
                    self._on_reject(kind)
                raise

    # -- fenced verbs ------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        self._check(kind)
        return self._inner.create(kind, obj)

    def update(self, kind: str, obj: Any) -> Any:
        self._check(kind)
        return self._inner.update(kind, obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._check(kind)
        return self._inner.delete(kind, namespace, name)

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    @property
    def inner(self):
        """The shared (unfenced) backend — what the lease store and the
        replica group's shared fixtures write through."""
        return self._inner
