"""Active-active HA: lease-based leader election, warm standbys, sharding.

The reference's failover story (failover.go:35-72) assumes ONE extender
process whose restart is a leader change; this package makes the leader a
ROLE instead of a process:

  lease      a fenced lease record (epoch counter bumped on every
             takeover) renewed on a heartbeat; CAS through the backend's
             optimistic concurrency in-process, or an flock-guarded
             sidecar file for multi-process WAL deployments.
  fencing    `FencedBackend` — reservation/demand writes carry the
             holder's fencing epoch; a deposed leader's in-flight commit
             raises `FencingError` instead of double-placing.
  standby    `StandbyTailer` — replicas tail backend events so the
             reservation cache, usage tracker, and host feature store
             stay hot; promotion only needs the failover reconcile.
  shard      `ShardMap` — instance group -> owning replica (stable
             CRC32), the active-active traffic partition; per-group
             solves commute (PR 4 domain partitioning), so sharded
             decisions are byte-identical per group to one replica.
  replica    `ReplicaRuntime` (role state machine: standby -> leader via
             `promote()`, heartbeat loop, /debug/ha surface) and
             `ShardedServingGroup` (N active replicas over one backend,
             wrong-shard requests forwarded to the owner).
"""

from spark_scheduler_tpu.ha.lease import (  # noqa: F401
    BackendLeaseStore,
    FencingError,
    FileLeaseStore,
    LeaseManager,
    LeaseRecord,
)
from spark_scheduler_tpu.ha.fencing import FencedBackend  # noqa: F401
from spark_scheduler_tpu.ha.shard import ShardMap  # noqa: F401
from spark_scheduler_tpu.ha.standby import StandbyTailer  # noqa: F401
from spark_scheduler_tpu.ha.replica import (  # noqa: F401
    ReplicaRuntime,
    ShardedServingGroup,
)

__all__ = [
    "BackendLeaseStore",
    "FencedBackend",
    "FencingError",
    "FileLeaseStore",
    "LeaseManager",
    "LeaseRecord",
    "ReplicaRuntime",
    "ShardMap",
    "ShardedServingGroup",
    "StandbyTailer",
]
