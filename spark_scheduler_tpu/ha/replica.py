"""ReplicaRuntime — one scheduler replica's role state machine.

A replica is a full SchedulerApp built over a FencedBackend, plus:

  - a LeaseManager (election mode) whose heartbeat renews leadership or
    detects deposition;
  - a StandbyTailer keeping its caches/feature store hot in every role;
  - `promote()` — the standby -> leader transition: run the failover
    reconciler against observed pods (the reference's new-leader rebuild,
    failover.go:35-72), warm the feature-store snapshot, and only then
    mark the replica serving. Warm caches make this a reconcile, not a
    state rebuild (bench.py ha_failover measures the gap).

`ShardedServingGroup` composes N replicas over ONE shared backend into
the active-active topology: traffic shards by instance group (ShardMap),
replica 0 additionally holds the lease and owns reconciliation, and
wrong-shard requests are forwarded to their owner so kube-scheduler can
hit any replica.
"""

from __future__ import annotations

import threading
import time

from spark_scheduler_tpu.ha.fencing import FencedBackend
from spark_scheduler_tpu.ha.lease import BackendLeaseStore, FencingError, LeaseManager
from spark_scheduler_tpu.ha.shard import ShardMap
from spark_scheduler_tpu.ha.standby import StandbyTailer

ROLE_STANDBY = "standby"
ROLE_LEADER = "leader"
ROLE_ACTIVE = "active"  # sharded-group member serving its shard
ROLE_DEPOSED = "deposed"

SERVING_ROLES = frozenset({ROLE_LEADER, ROLE_ACTIVE})


class ReplicaRuntime:
    def __init__(
        self,
        replica_id: str,
        app,
        lease: LeaseManager | None = None,
        tailer: StandbyTailer | None = None,
        telemetry=None,
        heartbeat_s: float | None = None,
        clock=time.time,
    ):
        self.replica_id = replica_id
        self.app = app
        self.lease = lease
        self.tailer = tailer
        self.telemetry = telemetry
        self._clock = clock
        # Heartbeat well inside the TTL: three chances to renew before a
        # standby may take over (the classic lease discipline).
        self.heartbeat_s = heartbeat_s or (
            lease.ttl_s / 3.0 if lease is not None else 1.0
        )
        self.role = ROLE_STANDBY
        self.last_promotion_ms: float | None = None
        self.last_reconcile_ms: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._dead = False  # kill() flips it: chaos-crashed, ticks no-op
        if telemetry is not None:
            telemetry.on_role(self.role)

    # -- election ----------------------------------------------------------

    def run_election_once(self) -> str:
        """One deterministic election tick (the heartbeat thread calls this
        on its interval; tests and the chaos soak drive it by hand):
        leaders renew (a failed renew = deposed, serving stops), standbys
        poll the lease and promote on takeover. Returns the role after the
        tick."""
        if self._dead or self.lease is None:
            return self.role
        if self.role == ROLE_LEADER:
            if not self.lease.renew():
                self._set_role(ROLE_DEPOSED)
        elif self.role in (ROLE_STANDBY, ROLE_DEPOSED):
            if self.role == ROLE_DEPOSED:
                # Deposition is an event, not a terminal state: serving
                # stopped the tick the renew failed; from the next tick on
                # the replica rejoins the election as a warm standby. (A
                # single transient lease-store read failure must not
                # permanently halve the fleet.)
                self._set_role(ROLE_STANDBY)
            # Cross-process WAL deployments: pull the leader's appended
            # records before judging the lease, so promotion reconciles
            # against current state (in-process backends have no poll_log
            # — the event bus already delivered everything).
            poll = getattr(self.app.backend, "poll_log", None)
            if poll is not None:
                poll()
            if self.lease.try_acquire():
                self.promote()
            else:
                # Keep the host feature arrays warm every heartbeat: the
                # promotion-time snapshot then pays O(since-last-tick),
                # not an O(nodes) roster walk accumulated over the whole
                # standby life.
                try:
                    self.app.extender.features.snapshot()
                except Exception:
                    pass  # a torn mid-churn snapshot retries next tick
        if self.telemetry is not None and self.lease is not None:
            st = self.lease.state()
            self.telemetry.on_lease(st["lease_epoch"], st["lease_age_s"])
            if self.tailer is not None:
                self.telemetry.on_tailed(self.tailer.applied)
        return self.role

    def promote(self) -> dict:
        """Standby -> leader: reconcile durable state against observed pods
        BEFORE serving (a takeover IS a leader change), warm the feature
        snapshot, then flip the role. Returns the reconcile summary."""
        t0 = time.perf_counter()
        poll = getattr(self.app.backend, "poll_log", None)
        if poll is not None:
            poll()  # final catch-up before we own the state
        become_writer = getattr(self.app.backend, "promote_to_writer", None)
        if become_writer is not None:
            become_writer()
        r0 = time.perf_counter()
        summary = self.app.reconciler.sync_resource_reservations_and_demands()
        reconcile_ms = (time.perf_counter() - r0) * 1e3
        # First serving window must not pay the roster walk: snapshot now.
        self.app.extender.features.snapshot()
        # The promotion reconcile covers the gap heuristic's reason to
        # exist for this leadership term.
        self.app.extender._last_request = self.app.extender._clock()
        self._set_role(ROLE_LEADER)
        self.last_reconcile_ms = reconcile_ms
        self.last_promotion_ms = (time.perf_counter() - t0) * 1e3
        if self.telemetry is not None:
            self.telemetry.on_promotion(self.last_promotion_ms, reconcile_ms)
        return summary if isinstance(summary, dict) else {}

    def _set_role(self, role: str) -> None:
        self.role = role
        if self.telemetry is not None:
            self.telemetry.on_role(role)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Spawn the heartbeat/election thread (idempotent)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.heartbeat_s):
                try:
                    self.run_election_once()
                except Exception:
                    # A flaky lease store read must not kill the election
                    # loop; the next tick retries (an expired lease is the
                    # failure detector, not this thread's liveness).
                    pass

        self._thread = threading.Thread(
            target=_loop, daemon=True, name=f"ha-heartbeat-{self.replica_id}"
        )
        self._thread.start()

    def stop(self) -> None:
        """Graceful shutdown: stop heartbeating and expire the lease NOW so
        a standby promotes without waiting out the TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.lease is not None and self.role == ROLE_LEADER:
            self.lease.release()
        if self.role in SERVING_ROLES:
            self._set_role(ROLE_STANDBY)

    def kill(self) -> None:
        """Chaos crash: heartbeats stop mid-lease, NOTHING is released —
        the lease expires by TTL and the successor's takeover bumps the
        fencing epoch, exactly like a SIGKILLed process."""
        self._dead = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- serving -----------------------------------------------------------

    def is_serving(self) -> bool:
        return not self._dead and self.role in SERVING_ROLES

    def state(self) -> dict:
        out = {
            "replica": self.replica_id,
            "role": self.role,
            "serving": self.is_serving(),
            "promotion_ms": self.last_promotion_ms,
            "reconcile_ms": self.last_reconcile_ms,
        }
        if self.lease is not None:
            out["lease"] = self.lease.state()
        if self.tailer is not None:
            out["tailer"] = self.tailer.stats()
        return out


def build_replica(
    shared_backend,
    replica_id: str,
    *,
    config=None,
    lease: LeaseManager | None = None,
    gate=None,
    metrics=None,
    events=None,
    waste=None,
    clock=None,
    registry=None,
) -> ReplicaRuntime:
    """Wire one replica over a shared backend: lease (unless a custom
    fencing `gate` is supplied — the sharded group does that), fenced
    backend, full SchedulerApp, standby tailer, telemetry."""
    import time as _time

    from spark_scheduler_tpu.observability import HATelemetry
    from spark_scheduler_tpu.server.app import build_scheduler_app

    clock = clock or _time.time
    ttl = getattr(config, "ha_lease_ttl_s", 3.0) if config is not None else 3.0
    if lease is None and gate is None:
        lease = LeaseManager(
            BackendLeaseStore(shared_backend), replica_id, ttl_s=ttl, clock=clock
        )
    telemetry = HATelemetry(
        registry if registry is not None
        else (metrics.registry if metrics is not None else None),
        replica=replica_id,
    )
    fenced = FencedBackend(
        shared_backend,
        gate if gate is not None else lease.check_fence,
        on_reject=lambda _kind: telemetry.on_fenced_reject(),
    )
    app = build_scheduler_app(
        fenced, config, metrics=metrics, events=events, waste=waste, clock=clock
    )
    if lease is not None:
        app.extender.ha_lease = lease
    tailer = StandbyTailer(app)
    heartbeat = (
        getattr(config, "ha_heartbeat_s", None) if config is not None else None
    )
    return ReplicaRuntime(
        replica_id, app, lease=lease, tailer=tailer, telemetry=telemetry,
        heartbeat_s=heartbeat, clock=clock,
    )


class ShardedServingGroup:
    """N active replicas over one shared backend, traffic sharded by
    instance group. Replica 0 holds the lease (it owns promotion-time and
    gap-heuristic reconciliation); every member serves its own groups'
    predicates, and a request landing on the wrong member is FORWARDED to
    the owner (the in-process analog of an HTTP redirect) so the client
    never sees a gap. Per-group decisions are byte-identical to a single
    unsharded replica: group domains are disjoint (pods pin their
    instance group), so per-group solves commute — the property PR 4's
    domain partitioning established and the equivalence test pins."""

    def __init__(
        self,
        shared_backend,
        n_replicas: int,
        *,
        config_factory=None,
        clock=None,
        registry=None,
    ):
        import time as _time

        self.shard_map = ShardMap(n_replicas)
        self.forwarded = 0
        self._members_live = [True] * n_replicas
        clock = clock or _time.time
        self.replicas: list[ReplicaRuntime] = []
        for i in range(n_replicas):
            config = config_factory(i) if config_factory is not None else None
            if i == 0:
                runtime = build_replica(
                    shared_backend, f"replica-{i}", config=config,
                    clock=clock, registry=registry,
                )
            else:
                runtime = build_replica(
                    shared_backend, f"replica-{i}", config=config,
                    gate=self._member_gate(i), clock=clock, registry=registry,
                )
                # Reconciliation belongs to the lease holder (replica 0);
                # a member's request-gap resync would race it AND be
                # fenced — disable the heuristic outright.
                runtime.app.extender._config.resync_gap_seconds = float("inf")
            self.replicas.append(runtime)
        self._label = self.replicas[0].app.extender._config.instance_group_label

    def _member_gate(self, index: int):
        def gate() -> None:
            if not self._members_live[index]:
                raise FencingError(
                    f"fenced write rejected: replica-{index} was removed "
                    "from the serving group"
                )

        return gate

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Elect replica 0, promote it (reconcile-before-serve), and mark
        every other member active for its shard."""
        leader = self.replicas[0]
        assert leader.lease is not None and leader.lease.try_acquire()
        leader.promote()
        for r in self.replicas[1:]:
            r._set_role(ROLE_ACTIVE)

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()
            r.app.stop()

    def remove_member(self, index: int) -> None:
        """Fence a member OUT of the group (crash or drain): its shard's
        groups remap onto the survivors, it stops serving, and any commit
        it still has in flight is rejected by its gate instead of racing
        the new owner — the member-group analog of the lease's fencing
        epoch. Replica 0 cannot leave this way: it holds the lease, so its
        death is a leader failover (the chaos soak's territory)."""
        if index == 0:
            raise ValueError(
                "replica 0 holds the lease; its death is a leader "
                "failover, not a member drain"
            )
        self._members_live[index] = False
        self.shard_map.remove(index)
        self.replicas[index]._set_role(ROLE_STANDBY)

    # -- routing -----------------------------------------------------------

    def owner_index(self, pod) -> int:
        from spark_scheduler_tpu.core.sparkpods import find_instance_group

        return self.shard_map.owner(find_instance_group(pod, self._label) or "")

    def predicate(self, args, via: int = 0):
        """Serve one predicate as replica `via` received it: owner serves
        directly, non-owners forward."""
        idx = self.owner_index(args.pod)
        if idx != via:
            self.forwarded += 1
        return self.replicas[idx].app.extender.predicate(args)

    def predicate_batch(self, args_list, via: int = 0):
        """Serve a window: split by owning shard (per-group arrival order
        preserved), serve each owner's sub-window through its own
        extender, and reassemble results in request order."""
        by_owner: dict[int, list[int]] = {}
        for i, a in enumerate(args_list):
            by_owner.setdefault(self.owner_index(a.pod), []).append(i)
        results = [None] * len(args_list)
        for idx, positions in by_owner.items():
            if idx != via:
                self.forwarded += len(positions)
            sub = [args_list[p] for p in positions]
            for p, res in zip(
                positions, self.replicas[idx].app.extender.predicate_batch(sub)
            ):
                results[p] = res
        return results

    def state(self) -> dict:
        return {
            "replicas": [r.state() for r in self.replicas],
            "forwarded": self.forwarded,
            "shard_map": self.shard_map.describe(),
        }
