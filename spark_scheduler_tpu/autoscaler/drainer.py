"""Scale-down drainer: cordon + remove nodes idle past a TTL.

Two-phase, like a real node-group scale-down: a node that has been idle
(no hard reservation, no soft reservation, no pod bound to it) for
`idle_ttl_s` is CORDONED first (a replacement Node object with
unschedulable=True, the watch-path idiom — the solver's candidate mask
excludes cordoned nodes, so no new gang can land while the drain is
pending); on a LATER pass, if it is still idle, it is deleted. A node that
picks up work between the two phases is uncordoned and forgotten.

The refusal rule is absolute: reservation_manager (hard slots) and the
soft-reservation store are the source of truth, and a node either of them
names is never cordoned or deleted, whatever its idle age. By default only
nodes the provisioner created (PROVISIONED_BY_LABEL) are eligible, so the
static fleet — cordoned by an operator or not — is never touched. An
eligible node found cordoned outside this drainer's memory (a pre-restart
drain pass whose in-memory phase state died with the process, or an
operator cordoning elastic capacity) is re-adopted: idle-tracked and
removed only after a full fresh TTL of staying reservation-free.
"""

from __future__ import annotations

import dataclasses

from spark_scheduler_tpu.autoscaler.provisioner import (
    PROVISIONED_BY_LABEL,
    PROVISIONER_NAME,
)
from spark_scheduler_tpu.store.backend import BackendError


class ScaleDownDrainer:
    def __init__(
        self,
        backend,
        rr_cache,
        soft_store,
        idle_ttl_s: float = 300.0,
        clock=None,
        drain_static_fleet: bool = False,
        census=None,
    ):
        import time as _time

        self._backend = backend
        self._rr_cache = rr_cache
        self._soft_store = soft_store
        self.idle_ttl_s = idle_ttl_s
        self._clock = clock or _time.time
        self._drain_static = drain_static_fleet
        # Incremental control-loop census (core/census.py): when attached,
        # a drain pass reads the resident node mirror / busy refcounts
        # (O(eligible fleet) per pass, O(1) busy checks) instead of
        # re-listing every node, pod, and reservation. None = the
        # reference's full walks.
        self._census = census
        self._idle_since: dict[str, float] = {}
        # Nodes WE cordoned, pending deletion next pass. Operator cordons
        # are not in this map and are never uncordoned by us.
        self._pending_drain: set[str] = set()

    # -- busy-node census ----------------------------------------------------

    def reserved_node_names(self) -> set[str]:
        """Every node a hard OR soft reservation names — the never-drain set."""
        if self._census is not None:
            return self._census.reserved_node_names()
        used: set[str] = set()
        for rr in self._rr_cache.list():
            for res in rr.spec.reservations.values():
                used.add(res.node)
        for sr in self._soft_store.get_all_copy().values():
            for r in sr.reservations.values():
                used.add(r.node)
        return used

    def _busy_nodes(self) -> set[str]:
        busy = self.reserved_node_names()
        for pod in self._backend.list("pods"):
            if pod.node_name and not pod.is_terminated():
                busy.add(pod.node_name)
        return busy

    # -- the pass ------------------------------------------------------------

    def run_once(self, now: float | None = None) -> list[str]:
        """One drain pass; returns the names of nodes deleted this pass."""
        if now is None:
            now = self._clock()
        census = self._census
        if census is not None:
            # Census pass: scan only the eligible (provisioned) fleet —
            # at the million-node tier the static fleet never enters the
            # loop — with O(1) busy checks against the resident refcounts.
            # Identical decisions to the full-walk pass (the census is the
            # same sources, event-maintained).
            busy = None
            live = (
                census.nodes_view()
                if self._drain_static
                else census.eligible_view()
            )
        else:
            busy = self._busy_nodes()
            live = {n.name: n for n in self._backend.list_nodes()}
        drained: list[str] = []
        # Forget tracking state for nodes that disappeared out from under us.
        for name in list(self._idle_since):
            if name not in live:
                del self._idle_since[name]
        self._pending_drain &= set(live)

        for name, node in live.items():
            eligible = self._drain_static or (
                node.labels.get(PROVISIONED_BY_LABEL) == PROVISIONER_NAME
            )
            if not eligible:
                continue
            is_busy = census.is_busy(name) if busy is None else name in busy
            if is_busy:
                # Busy again: reset the idle clock; if we had cordoned it
                # for drain, hand it back (a reservation raced the cordon).
                # On a failed uncordon write (rv conflict with concurrent
                # ingestion) the node STAYS in _pending_drain so the
                # uncordon retries next pass against the re-listed object.
                self._idle_since.pop(name, None)
                if name in self._pending_drain and self._mutate(
                    "update", dataclasses.replace(node, unschedulable=False)
                ):
                    self._pending_drain.discard(name)
                continue
            if name in self._pending_drain:
                # Phase 2: still idle after a full pass cordoned — remove.
                if self._mutate("delete", node):
                    drained.append(name)
                self._pending_drain.discard(name)
                self._idle_since.pop(name, None)
                continue
            if node.unschedulable:
                # An eligible (provisioned) node cordoned outside this
                # drainer's memory: a pre-restart drain pass (the durable
                # backend persists nodes; _pending_drain doesn't survive),
                # or an operator cordoning elastic capacity. Re-adopt it —
                # idle-track and remove only after a FULL fresh TTL of
                # staying reservation-free, never instantly. Static-fleet
                # cordons are never seen here (not eligible).
                if now - self._idle_since.setdefault(name, now) >= self.idle_ttl_s:
                    self._pending_drain.add(name)
                continue
            first_idle = self._idle_since.setdefault(name, now)
            if now - first_idle >= self.idle_ttl_s:
                # Phase 1: cordon with a REPLACEMENT object (watch-path
                # idiom; in-place mutation would defeat the solver's
                # identity-based arena sync).
                if self._mutate(
                    "update", dataclasses.replace(node, unschedulable=True)
                ):
                    self._pending_drain.add(name)
        return drained

    def _mutate(self, verb: str, node) -> bool:
        """Node write tolerant of concurrent topology churn: a node updated
        or deleted out from under a drain pass just falls out of this pass;
        the next one re-censuses."""
        try:
            if verb == "delete":
                self._backend.delete("nodes", "", node.name)
            else:
                self._backend.update("nodes", node)
            return True
        except BackendError:
            return False
