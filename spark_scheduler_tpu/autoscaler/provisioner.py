"""Node provisioner: turns demand units into registered Nodes.

The slot a cloud node-group API would fill in a real deployment: given the
units of one or more Demands, compute how many template-shaped nodes are
needed (first-fit-decreasing over empty template bins — the same shape the
external autoscaler's node-group estimator runs) and register them through
the cluster backend, labeled with the demand's instance group and a zone.

Zone policy (v1alpha2 semantics, models/demands.py):
  - `spec.zone` set (executor reschedule affinity) -> every node lands there;
  - `enforce_single_zone_scheduling` -> one zone, chosen round-robin per
    provisioning call, reported back as `fulfilled_zone`;
  - otherwise nodes spread round-robin across the configured zones.

Provisioned nodes carry PROVISIONED_BY_LABEL so the scale-down drainer can
tell elastic capacity from the static fleet.
"""

from __future__ import annotations

import itertools

from spark_scheduler_tpu.models.demands import DemandUnit
from spark_scheduler_tpu.models.kube import DEFAULT_ZONE, ZONE_LABEL, Node
from spark_scheduler_tpu.models.resources import Resources

PROVISIONED_BY_LABEL = "spark-scheduler/provisioned-by"
PROVISIONER_NAME = "elastic-autoscaler"


def nodes_needed(units: list[DemandUnit], template: Resources) -> int | None:
    """Template-node count that fits every unit instance, by first-fit-
    decreasing (sorted by cpu, then memory) over empty template bins.
    Returns None when any single instance exceeds an empty template node —
    no amount of scale-up can fulfill that demand."""
    instances: list[Resources] = []
    for u in units:
        for _ in range(u.count):
            instances.append(u.resources)
    for r in instances:
        if (
            r.cpu_milli > template.cpu_milli
            or r.mem_kib > template.mem_kib
            or r.gpu_milli > template.gpu_milli
        ):
            return None
    instances.sort(key=lambda r: (r.cpu_milli, r.mem_kib, r.gpu_milli), reverse=True)
    bins: list[Resources] = []  # free space per new node
    for r in instances:
        for free in bins:
            if (
                r.cpu_milli <= free.cpu_milli
                and r.mem_kib <= free.mem_kib
                and r.gpu_milli <= free.gpu_milli
            ):
                free.sub(r)
                break
        else:
            free = template.copy()
            free.sub(r)
            bins.append(free)
    return len(bins)


class NodeProvisioner:
    def __init__(
        self,
        backend,
        instance_group_label: str,
        node_template: Resources,
        zones: list[str] | None = None,
        node_prefix: str = "autoscaled",
        clock=None,
    ):
        import time as _time

        self._backend = backend
        self._ig_label = instance_group_label
        self.node_template = node_template
        self._zones = list(zones) if zones else [DEFAULT_ZONE]
        self._prefix = node_prefix
        self._clock = clock or _time.time
        self._seq = itertools.count()
        self._zone_rr = itertools.count()

    def nodes_needed(self, units: list[DemandUnit]) -> int | None:
        return nodes_needed(units, self.node_template)

    def pick_zone(self) -> str:
        return self._zones[next(self._zone_rr) % len(self._zones)]

    def provision(
        self, count: int, instance_group: str, zone: str | None
    ) -> list[Node]:
        """Register `count` template nodes. A fixed `zone` pins every node;
        zone=None spreads round-robin across the configured zones."""
        created: list[Node] = []
        now = self._clock()
        for _ in range(count):
            z = zone if zone is not None else self.pick_zone()
            node = Node(
                name=f"{self._prefix}-{next(self._seq)}",
                allocatable=self.node_template.copy(),
                labels={
                    ZONE_LABEL: z,
                    self._ig_label: instance_group,
                    PROVISIONED_BY_LABEL: PROVISIONER_NAME,
                },
                creation_timestamp=now,
            )
            self._backend.add_node(node)
            created.append(node)
        return created
