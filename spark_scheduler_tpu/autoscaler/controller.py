"""ElasticAutoscaler: the control loop that closes the Demand loop.

One `run_once()` pass (the loop body, also the deterministic test/soak
hook):

  1. take ownership of newly-created demands (phase "" -> "pending");
     cap-limited "cannot-fulfill" demands whose units still fit a template
     node are re-acked to "pending" once headroom exists, so a capped gang
     is never starved after capacity frees;
  2. group pending demands by (instance-group, zone), oldest first, and
     decide scale-up counts per group by packing the group's units into
     template-node bins (provisioner.nodes_needed);
  3. provision nodes for every group that fits under the max-cluster-size
     cap and flip its demands "pending" -> "fulfilled" (recording
     demand-to-fulfilled latency); demands that cannot fit — a unit larger
     than a template node, or the cap reached — flip to "cannot-fulfill";
  4. run the scale-down drainer.

Phase flips are written straight to the backend with a REPLACEMENT object,
exactly how the external autoscaler would write the status subresource: the
scheduler's demand cache fast-forwards resourceVersions on watch and the
waste reporter's on-update subscription observes the fulfillment
(server/app.py), so nothing downstream can tell this autoscaler from the
reference's external one.

`start()` runs the loop on a daemon thread with a demand-add wakeup (gated
on the Demand CRD existing, same as every other demand consumer);
`run_once()` stays callable without any thread for tests, the elastic soak,
and the bench.
"""

from __future__ import annotations

import dataclasses
import threading

from spark_scheduler_tpu.autoscaler.drainer import ScaleDownDrainer
from spark_scheduler_tpu.autoscaler.metrics import AutoscalerMetrics
from spark_scheduler_tpu.autoscaler.provisioner import NodeProvisioner
from spark_scheduler_tpu.models.demands import (
    PHASE_CANNOT_FULFILL,
    PHASE_EMPTY,
    PHASE_FULFILLED,
    PHASE_PENDING,
    Demand,
)
from spark_scheduler_tpu.store.backend import BackendError


class ElasticAutoscaler:
    def __init__(
        self,
        backend,
        provisioner: NodeProvisioner,
        drainer: ScaleDownDrainer,
        max_cluster_size: int = 1000,
        poll_interval_s: float = 2.0,
        metrics: AutoscalerMetrics | None = None,
        recorder=None,
        clock=None,
        census=None,
    ):
        import time as _time

        self._backend = backend
        self.provisioner = provisioner
        self.drainer = drainer
        # Incremental census (core/census.py): cluster size becomes an
        # O(1) counter read instead of materializing the full node list
        # (three times per pass). None = the reference's list_nodes walk.
        self._census = census
        self.max_cluster_size = max_cluster_size
        self._poll_interval_s = poll_interval_s
        self.metrics = metrics or AutoscalerMetrics()
        # FlightRecorder: fulfilled demands annotate the denied decision
        # that created them, closing the denial -> scale-up story on the
        # record an operator pulls from GET /debug/decisions.
        self._recorder = recorder
        self._clock = clock or _time.time
        # (namespace, name) -> first time this controller saw the demand;
        # fallback latency anchor when the creator didn't stamp
        # creationTimestamp into metadata_extra.
        self._first_seen: dict[tuple[str, str], float] = {}
        self._wakeup = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._attached = False
        # Failed-pass backoff (ISSUE 9 satellite): the loop used to retry
        # a failing pass at full poll cadence forever; now consecutive
        # failures back off exponentially (capped, full jitter) and the
        # count is a gauge so a wedged controller is visible, not silent.
        from spark_scheduler_tpu.faults.retry import RetryPolicy

        self.retry_policy = RetryPolicy(
            max_attempts=None,
            base_delay_s=poll_interval_s,
            multiplier=2.0,
            max_delay_s=max(30.0, poll_interval_s),
        )
        self.consecutive_failures = 0

    # -- lifecycle -----------------------------------------------------------

    def attach(self) -> None:
        """Subscribe the demand-add wakeup. Called via the Demand-CRD
        watcher's on_ready (demands may appear any time after startup)."""
        if self._attached:
            return
        self._attached = True
        self._backend.subscribe("demands", on_add=lambda d: self._wakeup.set())

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.is_set():
                self._wakeup.wait(self._poll_interval_s)
                self._wakeup.clear()
                if self._stop.is_set():
                    return
                try:
                    self.run_once()
                    self._note_pass_ok()
                except Exception as exc:
                    from spark_scheduler_tpu.tracing import svc1log

                    pause = self._note_pass_failed()
                    svc1log().warn(
                        "autoscaler pass failed; backing off",
                        error=f"{type(exc).__name__}: {exc}",
                        consecutiveFailures=self.consecutive_failures,
                        backoffS=round(pause, 3),
                    )
                    # On top of the poll wait: a failing backend is
                    # probed at the ladder's cadence, and the demand-add
                    # wakeup is cleared below so it cannot bypass it.
                    self._stop.wait(pause)
                    self._wakeup.clear()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="elastic-autoscaler"
        )
        self._thread.start()

    def _note_pass_ok(self) -> None:
        if self.consecutive_failures:
            self.consecutive_failures = 0
            self.metrics.set_consecutive_failures(0)

    def _note_pass_failed(self) -> float:
        """Count one failed pass; returns the backoff to wait before the
        next attempt (exponential in the failure streak)."""
        delay = self.retry_policy.delay(self.consecutive_failures)
        self.consecutive_failures += 1
        self.metrics.set_consecutive_failures(self.consecutive_failures)
        return delay

    def stop(self) -> None:
        self._stop.set()
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_interval_s + 1)
            self._thread = None

    # -- the pass ------------------------------------------------------------

    def run_once(self, now: float | None = None) -> dict:
        """One full control-loop pass. Returns a summary dict:
        {nodes_added, drained, fulfilled, unfulfillable}."""
        if now is None:
            now = self._clock()
        summary = {"nodes_added": 0, "drained": [], "fulfilled": 0, "unfulfillable": 0}

        # 1. ownership: "" -> pending (the external autoscaler's ack).
        # Cap-limited refusals are retried — a cannot-fulfill demand goes
        # back to pending once its OWN node count fits under the cap
        # (drained capacity or a raised cap), so a capped gang is never
        # starved forever; requiring full fit (not just any headroom)
        # keeps a still-too-big demand from oscillating cannot-fulfill ->
        # pending -> cannot-fulfill with two status writes per pass.
        # Unit-infeasible demands (a unit larger than an empty template
        # node) stay terminal.
        cluster_size = self._cluster_size()
        pending: list[Demand] = []
        live: set[tuple[str, str]] = set()
        for d in self._backend.list("demands"):
            key = (d.namespace, d.name)
            live.add(key)
            if d.status.phase in (PHASE_EMPTY, PHASE_CANNOT_FULFILL):
                if d.status.phase == PHASE_CANNOT_FULFILL:
                    needed = self.provisioner.nodes_needed(d.spec.units)
                    if (
                        needed is None
                        or cluster_size + needed > self.max_cluster_size
                    ):
                        continue
                marked = self._set_phase(d, PHASE_PENDING, now)
                if marked is not None:
                    self._first_seen.setdefault(key, now)
                    pending.append(marked)
            elif d.status.phase == PHASE_PENDING:
                self._first_seen.setdefault(key, now)
                pending.append(d)
        # Forget latency anchors for demands that no longer exist (GC'd,
        # deleted on successful schedule).
        for key in list(self._first_seen):
            if key not in live:
                del self._first_seen[key]

        # 2. group by (instance-group, pinned zone), oldest demand first.
        groups: dict[tuple[str, str | None], list[Demand]] = {}
        for d in sorted(
            pending, key=lambda d: self._first_seen.get((d.namespace, d.name), now)
        ):
            zone = d.spec.zone or None
            groups.setdefault((d.spec.instance_group, zone), []).append(d)

        for (instance_group, zone), demands in groups.items():
            # Impossible demands (a unit larger than an empty template
            # node) can never be fulfilled by scale-up: fail them now so
            # they don't poison the group's bin-pack.
            feasible: list[Demand] = []
            for d in demands:
                if self.provisioner.nodes_needed(d.spec.units) is None:
                    self._finish(d, PHASE_CANNOT_FULFILL, None, now)
                    summary["unfulfillable"] += 1
                else:
                    feasible.append(d)
            if not feasible:
                continue
            # Largest oldest-first prefix that fits under the cap: demands
            # beyond it are unfulfillable at the current max cluster size.
            # Prefix node count is monotone in prefix length (a superset of
            # units never packs into fewer bins), so binary-search the cut
            # instead of re-packing per one-demand decrement.
            cluster_size = self._cluster_size()
            units = lambda ds: [u for d in ds for u in d.spec.units]  # noqa: E731
            lo, hi, needed = 0, len(feasible), 0
            while lo < hi:
                mid = (lo + hi + 1) // 2
                mid_needed = self.provisioner.nodes_needed(units(feasible[:mid]))
                if cluster_size + mid_needed <= self.max_cluster_size:
                    lo, needed = mid, mid_needed
                else:
                    hi = mid - 1
            take = lo
            for d in feasible[take:]:
                self._finish(d, PHASE_CANNOT_FULFILL, None, now)
                summary["unfulfillable"] += 1
            if take == 0:
                continue
            # Zone pin: the demand's own zone, else one round-robin zone
            # when any demand in the group enforces single-zone placement.
            pinned = zone
            if pinned is None and any(
                d.spec.enforce_single_zone_scheduling for d in feasible[:take]
            ):
                pinned = self.provisioner.pick_zone()
            created = self.provisioner.provision(needed, instance_group, pinned)
            summary["nodes_added"] += len(created)
            self.metrics.on_nodes_added(instance_group, len(created))
            for d in feasible[:take]:
                self._finish(d, PHASE_FULFILLED, pinned, now)
                summary["fulfilled"] += 1

        # 4. scale down.
        drained = self.drainer.run_once(now)
        summary["drained"] = drained
        if drained:
            self.metrics.on_nodes_drained(len(drained))
        self.metrics.set_cluster_size(self._cluster_size())
        return summary

    def _cluster_size(self) -> int:
        if self._census is not None:
            return self._census.node_count()
        return len(self._backend.list_nodes())

    # -- phase transitions ---------------------------------------------------

    def _set_phase(
        self, demand: Demand, phase: str, now: float, fulfilled_zone: str | None = None
    ) -> Demand | None:
        """Flip a demand's phase with a replacement object against the
        backend (the external-autoscaler write path). Returns the updated
        object, or None when the demand was deleted/rewritten concurrently
        (the next pass re-reads)."""
        cur = self._backend.get("demands", demand.namespace, demand.name)
        if cur is None:
            return None
        updated = dataclasses.replace(cur)
        updated.status = dataclasses.replace(
            cur.status,
            phase=phase,
            last_transition_time=now,
            fulfilled_zone=fulfilled_zone or cur.status.fulfilled_zone,
        )
        try:
            return self._backend.update("demands", updated)
        except BackendError:
            return None

    def _finish(
        self, demand: Demand, phase: str, fulfilled_zone: str | None, now: float
    ) -> None:
        if self._set_phase(demand, phase, now, fulfilled_zone) is None:
            return
        key = (demand.namespace, demand.name)
        if phase == PHASE_FULFILLED:
            anchor = demand.metadata_extra.get("creationTimestamp")
            try:
                anchor = float(anchor)
            except (TypeError, ValueError):
                # A demand ingested off the wire carries an RFC3339 string
                # here (conversion keeps unknown metadata verbatim) — not
                # this clock's epoch either way; anchor on first-seen.
                anchor = self._first_seen.get(key, now)
            self.metrics.on_demand_fulfilled(
                demand.spec.instance_group, max(0.0, now - anchor)
            )
            if self._recorder is not None:
                from spark_scheduler_tpu.models.demands import (
                    DEMAND_NAME_PREFIX,
                )

                pod_name = demand.name
                if pod_name.startswith(DEMAND_NAME_PREFIX):
                    pod_name = pod_name[len(DEMAND_NAME_PREFIX):]
                self._recorder.annotate_demand_fulfilled(
                    demand.namespace, pod_name, max(0.0, now - anchor), now
                )
        else:
            self.metrics.on_demand_unfulfillable(demand.spec.instance_group)
        self._first_seen.pop(key, None)
