"""Elastic autoscaler — the in-process consumer of the Demand surface.

The reference emits Demand CRDs for an EXTERNAL cluster autoscaler and stops
there (internal/extender/demand.go); this subsystem closes the loop inside
the process: a controller watches pending demands through the existing
backend/reflector surface, a provisioner registers simulated nodes (honoring
v1alpha2 zone affinity) and flips demand phases pending -> fulfilled (or
cannot-fulfill at the max-cluster-size cap), and a scale-down drainer
cordons + removes nodes idle past a TTL — never a node holding a hard or
soft reservation (reservation_manager + soft_reservations are the source of
truth for that refusal).
"""

from spark_scheduler_tpu.autoscaler.controller import ElasticAutoscaler
from spark_scheduler_tpu.autoscaler.drainer import ScaleDownDrainer
from spark_scheduler_tpu.autoscaler.metrics import AutoscalerMetrics
from spark_scheduler_tpu.autoscaler.provisioner import (
    PROVISIONED_BY_LABEL,
    PROVISIONER_NAME,
    NodeProvisioner,
)

__all__ = [
    "AutoscalerMetrics",
    "ElasticAutoscaler",
    "NodeProvisioner",
    "PROVISIONED_BY_LABEL",
    "PROVISIONER_NAME",
    "ScaleDownDrainer",
]
