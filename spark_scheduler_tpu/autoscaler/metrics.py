"""Autoscaler metric families, backed by the tagged registry.

Same shape as metrics/scheduler_metrics.py: `foundry.spark.scheduler.*`
names so the series land next to the scheduler's own on dashboards. The
scale-up latency histogram additionally keeps a bounded raw-sample list so
the bench can report exact p50/p99 (the registry histogram's percentiles
are reservoir-bounded approximations past its capacity).
"""

from __future__ import annotations

import threading

from spark_scheduler_tpu.metrics.registry import MetricRegistry

SCALE_UP_LATENCY = "foundry.spark.scheduler.autoscaler.scaleup.latency"
NODES_ADDED = "foundry.spark.scheduler.autoscaler.nodes.added"
NODES_DRAINED = "foundry.spark.scheduler.autoscaler.nodes.drained"
DEMANDS_FULFILLED = "foundry.spark.scheduler.autoscaler.demands.fulfilled"
DEMANDS_UNFULFILLABLE = "foundry.spark.scheduler.autoscaler.demands.unfulfillable"
CLUSTER_SIZE = "foundry.spark.scheduler.autoscaler.cluster.size"
CONSECUTIVE_FAILURES = (
    "foundry.spark.scheduler.autoscaler.consecutive.failures"
)

TAG_INSTANCE_GROUP = "instance-group"

_MAX_RAW_SAMPLES = 8192


class AutoscalerMetrics:
    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()
        self._lock = threading.Lock()
        # Raw demand-to-fulfilled latencies (seconds) for exact percentile
        # reporting in bench.py; bounded so a long-lived server can't grow
        # it without bound.
        self._scaleup_samples: list[float] = []

    # -- hooks ---------------------------------------------------------------

    def on_nodes_added(self, instance_group: str, count: int) -> None:
        self.registry.counter(
            NODES_ADDED, **{TAG_INSTANCE_GROUP: instance_group}
        ).inc(count)

    def on_nodes_drained(self, count: int) -> None:
        self.registry.counter(NODES_DRAINED).inc(count)

    def on_demand_fulfilled(self, instance_group: str, latency_s: float) -> None:
        self.registry.counter(
            DEMANDS_FULFILLED, **{TAG_INSTANCE_GROUP: instance_group}
        ).inc()
        self.registry.histogram(SCALE_UP_LATENCY).update(latency_s)
        with self._lock:
            if len(self._scaleup_samples) < _MAX_RAW_SAMPLES:
                self._scaleup_samples.append(latency_s)

    def on_demand_unfulfillable(self, instance_group: str) -> None:
        self.registry.counter(
            DEMANDS_UNFULFILLABLE, **{TAG_INSTANCE_GROUP: instance_group}
        ).inc()

    def set_cluster_size(self, n: int) -> None:
        self.registry.gauge(CLUSTER_SIZE).set(float(n))

    def set_consecutive_failures(self, n: int) -> None:
        """Failed control-loop passes in a row (0 = healthy); paired with
        the controller's exponential backoff (ISSUE 9 satellite)."""
        self.registry.gauge(CONSECUTIVE_FAILURES).set(float(n))

    # -- inspection ----------------------------------------------------------

    def scaleup_latency_samples(self) -> list[float]:
        with self._lock:
            return list(self._scaleup_samples)

    def counts(self) -> dict:
        """Compact {added, drained, fulfilled, unfulfillable} totals across
        instance groups — the test/bench summary view."""
        snap = self.registry.snapshot()

        def total(name: str) -> int:
            return sum(e["value"] for e in snap.get(name, []))

        return {
            "nodes_added": total(NODES_ADDED),
            "nodes_drained": total(NODES_DRAINED),
            "demands_fulfilled": total(DEMANDS_FULFILLED),
            "demands_unfulfillable": total(DEMANDS_UNFULFILLABLE),
        }
