"""Vectorized preemption search + eviction execution.

When a high-priority gang fails fit, enumerate running lower-priority gangs
as eviction candidates, and solve the masked fit for ALL candidate sets in
one batched device pass (core/solver.py preemption_search →
ops/packing.py preemption_batched_fit). Candidate sets are NESTED prefixes
of the victim list ordered (priority asc, youngest first): set c evicts
victims[0..c]. Freed capacity is monotone in c, so the first feasible
prefix is the minimal-cost eviction set — picked on host with one argmax,
no per-candidate Python loop over kernel calls.

Hard-reservation safety: eviction only ever *releases* a victim's own
reservations (pod deletes + cache delete + soft-store release — the exact
teardown path every other component uses); reservations of non-victims are
never touched, and gangs at or above the protected class ("system") are
never candidates. The search decides only WHO to evict; the requester then
re-runs the normal admission solve against the freed cluster, so placement
semantics (including single-AZ strategies) cannot drift from the serving
path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from spark_scheduler_tpu.models.resources import NUM_DIMS
from spark_scheduler_tpu.models.reservations import PRIORITY_CLASS_ANNOTATION
from spark_scheduler_tpu.policy.priority import (
    PROTECTED_PRIORITY,
    effective_priority,
    parse_priority_class,
)


@dataclasses.dataclass
class PreemptionResult:
    """What happened, for the FlightRecorder and the caller's retry."""

    evicted: list[str]  # app ids, eviction order
    candidates: int  # eviction sets searched (one batched pass)
    searched: int  # victims enumerated
    cost: int  # reservation slots released
    search_ms: float


class PreemptionSearch:
    def __init__(
        self,
        rr_cache,
        pod_lister,
        soft_store,
        backend,
        clock,
        *,
        max_evictions: int,
        protected_priority: int = PROTECTED_PRIORITY,
        promote_after_s: Optional[float] = None,
    ):
        self._rr_cache = rr_cache
        self._pod_lister = pod_lister
        self._soft_store = soft_store
        self._backend = backend
        self._clock = clock
        self.max_evictions = max_evictions
        self.protected_priority = protected_priority
        # Anti-starvation symmetry with the ordering's age promotion: a
        # gang that aged into a higher effective tier also stops being an
        # eviction candidate for that tier (else sustained high-priority
        # pressure could evict a promoted gang forever). None = base
        # priority only.
        self.promote_after_s = promote_after_s

    # -- candidate enumeration ----------------------------------------------

    def enumerate_victims(
        self, requester_priority: int, domain_names: Optional[set]
    ) -> list[tuple[int, float, object]]:
        """Running gangs strictly below the requester's priority (and below
        the protected class), whose reservations touch the requester's
        domain. Returns [(priority, creation_ts, rr)] ordered cheapest-first:
        lowest priority, then youngest (Borg §2.3 eviction order)."""
        ceiling = min(requester_priority, self.protected_priority)
        now = self._clock()
        out = []
        for rr in self._rr_cache.list():
            pc = parse_priority_class(
                rr.annotations.get(PRIORITY_CLASS_ANNOTATION)
            )
            if domain_names is not None:
                nodes = {r.node for r in rr.spec.reservations.values()}
                if not (nodes & domain_names):
                    continue
            driver = self._pod_lister.get_driver_pod(rr.name, rr.namespace)
            created = driver.creation_timestamp if driver is not None else 0.0
            if self.promote_after_s is not None and driver is not None:
                pc = effective_priority(
                    pc, now - created, self.promote_after_s
                )
            if pc >= ceiling:
                continue
            out.append((pc, created, rr))
        out.sort(key=lambda v: (v[0], -v[1]))
        return out

    def freed_prefixes(self, victims, registry) -> np.ndarray:
        """[C, rows, 3] int64 cumulative freed capacity: row c = capacity
        released by evicting victims[0..c] (hard slots + the victims' own
        soft reservations), scattered into the solver's registry index
        space. Nodes the registry does not know free nothing usable."""
        soft = self._soft_store.get_all_copy()
        rows = max(registry.capacity, 1)
        freed = np.zeros((len(victims), rows, NUM_DIMS), dtype=np.int64)
        for c, (_pc, _created, rr) in enumerate(victims):
            step = freed[c]
            for res in rr.spec.reservations.values():
                idx = registry.index_of(res.node)
                if idx is not None and idx < rows:
                    step[idx] += res.resources.as_array().astype(np.int64)
            sr = soft.get(rr.name)
            if sr is not None:
                for r in sr.reservations.values():
                    idx = registry.index_of(r.node)
                    if idx is not None and idx < rows:
                        step[idx] += r.resources.as_array().astype(np.int64)
        return np.cumsum(freed, axis=0)

    # -- search + execution --------------------------------------------------

    def search(
        self,
        solver,
        strategy: str,
        tensors,
        app_resources,
        driver_candidate_names,
        domain_names: Optional[set],
        requester_priority: int,
        domain_mask=None,
    ) -> tuple[Optional[PreemptionResult], list]:
        """One batched pass over all candidate eviction sets. Returns
        (result, victims_to_evict); (None, []) when no eviction set admits
        the gang."""
        t0 = self._clock()
        victims = self.enumerate_victims(requester_priority, domain_names)[
            : self.max_evictions
        ]
        if not victims:
            return None, []
        freed_cum = self.freed_prefixes(victims, solver.registry)
        idx, _info = solver.preemption_search(
            strategy,
            tensors,
            app_resources.driver_resources,
            app_resources.executor_resources,
            app_resources.min_executor_count,
            driver_candidate_names,
            freed_cum,
            domain_mask=domain_mask,
        )
        if idx < 0:
            return None, []
        chosen = victims[: idx + 1]
        cost = sum(len(rr.spec.reservations) for _p, _c, rr in chosen)
        result = PreemptionResult(
            evicted=[rr.name for _p, _c, rr in chosen],
            candidates=len(victims),
            searched=len(victims),
            cost=cost,
            search_ms=(self._clock() - t0) * 1e3,
        )
        return result, chosen

    def execute(self, victims) -> None:
        """Release the chosen gangs through the normal teardown path: delete
        the app's pods (fires the soft-store / reservation-manager pod
        handlers), drop the app's remaining soft reservations, then delete
        the hard reservation (debiting the usage tracker via the cache's
        mutation listeners). Never touches another gang's reservations."""
        for _pc, _created, rr in victims:
            for pod in self._pod_lister.list_app_pods(rr.name, rr.namespace):
                cur = self._backend.get("pods", pod.namespace, pod.name)
                if cur is not None:
                    self._backend.delete_pod(cur)
            self._soft_store.remove_driver_reservation(rr.name)
            if self._rr_cache.get(rr.namespace, rr.name) is not None:
                self._rr_cache.delete(rr.namespace, rr.name)
