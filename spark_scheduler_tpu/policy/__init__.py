"""Policy subsystem: priority tiers, vectorized preemption search, DRF
window ordering, and a pool-driven continuous defragmenter.

Everything here is default-off: `build_scheduler_app` only constructs a
`PolicyEngine` when `InstallConfig.policy_enabled` is set, and every hook in
the extender takes the exact pre-policy branch when the engine is absent —
the FIFO path stays byte-identical (pinned by
tests/test_policy_identity.py).
"""

from spark_scheduler_tpu.policy.engine import PolicyConfig, PolicyEngine  # noqa: F401
from spark_scheduler_tpu.policy.priority import (  # noqa: F401
    PRIORITY_CLASS_ANNOTATION,
    PRIORITY_CLASSES,
    effective_priority,
    pod_priority,
)
from spark_scheduler_tpu.policy.registry import UnknownStrategyError, resolve  # noqa: F401
