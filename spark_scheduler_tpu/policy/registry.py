"""Shared plug-board resolution for name-keyed strategy registries.

`core/binpacker.py select_binpacker` and the policy window-ordering
plug-board both map a config string to an implementation; both now resolve
through this helper so an unknown name fails the same way everywhere: a
`UnknownStrategyError` listing the valid names, instead of the reference's
silent fall-back to a default (binpack.go:47-54) which hid typos in
production config for years.
"""

from __future__ import annotations

from typing import Mapping, TypeVar

T = TypeVar("T")


class UnknownStrategyError(ValueError):
    """Raised when a config string names no registered strategy."""

    def __init__(self, kind: str, name: str, valid: list[str]):
        self.kind = kind
        self.name = name
        self.valid = valid
        super().__init__(
            f"unknown {kind} {name!r}; valid {kind}s: {', '.join(valid)}"
        )


def resolve(name: str, registry: Mapping[str, T], kind: str) -> T:
    """Look `name` up in `registry`, raising a listing error on a miss."""
    try:
        return registry[name]
    except KeyError:
        raise UnknownStrategyError(kind, name, sorted(registry)) from None
