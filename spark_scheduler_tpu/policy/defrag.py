"""Continuous defragmenter: pool-idle repack planning off the serving path.

Fragmentation here is slot-stranding: free capacity that cannot host a
whole reference executor because it is scattered sub-slot across nodes.
With `unit` the reference executor shape,

    slots(node)  = min over dims of floor(free[d] / unit[d])
    ideal_slots  = min over dims of floor(sum(free)[d] / unit[d])
    fragmentation = 1 - total_slots / ideal_slots          (0 when ideal=0)

— 0.0 means every free byte is usable at executor granularity, 1.0 means
all free capacity is stranded.

`run_once()` (called from the policy engine's background cadence when the
device pool is idle, or directly by tests/soak) measures fragmentation,
then *migrates* up to `budget` reclaimable executors per pass: it picks
soft-reserved (dynamic-allocation extra) executors on stranded donor nodes
whose release completes at least one slot, deletes those pods — the normal
executor-death path, which releases the soft slot and queues the app for
compaction — and drains `compact_dynamic_allocation_applications()` so the
apps re-bind into hard slots. Hard reservations are never touched, so the
preemption budget bounds exactly the number of running executor pods
disturbed per pass.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from spark_scheduler_tpu.models.reservations import APP_ID_LABEL
from spark_scheduler_tpu.models.resources import NUM_DIMS, Resources

FRAGMENTATION_GAUGE = "foundry.spark.scheduler.policy.fragmentation"
DEFRAG_MIGRATIONS = "foundry.spark.scheduler.policy.defrag.migrations"
DEFRAG_PASSES = "foundry.spark.scheduler.policy.defrag.passes"


def _slots(free: np.ndarray, unit: np.ndarray) -> int:
    """Whole reference-executor slots a free vector can host (dims with a
    zero unit requirement don't constrain)."""
    s = None
    for d in range(NUM_DIMS):
        if unit[d] <= 0:
            continue
        k = int(free[d] // unit[d])
        s = k if s is None else min(s, k)
    return max(s or 0, 0)


class Defragmenter:
    def __init__(
        self,
        backend,
        soft_store,
        reservation_manager,
        clock,
        *,
        budget: int,
        unit: Resources | None = None,
        registry=None,
        solver=None,
    ):
        self._backend = backend
        self._soft_store = soft_store
        self._rrm = reservation_manager
        self._clock = clock
        self.budget = budget
        self._unit = np.maximum(
            (unit or Resources.from_quantities("1", "1Gi", "0", round_up=False))
            .as_array()
            .astype(np.int64),
            0,
        )
        self._metrics = registry
        self._solver = solver
        self.passes = 0
        self.migrations = 0
        self.last_fragmentation: Optional[float] = None

    # -- metric --------------------------------------------------------------

    def _free_by_node(self) -> dict[str, np.ndarray]:
        reserved = self._rrm.get_reserved_resources()
        out: dict[str, np.ndarray] = {}
        for node in self._backend.list_nodes():
            free = node.allocatable.as_array().astype(np.int64)
            res = reserved.get(node.name)
            if res is not None:
                free = free - res.as_array().astype(np.int64)
            out[node.name] = np.maximum(free, 0)
        return out

    def fragmentation(self) -> float:
        free = self._free_by_node()
        if not free:
            return 0.0
        total_slots = sum(_slots(f, self._unit) for f in free.values())
        ideal = _slots(sum(free.values()), self._unit)
        if ideal <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - total_slots / ideal))

    # -- one pass ------------------------------------------------------------

    def _pool_idle(self) -> bool:
        """Only consume device time the serving path is not using. Solvers
        without a pool (single-device) are always 'idle' for this purpose."""
        pool = getattr(self._solver, "pool", None) or getattr(
            self._solver, "_pool", None
        )
        if pool is None:
            return True
        idle = getattr(pool, "idle_slots", None)
        if callable(idle):
            try:
                return bool(idle())
            except Exception:
                return True
        return True

    def run_once(self, force: bool = False) -> dict:
        """One defrag pass. Returns {fragmentation_before, fragmentation_after,
        migrations} (the soak's reduction assertion reads these)."""
        if not force and not self._pool_idle():
            return {"skipped": "pool-busy"}
        before = self.fragmentation()
        free = self._free_by_node()
        migrated = 0
        # Reclaimable executors: soft-reserved extras whose release completes
        # at least one slot on their (currently stranded) node.
        soft = self._soft_store.get_all_copy()
        candidates: list[tuple[str, str, str]] = []  # (app, pod, node)
        for app_id, sr in soft.items():
            for pod_name, r in sr.reservations.items():
                f = free.get(r.node)
                if f is None:
                    continue
                gain = _slots(
                    f + r.resources.as_array().astype(np.int64), self._unit
                ) - _slots(f, self._unit)
                if _slots(f, self._unit) == 0 and gain > 0:
                    candidates.append((app_id, pod_name, r.node))
        for app_id, pod_name, _node in candidates[: self.budget]:
            pod = next(
                (
                    p
                    for p in self._backend.list_pods(
                        labels={APP_ID_LABEL: app_id}
                    )
                    if p.name == pod_name
                ),
                None,
            )
            if pod is None:
                continue
            self._backend.delete_pod(pod)
            migrated += 1
        if migrated:
            # Migrations ride the EXISTING soft-reservation compaction: the
            # deletions above queued each app; one drain re-binds survivors
            # into freed hard slots.
            self._rrm.compact_dynamic_allocation_applications()
        after = self.fragmentation()
        self.passes += 1
        self.migrations += migrated
        self.last_fragmentation = after
        if self._metrics is not None:
            self._metrics.gauge(FRAGMENTATION_GAUGE).set(round(after, 6))
            self._metrics.counter(DEFRAG_PASSES).inc()
            if migrated:
                self._metrics.counter(DEFRAG_MIGRATIONS).inc(migrated)
        return {
            "fragmentation_before": before,
            "fragmentation_after": after,
            "migrations": migrated,
        }
