"""Priority tiers with age-based anti-starvation promotion.

Borg-style priority bands (Verma et al., EuroSys '15 §2.3) reduced to four
named classes; a gang's class rides the `spark-priority-class` annotation on
its driver pod and is stamped onto the ResourceReservation at admission
(models/reservations.py) so running gangs keep their tier after the driver
pod is gone.

Anti-starvation: a pending gang's *effective* priority is promoted one tier
per `promote_after_s` of queue age, capped at "high" — a low-priority gang
waiting long enough eventually outranks fresh high-priority arrivals
(bounded starvation), but nothing ages into "system", so the protected
class stays strictly above all promotable work.
"""

from __future__ import annotations

from spark_scheduler_tpu.models.reservations import PRIORITY_CLASS_ANNOTATION  # noqa: F401

PRIORITY_CLASSES: dict[str, int] = {
    "low": 0,
    "default": 100,
    "high": 200,
    "system": 300,
}
DEFAULT_PRIORITY = PRIORITY_CLASSES["default"]
PROMOTION_STEP = 100  # one tier per promotion interval
PROMOTION_CAP = PRIORITY_CLASSES["high"]  # aging never reaches "system"
PROTECTED_PRIORITY = PRIORITY_CLASSES["system"]


def parse_priority_class(value: str | None) -> int:
    """Class name or bare integer -> numeric priority; unknown/absent ->
    default. Unknowns map to default rather than raising because the value
    arrives on user-authored pods, not operator config."""
    if value is None:
        return DEFAULT_PRIORITY
    v = value.strip().lower()
    if v in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[v]
    try:
        return int(v)
    except ValueError:
        return DEFAULT_PRIORITY


def pod_priority(pod) -> int:
    """Numeric priority of a driver pod (annotation, default tier absent)."""
    return parse_priority_class(
        (pod.annotations or {}).get(PRIORITY_CLASS_ANNOTATION)
    )


def effective_priority(base: int, age_s: float, promote_after_s: float) -> int:
    """Queue-age-promoted priority: +1 tier per full `promote_after_s` of
    age, capped at "high". A base already at/above the cap is unchanged
    (promotion never demotes, never reaches "system")."""
    if promote_after_s <= 0 or age_s <= 0 or base >= PROMOTION_CAP:
        return base
    steps = int(age_s // promote_after_s)
    if steps <= 0:
        return base
    return min(PROMOTION_CAP, base + steps * PROMOTION_STEP)
