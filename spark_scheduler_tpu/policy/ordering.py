"""Window-ordering strategies: FIFO (reference), priority-then-FIFO, DRF.

The extender consults ONE hook per driver request — `blockers(pod, group,
parsed_pending, now)` — to decide which pending gangs are ahead of it in
the queue. The contract mirrors the reference's FIFO predecessor scan
(sparkpods.go:51-77): same-instance-group blockers become capacity rows
packed ahead of the driver in the same window solve, so ordering and
feasibility are decided by one device program. Cross-instance-group
ordering (DRF) cannot ride capacity rows — instance-group domains are
disjoint node sets — so it surfaces as a *hard block*: the driver yields
this round with FAILURE_EARLIER_DRIVER and retries, exactly how
kube-scheduler treats any other queueing denial.

DRF (Ghodsi et al., NSDI '11): a gang's instance group is charged the sum
of its hard reservations (soft/speculative executor slots deliberately
excluded — they are reclaimable and would let opportunistic bursts distort
fairness); dominant share = max over resource dimensions of group usage /
cluster capacity; the queue admits smallest dominant share first.
`GroupUsageAggregates` maintains the per-group totals event-driven off the
reservation cache and node feed (the `core/zone_aggregates.py` pattern) —
no per-request walks.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from spark_scheduler_tpu.core.sparkpods import SparkPodLister
from spark_scheduler_tpu.models.resources import NUM_DIMS
from spark_scheduler_tpu.policy.priority import effective_priority, pod_priority
from spark_scheduler_tpu.store.cache import BatchableListener

_UNKNOWN_GROUP = ""


class GroupUsageAggregates:
    """Per-instance-group reserved usage + cluster capacity, delta-maintained.

    Same listener discipline as ReservedUsageTracker (core/usage_tracker.py):
    the reservation cache owner is the sole writer, so every hard-reservation
    change flows through the mutation listener; node capacity follows the
    backend's node feed. `rebuild()` is the from-scratch oracle the
    consistency test diffs against."""

    def __init__(self, backend, rr_cache, pod_lister: SparkPodLister):
        self._pod_lister = pod_lister
        self._backend = backend
        self._rr_cache = rr_cache
        self._lock = threading.Lock()
        self._usage: dict[str, np.ndarray] = {}
        self._capacity = np.zeros(NUM_DIMS, dtype=np.int64)
        # app (ns, name) -> instance group, pinned at reservation create so
        # the debit on delete matches the credit even after the driver pod
        # (the group's source of truth) is gone.
        self._group_of_app: dict[tuple[str, str], str] = {}
        backend.subscribe(
            "nodes",
            on_add=self._on_node_add,
            on_update=self._on_node_update,
            on_delete=self._on_node_delete,
        )
        rr_cache.add_mutation_listener(
            BatchableListener(self._on_rr_mutation, self._on_rr_mutation_batch)
        )
        self.rebuild()

    # -- queries -------------------------------------------------------------

    def dominant_share(self, group: Optional[str]) -> float:
        """max over dimensions of group usage / cluster capacity, in [0, 1]
        (0.0 for unseen groups or an empty cluster)."""
        key = group if group is not None else _UNKNOWN_GROUP
        with self._lock:
            u = self._usage.get(key)
            if u is None:
                return 0.0
            share = 0.0
            for d in range(NUM_DIMS):
                cap = int(self._capacity[d])
                if cap > 0:
                    share = max(share, int(u[d]) / cap)
            return share

    def snapshot(self) -> dict[str, tuple[int, ...]]:
        """{group: usage tuple} — for tests and the stats endpoint."""
        with self._lock:
            return {g: tuple(int(x) for x in u) for g, u in self._usage.items()}

    # -- maintenance ---------------------------------------------------------

    def rebuild(self) -> None:
        with self._lock:
            self._usage = {}
            self._capacity = np.zeros(NUM_DIMS, dtype=np.int64)
            for node in self._backend.list_nodes():
                self._capacity += node.allocatable.as_array().astype(np.int64)
            for rr in self._rr_cache.list():
                self._apply_rr(None, rr)

    def _group_of(self, rr) -> str:
        key = (rr.namespace, rr.name)
        group = self._group_of_app.get(key)
        if group is None:
            driver = self._pod_lister.get_driver_pod(rr.name, rr.namespace)
            if driver is not None:
                from spark_scheduler_tpu.core.sparkpods import find_instance_group

                group = find_instance_group(
                    driver, self._pod_lister.instance_group_label
                ) or _UNKNOWN_GROUP
            else:
                group = _UNKNOWN_GROUP
            self._group_of_app[key] = group
        return group

    @staticmethod
    def _rr_usage(rr) -> np.ndarray:
        total = np.zeros(NUM_DIMS, dtype=np.int64)
        for res in rr.spec.reservations.values():
            total += res.resources.as_array().astype(np.int64)
        return total

    def _apply_rr(self, old, new) -> None:
        """Caller holds the lock. O(slots of the touched app)."""
        if (
            old is not None
            and new is not None
            and old.spec.reservations == new.spec.reservations
        ):
            return  # status-only update (executor binding)
        rr = new if new is not None else old
        group = self._group_of(rr)
        bucket = self._usage.setdefault(group, np.zeros(NUM_DIMS, dtype=np.int64))
        if old is not None:
            bucket -= self._rr_usage(old)
        if new is not None:
            bucket += self._rr_usage(new)
        if new is None:
            self._group_of_app.pop((rr.namespace, rr.name), None)

    # -- listeners -----------------------------------------------------------

    def _on_rr_mutation(self, old, new) -> None:
        with self._lock:
            self._apply_rr(old, new)

    def _on_rr_mutation_batch(self, pairs) -> None:
        with self._lock:
            for old, new in pairs:
                self._apply_rr(old, new)

    def _on_node_add(self, node) -> None:
        with self._lock:
            self._capacity += node.allocatable.as_array().astype(np.int64)

    def _on_node_update(self, old, new) -> None:
        with self._lock:
            self._capacity += new.allocatable.as_array().astype(np.int64)
            self._capacity -= old.allocatable.as_array().astype(np.int64)

    def _on_node_delete(self, node) -> None:
        with self._lock:
            self._capacity -= node.allocatable.as_array().astype(np.int64)


# ---------------------------------------------------------------------------
# Ordering strategies.
# ---------------------------------------------------------------------------


def _is_same_pod(a, b) -> bool:
    return a.namespace == b.namespace and a.name == b.name


class FifoOrdering:
    """The reference ordering, bit-for-bit: same-group strictly-earlier
    drivers block, in snapshot (oldest-first) order."""

    name = "fifo"

    def blockers(self, pod, group, parsed_pending, now):
        rows = [
            t
            for t in parsed_pending
            if SparkPodLister.is_earlier_driver(t[0], t[1], pod, group)
        ]
        return rows, False


class PriorityOrdering:
    """Priority-then-FIFO with age-based anti-starvation promotion: a
    same-group pending gang is ahead when its effective (age-promoted)
    priority is higher, or equal and it is older. Ordering among blockers is
    (effective priority desc, creation asc) — the order they would admit."""

    name = "priority"

    def __init__(self, promote_after_s: float):
        self.promote_after_s = promote_after_s

    def _effective(self, pod, now: float) -> int:
        return effective_priority(
            pod_priority(pod), now - pod.creation_timestamp, self.promote_after_s
        )

    def blockers(self, pod, group, parsed_pending, now):
        mine = self._effective(pod, now)
        ahead: list[tuple[int, tuple]] = []
        for t in parsed_pending:
            ed, ed_group = t[0], t[1]
            if (
                ed_group != group
                or ed.scheduler_name != pod.scheduler_name
                or _is_same_pod(ed, pod)
            ):
                continue
            ep = self._effective(ed, now)
            if ep > mine or (
                ep == mine and ed.creation_timestamp < pod.creation_timestamp
            ):
                ahead.append((ep, t))
        # Stable sort: equal keys keep the snapshot's oldest-first order.
        ahead.sort(key=lambda e: (-e[0], e[1][0].creation_timestamp))
        return [t for _, t in ahead], False


class DrfOrdering:
    """Smallest-dominant-share-first across instance groups; FIFO within a
    group. A pending gang of another group with a strictly smaller dominant
    share hard-blocks this driver (disjoint domains — capacity rows cannot
    express the yield); the age gate (`skip` flag, resource.go:260-270
    semantics) keeps too-young gangs from enforcing the yield, which bounds
    cross-group waiting exactly like FIFO's enforcement delay."""

    name = "drf"

    def __init__(self, shares: GroupUsageAggregates):
        self.shares = shares

    def blockers(self, pod, group, parsed_pending, now):
        rows = [
            t
            for t in parsed_pending
            if SparkPodLister.is_earlier_driver(t[0], t[1], pod, group)
        ]
        my_share = self.shares.dominant_share(group)
        share_of: dict = {}
        hard = False
        for t in parsed_pending:
            ed, ed_group, _res, ed_skip = t
            if (
                ed_group == group
                or ed_skip
                or ed.scheduler_name != pod.scheduler_name
                or _is_same_pod(ed, pod)
            ):
                continue
            if ed_group not in share_of:
                share_of[ed_group] = self.shares.dominant_share(ed_group)
            if share_of[ed_group] < my_share:
                hard = True
                break
        return rows, hard


ORDERING_STRATEGIES = ("fifo", "priority", "drf")
