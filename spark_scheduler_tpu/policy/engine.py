"""PolicyEngine: the one object the extender consults.

Constructed by `build_scheduler_app` ONLY when `InstallConfig.policy_enabled`
— every extender hook takes the exact pre-policy branch when the engine is
absent, keeping the FIFO path byte-identical (the CI identity pin).

Composes the four policy parts behind three hooks:

  ordering.blockers(...)       window/solo queue ordering (fifo|priority|drf)
  try_preempt(...)             vectorized preemption search + eviction
  maybe_defrag() / defrag      pool-idle fragmentation passes

Every preemption decision is recorded into the FlightRecorder by the
extender (eviction set, candidate count, slot cost, search wall time) and
counted in the policy metric family.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from spark_scheduler_tpu.policy.defrag import Defragmenter
from spark_scheduler_tpu.policy.ordering import (
    DrfOrdering,
    FifoOrdering,
    GroupUsageAggregates,
    PriorityOrdering,
)
from spark_scheduler_tpu.policy.preemption import PreemptionResult, PreemptionSearch
from spark_scheduler_tpu.policy.priority import (
    PROTECTED_PRIORITY,
    parse_priority_class,
    pod_priority,
)
from spark_scheduler_tpu.policy.registry import resolve

PREEMPTIONS = "foundry.spark.scheduler.policy.preemptions"
PREEMPTION_EVICTIONS = "foundry.spark.scheduler.policy.preemption.evictions"
PREEMPTION_SEARCH_MS = "foundry.spark.scheduler.policy.preemption.search-ms"


@dataclasses.dataclass
class PolicyConfig:
    ordering: str = "fifo"  # fifo | priority | drf
    preemption: bool = False
    max_evictions: int = 8
    promote_after_s: float = 300.0
    defrag: bool = False
    defrag_interval_s: float = 30.0
    defrag_budget: int = 4
    protected_class: str = "system"


class PolicyEngine:
    def __init__(
        self,
        config: PolicyConfig,
        *,
        backend,
        rr_cache,
        pod_lister,
        soft_store,
        reservation_manager,
        solver,
        clock,
        metrics_registry=None,
    ):
        self.config = config
        self._clock = clock
        self._metrics = metrics_registry
        self._lock = threading.Lock()
        self._last_defrag = 0.0

        # Ordering plug-board: same registry/error shape as select_binpacker.
        shares: Optional[GroupUsageAggregates] = None
        if config.ordering == "drf":
            shares = GroupUsageAggregates(backend, rr_cache, pod_lister)
        strategies = {
            "fifo": lambda: FifoOrdering(),
            "priority": lambda: PriorityOrdering(config.promote_after_s),
            "drf": lambda: DrfOrdering(shares),
        }
        self.ordering = resolve(
            config.ordering, strategies, "policy ordering strategy"
        )()
        self.shares = shares

        self.preemption: Optional[PreemptionSearch] = None
        if config.preemption:
            self.preemption = PreemptionSearch(
                rr_cache,
                pod_lister,
                soft_store,
                backend,
                clock,
                max_evictions=config.max_evictions,
                protected_priority=parse_priority_class(
                    config.protected_class
                )
                if config.protected_class
                else PROTECTED_PRIORITY,
                promote_after_s=config.promote_after_s,
            )

        self.defrag: Optional[Defragmenter] = None
        if config.defrag:
            self.defrag = Defragmenter(
                backend,
                soft_store,
                reservation_manager,
                clock,
                budget=config.defrag_budget,
                registry=metrics_registry,
                solver=solver,
            )

    # -- preemption ----------------------------------------------------------

    def try_preempt(
        self,
        solver,
        strategy: str,
        tensors,
        pod,
        app_resources,
        driver_candidate_names,
        domain_names,
        domain_mask=None,
    ) -> Optional[PreemptionResult]:
        """Search + execute: one batched masked-fit pass over candidate
        eviction sets; on a feasible minimal set, evict it and return the
        result (the caller bumps the capacity epoch and re-solves). None
        when preemption is off, the gang is not above the floor, or no
        eviction set admits it."""
        if self.preemption is None:
            return None
        requester = pod_priority(pod)
        result, victims = self.preemption.search(
            solver,
            strategy,
            tensors,
            app_resources,
            driver_candidate_names,
            set(domain_names) if domain_names is not None else None,
            requester,
            domain_mask=domain_mask,
        )
        if result is None:
            return None
        self.preemption.execute(victims)
        if self._metrics is not None:
            self._metrics.counter(PREEMPTIONS).inc()
            self._metrics.counter(PREEMPTION_EVICTIONS).inc(
                len(result.evicted)
            )
            self._metrics.histogram(PREEMPTION_SEARCH_MS).update(
                result.search_ms
            )
        return result

    # -- defragmenter --------------------------------------------------------

    def maybe_defrag(self) -> Optional[dict]:
        """Interval-gated defrag pass (called from the serving loop's idle
        moments / the background cadence). Returns the pass summary when a
        pass ran."""
        if self.defrag is None:
            return None
        now = self._clock()
        with self._lock:
            if now - self._last_defrag < self.config.defrag_interval_s:
                return None
            self._last_defrag = now
        return self.defrag.run_once()
