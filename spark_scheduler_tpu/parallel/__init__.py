"""Multi-chip scale-out for the placement solver.

The reference scales by running ONE single-threaded Go process (SURVEY.md §0);
the TPU rebuild scales the 10k-node x 1k-app solve across a device mesh
(SURVEY.md §2d, §5.8): the node axis is sharded like a sequence axis
("sequence parallelism" for this workload) and independent instance-group
subproblems are data-parallel. Collectives are never hand-written — shardings
are declared with `jax.sharding.NamedSharding` and XLA inserts the
psum/all-gather/all-to-all it needs (scaling-book recipe).
"""

from spark_scheduler_tpu.parallel.mesh import make_pool_slots, make_solver_mesh
from spark_scheduler_tpu.parallel.solve import (
    grouped_fifo_pack,
    grouped_fifo_pack_auto,
    node_sharding,
    shard_apps,
    sharded_fifo_pack,
    stack_groups,
)

__all__ = [
    "make_pool_slots",
    "make_solver_mesh",
    "node_sharding",
    "shard_apps",
    "sharded_fifo_pack",
    "grouped_fifo_pack",
    "grouped_fifo_pack_auto",
    "stack_groups",
]
