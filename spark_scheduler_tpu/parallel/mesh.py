"""Device-mesh construction for the solver.

Axes:
  "groups" — data parallelism over independent instance-group subproblems
             (apps in different instance groups contend for disjoint node
             sets: failover.go:276-313 groups nodes by the instance-group
             label, so each group's admission scan is independent).
  "nodes"  — model/sequence-style sharding of the node axis of one large
             subproblem (capacity kernels are elementwise over nodes; sorts,
             prefix sums and the feasibility psum become XLA collectives
             over ICI).

On a multi-host slice the same mesh spans hosts and XLA routes "nodes"
collectives over ICI and "groups" over DCN when
`jax.distributed.initialize()` has formed a multi-process runtime — the
NCCL/MPI slot of SURVEY.md §5.8, filled by XLA.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_solver_mesh(
    n_groups: int | None = None,
    n_nodes_shards: int | None = None,
    devices=None,
) -> Mesh:
    """Build a ("groups", "nodes") mesh over the available devices.

    With neither axis size given, all devices go to "nodes" (single large
    cluster). Axis sizes must multiply to the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    d = len(devices)
    if n_groups is None and n_nodes_shards is None:
        n_groups, n_nodes_shards = 1, d
    elif n_groups is None:
        n_groups = d // n_nodes_shards
    elif n_nodes_shards is None:
        n_nodes_shards = d // n_groups
    if n_groups * n_nodes_shards != d:
        raise ValueError(
            f"mesh {n_groups}x{n_nodes_shards} != {d} devices"
        )
    arr = np.asarray(devices).reshape(n_groups, n_nodes_shards)
    return Mesh(arr, ("groups", "nodes"))


def make_pool_slots(pool: int, node_shards: int = 1, devices=None) -> list:
    """Placements for the serving window-solve engine (core/solver.py):
    `pool` SLOTS, each either a plain device (node_shards == 1) or a
    single-axis ("nodes",) sub-mesh of `node_shards` devices. Slot k gets
    devices [k*S, (k+1)*S) of the flat device list — the same row-major
    layout make_solver_mesh uses, so a {groups, node_shards} install config
    describes both APIs identically.

    More slots than the backend has devices CLAMP to what exists (slot
    count is a throughput knob, not a correctness contract — a laptop run
    of an 8-pool config must serve, just without the parallelism)."""
    devices = list(devices if devices is not None else jax.devices())
    node_shards = max(1, node_shards)
    pool = max(1, pool)
    usable = len(devices) // node_shards
    if usable < 1:
        raise ValueError(
            f"mesh node-shards {node_shards} exceeds the {len(devices)} "
            "available devices"
        )
    pool = min(pool, usable)
    slots = []
    for k in range(pool):
        row = devices[k * node_shards : (k + 1) * node_shards]
        if node_shards == 1:
            slots.append(row[0])
        else:
            slots.append(Mesh(np.asarray(row), ("nodes",)))
    return slots
