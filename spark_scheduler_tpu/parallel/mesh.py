"""Device-mesh construction for the solver.

Axes:
  "groups" — data parallelism over independent instance-group subproblems
             (apps in different instance groups contend for disjoint node
             sets: failover.go:276-313 groups nodes by the instance-group
             label, so each group's admission scan is independent).
  "nodes"  — model/sequence-style sharding of the node axis of one large
             subproblem (capacity kernels are elementwise over nodes; sorts,
             prefix sums and the feasibility psum become XLA collectives
             over ICI).

On a multi-host slice the same mesh spans hosts and XLA routes "nodes"
collectives over ICI and "groups" over DCN when
`jax.distributed.initialize()` has formed a multi-process runtime — the
NCCL/MPI slot of SURVEY.md §5.8, filled by XLA.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_solver_mesh(
    n_groups: int | None = None,
    n_nodes_shards: int | None = None,
    devices=None,
) -> Mesh:
    """Build a ("groups", "nodes") mesh over the available devices.

    With neither axis size given, all devices go to "nodes" (single large
    cluster). Axis sizes must multiply to the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    d = len(devices)
    if n_groups is None and n_nodes_shards is None:
        n_groups, n_nodes_shards = 1, d
    elif n_groups is None:
        n_groups = d // n_nodes_shards
    elif n_nodes_shards is None:
        n_nodes_shards = d // n_groups
    if n_groups * n_nodes_shards != d:
        raise ValueError(
            f"mesh {n_groups}x{n_nodes_shards} != {d} devices"
        )
    arr = np.asarray(devices).reshape(n_groups, n_nodes_shards)
    return Mesh(arr, ("groups", "nodes"))
