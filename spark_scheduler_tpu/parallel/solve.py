"""Sharded batched FIFO admission.

Two composition levels over `ops.batched.batched_fifo_pack`:

  sharded_fifo_pack — one large cluster, node axis sharded over the mesh's
      "nodes" axis. The scan body's elementwise capacity math stays local to
      each shard; the total-capacity reduction, node sorts, and prefix sums
      become XLA collectives. This is the sequence-parallel analog for the
      10k-node axis (SURVEY.md §5.7).

  grouped_fifo_pack — G independent instance-group subproblems stacked on a
      leading axis, vmapped and sharded over "groups" (data parallel), each
      subproblem's node axis sharded over "nodes": full 2D parallelism.

Shardings are declared; collectives are XLA's to choose (no hand-written
ppermute/psum — scaling-book style).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_scheduler_tpu.models.cluster import ClusterTensors
from spark_scheduler_tpu.ops.batched import AppBatch, BatchedPacking, batched_fifo_pack


def node_sharding(mesh: Mesh, ndim: int, leading=()) -> NamedSharding:
    """THE sharding of a node-axis array on a ("nodes",) mesh: axis 0
    (after any `leading` axes) over "nodes", the rest replicated. The one
    definition both the one-shot sharded_fifo_pack placement and the
    serving engine's per-slot replica placement (core/solver.py
    _PoolSlot) build on — edit here, both follow."""
    spec = P(*leading, "nodes", *([None] * (ndim - 1 - len(leading))))
    return NamedSharding(mesh, spec)


def _shard_cluster(cluster: ClusterTensors, mesh: Mesh, leading=()) -> ClusterTensors:
    """Place cluster tensors with the node axis sharded over "nodes"."""

    def put(x):
        x = jnp.asarray(x)
        return jax.device_put(x, node_sharding(mesh, x.ndim, leading))

    return jax.tree_util.tree_map(put, cluster)


def _shard_apps(apps: AppBatch, mesh: Mesh, leading=()) -> AppBatch:
    """App batch: replicated across "nodes" (the scan walks it sequentially),
    optionally sharded on a leading "groups" axis. The optional per-app
    [B, N] masks carry a node axis, which shards over "nodes" like the
    cluster tensors."""

    def put(x, node_axis=False):
        if x is None:
            return None
        x = jnp.asarray(x)
        if node_axis:
            spec = P(*leading, None, "nodes")
        else:
            spec = P(*leading, *([None] * (x.ndim - len(leading))))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return AppBatch(
        driver_req=put(apps.driver_req),
        exec_req=put(apps.exec_req),
        exec_count=put(apps.exec_count),
        app_valid=put(apps.app_valid),
        skippable=put(apps.skippable),
        driver_cand=put(apps.driver_cand, node_axis=True),
        domain=put(apps.domain, node_axis=True),
        commit=put(apps.commit),
        reset=put(apps.reset),
    )


# Public surface for the serving window-solve engine (core/solver.py):
# `node_sharding` places a mesh slot's resident replica fields and
# `shard_apps` its window app batches with the SAME shardings the one-shot
# sharded_fifo_pack picks; the engine then runs its own blob-packing jit
# over them (computation follows input shardings — GSPMD).
def shard_apps(apps: AppBatch, mesh: Mesh) -> AppBatch:
    """App batch replicated over "nodes" except the per-app [B, N] masks,
    which shard their node axis with the cluster."""
    return _shard_apps(apps, mesh)


def sharded_fifo_pack(
    mesh: Mesh,
    cluster: ClusterTensors,
    apps: AppBatch,
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
) -> BatchedPacking:
    """Batched FIFO admission with the node axis sharded across the mesh.

    Node count must divide evenly by the "nodes" axis size (pad the cluster
    tensors with invalid slots — build_cluster_tensors' `pad_to`)."""
    n_shards = mesh.shape["nodes"]
    if cluster.available.shape[0] % n_shards:
        raise ValueError(
            f"node count {cluster.available.shape[0]} not divisible by "
            f'mesh "nodes" axis {n_shards}; pad with invalid slots'
        )
    cluster = _shard_cluster(cluster, mesh)
    apps = _shard_apps(apps, mesh)
    # Computation follows the input shardings (GSPMD); no explicit mesh
    # context needed — XLA partitions the scan body and inserts collectives.
    return batched_fifo_pack(cluster, apps, fill=fill, emax=emax, num_zones=num_zones)


def stack_groups(
    clusters: list[ClusterTensors], app_batches: list[AppBatch]
) -> tuple[ClusterTensors, AppBatch]:
    """Stack per-instance-group subproblems on a leading axis. All groups
    must be padded to identical (N, B, Emax) shapes (bucketing keeps the
    compile cache warm anyway)."""
    cluster = jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *clusters
    )
    stacked_cols = []
    for field, cols in zip(AppBatch._fields, zip(*app_batches)):
        present = [x is not None for x in cols]
        if not any(present):
            stacked_cols.append(None)
            continue
        if not all(present):
            raise ValueError(
                f"AppBatch field {field!r} set for some groups but not others; "
                "masks must be provided for every group or none"
            )
        stacked_cols.append(np.stack([np.asarray(x) for x in cols]))
    return cluster, AppBatch(*stacked_cols)


def _grouped_pallas_sharded(
    mesh: Mesh,
    clusters: ClusterTensors,  # leaves stacked [G, N, ...]
    apps: AppBatch,  # leaves stacked [G, B, ...]
    *,
    fill: str,
    emax: int,
    num_zones: int,
    interpret: bool = False,
) -> BatchedPacking:
    """The MULTI-CHIP Mosaic path (VERDICT r3 #5): instance groups are
    independent subproblems, so shard the group axis across the mesh with
    `shard_map` and run the Pallas queue kernel per group on each device —
    SPMD data parallelism with ZERO cross-device collectives in the solve
    (the scaling-book recipe: pick the axis with no data dependence).

    Sharding the NODE axis of one large cluster through the kernel would
    put a cross-shard argmin + capacity psum inside every fill round
    (emax collectives per app, latency-bound on ICI); measured single-chip
    Pallas at 100k nodes (16.6 ms, PERFORMANCE.md) already beats the
    node-sharded XLA scan, so node-axis scale-out stays on the GSPMD scan
    (`sharded_fifo_pack`) and chip scale-out happens on the group axis."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    g = clusters.available.shape[0]
    n_dev = mesh.shape["groups"]
    if g % n_dev:
        raise ValueError(
            f'group count {g} not divisible by mesh "groups" axis {n_dev}'
        )
    g_local = g // n_dev

    def body(local_c, local_a):
        return _grouped_pallas(
            local_c, local_a, fill=fill, emax=emax, num_zones=num_zones,
            g=g_local, interpret=interpret,
        )

    # check_vma/check_rep: the replication checker cannot see through
    # pallas_call's opaque outputs — the body is elementwise over the
    # sharded group axis by construction (each group solved locally).
    try:
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P("groups"), P("groups")),
            out_specs=P("groups"),
            check_vma=False,
        )
    except TypeError:  # older jax spells it check_rep
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P("groups"), P("groups")),
            out_specs=P("groups"),
            check_rep=False,
        )
    return fn(clusters, apps)


def grouped_fifo_pack_auto(
    mesh: Mesh,
    clusters: ClusterTensors,  # leaves stacked [G, N, ...]
    apps: AppBatch,  # leaves stacked [G, B, ...]
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
) -> BatchedPacking:
    """`grouped_fifo_pack` with Pallas fast paths: when the subproblems are
    plain queue-mode and the backend compiles Mosaic, a single-chip mesh
    solves each group with the Pallas queue kernel back to back (G
    sequential sub-ms kernels beat one vmapped XLA scan, whose per-step
    overhead multiplies under vmap), and a multi-chip mesh sharded ONLY on
    "groups" runs the same kernel per device under shard_map
    (_grouped_pallas_sharded) — decisions identical, groups are
    independent. Node-sharded meshes and masked/segmented batches keep the
    GSPMD vmapped scan."""
    from spark_scheduler_tpu.ops.pallas_fifo import (
        pallas_available,
        pallas_eligible,
    )

    if (
        mesh.devices.size > 1
        and mesh.shape["groups"] == mesh.devices.size
        and mesh.shape.get("nodes", 1) == 1
        and clusters.available.shape[0] % mesh.devices.size == 0
        and pallas_eligible(apps, fill)
        and pallas_available()
    ):
        return _grouped_pallas_sharded(
            mesh, clusters, apps, fill=fill, emax=emax, num_zones=num_zones
        )
    if (
        mesh.devices.size == 1
        and pallas_eligible(apps, fill)
        and pallas_available()
    ):
        # Pin execution (and result placement) to the mesh's device.
        # jax.default_device only steers UNcommitted arrays — jit follows
        # committed inputs — so committed-elsewhere leaves are moved
        # explicitly.
        dev = list(mesh.devices.flat)[0]

        def _pin(x):
            if x is None:
                return None
            if getattr(x, "devices", None) and x.devices() != {dev}:
                return jax.device_put(x, dev)
            return x

        clusters = jax.tree_util.tree_map(_pin, clusters)
        apps = AppBatch(*[_pin(col) for col in apps])
        with jax.default_device(dev):
            return _grouped_pallas(
                clusters,
                apps,
                fill=fill,
                emax=emax,
                num_zones=num_zones,
                g=clusters.available.shape[0],
            )
    return grouped_fifo_pack(
        mesh, clusters, apps, fill=fill, emax=emax, num_zones=num_zones
    )


@partial(
    jax.jit, static_argnames=("fill", "emax", "num_zones", "g", "interpret")
)
def _grouped_pallas(
    clusters, apps, *, fill, emax, num_zones, g, interpret=False
):
    """All G group solves in ONE jitted program (one dispatch; G Mosaic
    kernel launches back to back). Slicing the group axis eagerly would
    cost an RPC per op on a tunneled device. `interpret` lets the CPU
    suite drive the slicing/stacking logic through the Pallas
    interpreter."""
    from spark_scheduler_tpu.ops.pallas_fifo import fifo_pack_pallas

    outs = []
    for i in range(g):
        c_i = jax.tree_util.tree_map(lambda x: x[i], clusters)
        a_i = AppBatch(*[None if col is None else col[i] for col in apps])
        outs.append(
            fifo_pack_pallas(
                c_i, a_i, fill=fill, emax=emax, num_zones=num_zones,
                interpret=interpret,
            )
        )
    return BatchedPacking(
        *[
            jnp.stack([getattr(o, f) for o in outs])
            for f in BatchedPacking._fields
        ]
    )


def grouped_fifo_pack(
    mesh: Mesh,
    clusters: ClusterTensors,  # leaves stacked [G, N, ...]
    apps: AppBatch,  # leaves stacked [G, B, ...]
    *,
    fill: str = "tightly-pack",
    emax: int,
    num_zones: int,
) -> BatchedPacking:
    """2D-parallel admission: vmap over the instance-group axis (sharded
    over "groups"), node axis of each subproblem sharded over "nodes"."""
    g = clusters.available.shape[0]
    if g % mesh.shape["groups"]:
        raise ValueError(
            f'group count {g} not divisible by mesh "groups" axis '
            f"{mesh.shape['groups']}; pad with empty groups"
        )
    clusters = _shard_cluster(clusters, mesh, leading=("groups",))
    apps = _shard_apps(apps, mesh, leading=("groups",))
    # unroll=1: scan unrolling regresses ~2x under vmap (measured on v5e —
    # the unrolled fused body blows the per-group working set).
    fn = jax.vmap(
        partial(
            batched_fifo_pack, fill=fill, emax=emax, num_zones=num_zones, unroll=1
        )
    )
    return fn(clusters, apps)
