"""Prometheus text exposition of a MetricRegistry snapshot.

The registry's native output is push: JSON lines per reporter tick
(metrics/registry.py emit). This renders the same snapshot as the
Prometheus text format (version 0.0.4) so a scrape-based stack can pull
GET /metrics directly: dotted names become underscore names, tags become
labels, histograms render as summaries (quantile series + _count + _sum).
"""

from __future__ import annotations

import re

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")

# Histogram stat -> quantile label (min/max/mean ride their own suffixes).
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def prefers_prometheus(accept: str) -> bool:
    """Does this Accept header PREFER a text exposition over JSON?
    Minimal q-value parse: the highest q among text/plain +
    application/openmetrics-text must beat application/json's (a client
    listing `application/json, text/plain;q=0.1` keeps JSON — a bare
    substring test would hand it unparseable text)."""
    q_text = q_json = 0.0
    for part in (accept or "").split(","):
        fields = part.split(";")
        mtype = fields[0].strip().lower()
        q = 1.0
        for f in fields[1:]:
            f = f.strip()
            if f.startswith("q="):
                try:
                    q = float(f[2:])
                except ValueError:
                    q = 0.0
        if mtype in ("text/plain", "application/openmetrics-text"):
            q_text = max(q_text, q)
        elif mtype == "application/json":
            q_json = max(q_json, q)
    return q_text > q_json


def _metric_name(name: str) -> str:
    out = _NAME_OK.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _labels(tags: dict[str, str]) -> str:
    if not tags:
        return ""
    parts = []
    for k in sorted(tags):
        v = str(tags[k]).replace("\\", "\\\\").replace('"', '\\"')
        v = v.replace("\n", "\\n")
        parts.append(f'{_LABEL_OK.sub("_", k)}="{v}"')
    return "{" + ",".join(parts) + "}"


def render_prometheus(snapshot: dict, extra_gauges: dict | None = None) -> str:
    """`snapshot` is MetricRegistry.snapshot(); `extra_gauges` is
    {name: value} for serving-layer stats that live outside the registry
    (the predicate batcher's counters)."""
    lines: list[str] = []
    for name in sorted(snapshot):
        entries = snapshot[name]
        if not entries:
            continue
        pname = _metric_name(name)
        kind = entries[0]["kind"]
        if kind == "histogram":
            lines.append(f"# TYPE {pname} summary")
            for e in entries:
                tags = e["tags"]
                for stat, q in _QUANTILES:
                    if stat in e:
                        lines.append(
                            f"{pname}{_labels({**tags, 'quantile': q})}"
                            f" {e[stat]}"
                        )
                count = e.get("count", 0)
                lines.append(f"{pname}_count{_labels(tags)} {count}")
                # The exact running sum (monotone); mean*count only as a
                # fallback for foreign snapshot shapes.
                total = e.get("sum", e.get("mean", 0.0) * count)
                lines.append(f"{pname}_sum{_labels(tags)} {total}")
                for stat in ("min", "max"):
                    if stat in e:
                        lines.append(
                            f"{pname}_{stat}{_labels(tags)} {e[stat]}"
                        )
        else:
            lines.append(
                f"# TYPE {pname} {'counter' if kind == 'counter' else 'gauge'}"
            )
            for e in entries:
                lines.append(f"{pname}{_labels(e['tags'])} {e['value']}")
    for name in sorted(extra_gauges or {}):
        pname = _metric_name(name)
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {extra_gauges[name]}")
    return "\n".join(lines) + "\n"
