"""Scheduling flight recorder + solver telemetry (SURVEY.md §0 decision
explainability; the Witchcraft-middleware observability the Go reference got
for free, adapted to the JAX hot path).

  - `recorder`: every extender decision becomes a structured
    `DecisionRecord` (verdict, per-node failure map, FIFO queue position,
    padding bucket, compile-cache hit/miss, featurize/solve/commit phase
    times) in a bounded thread-safe ring, queryable at
    GET /debug/decisions.
  - `telemetry`: `SolverTelemetry` — the hook surface core/solver.py calls
    to publish jit-compile counts/seconds, padding-bucket occupancy,
    pipeline drain/discard/fetch-failure counters, and host<->device
    transfer bytes into the tagged registry under
    `foundry.spark.scheduler.solver.*`.
  - `exposition`: Prometheus text rendering of a MetricRegistry snapshot,
    giving the push-only JSON-line reporter a pull surface (GET /metrics).
  - `state`: the point-in-time GET /debug/state snapshot (hard/soft
    reservations, FIFO queue, unschedulable set, node fleet).
"""

from spark_scheduler_tpu.observability.recorder import (  # noqa: F401
    DecisionRecord,
    FlightRecorder,
)
from spark_scheduler_tpu.observability.telemetry import (  # noqa: F401
    FleetTelemetry,
    HATelemetry,
    RetryTelemetry,
    SolverTelemetry,
    TransportTelemetry,
    compile_stats,
)
from spark_scheduler_tpu.observability.exposition import (  # noqa: F401
    prefers_prometheus,
    render_prometheus,
)
from spark_scheduler_tpu.observability.state import (  # noqa: F401
    debug_state_snapshot,
)

__all__ = [
    "DecisionRecord",
    "FleetTelemetry",
    "FlightRecorder",
    "HATelemetry",
    "RetryTelemetry",
    "SolverTelemetry",
    "TransportTelemetry",
    "compile_stats",
    "prefers_prometheus",
    "render_prometheus",
    "debug_state_snapshot",
]
