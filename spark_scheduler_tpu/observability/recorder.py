"""The scheduling flight recorder.

Every extender decision appends one `DecisionRecord` to a bounded
thread-safe ring. The record answers the operator questions the final
verdict alone cannot (SURVEY.md §0): why was this app denied, on which
nodes, at what FIFO queue position, which padding bucket served it, did the
solve hit the XLA compile cache, and how long did each phase
(featurize -> solve -> commit) take. Queryable at GET /debug/decisions;
the autoscaler annotates records whose demand it later fulfilled, closing
the denied -> demand -> scale-up -> fulfilled story on one object.

Appends are O(1) under one lock (a dict build + deque append) — the
recorder rides the serving hot path, and bench.py's recorder-overhead
section measures, rather than assumes, that this stays in the noise.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Optional

# Sentinel key marking a failure map truncated to MAX_FAILED_NODES — never
# a real node name ("..." is not a valid k8s object name).
TRUNCATION_KEY = "..."

# Verdicts whose denial creates a Demand (extender: failed gang admission /
# executor reschedule) — the only records a fulfilled demand can originate
# from, and so the only ones annotate_demand_fulfilled may stamp.
DEMAND_CREATING_VERDICTS = frozenset(
    {"failure-fit", "failure-earlier-driver"}
)


def _truncation_marker(omitted: int) -> str:
    return f"truncated: {omitted} more nodes with the same verdict"


@dataclasses.dataclass
class DecisionRecord:
    """One extender decision, explained."""

    seq: int
    time: float
    namespace: str
    pod_name: str
    app_id: str
    instance_group: str
    role: str
    verdict: str
    node: Optional[str] = None
    message: str = ""
    # Per-node failure-reason map (the extender protocol's FailedNodes) —
    # empty on success.
    failed_nodes: dict[str, str] = dataclasses.field(default_factory=dict)
    # Number of earlier pending FIFO drivers this request re-packed
    # (None when FIFO is off or the path doesn't consult the queue).
    queue_position: Optional[int] = None
    # {"featurize_ms", "solve_ms", "commit_ms"} — whichever phases ran.
    phases: dict[str, float] = dataclasses.field(default_factory=dict)
    # Solver dispatch info: {"path", "nodes", "rows", "row_bucket", "emax",
    # "compile_cache_hit"} when a device solve served the decision.
    solve: Optional[dict[str, Any]] = None
    # Which pool slot solved this decision's window (partition), e.g.
    # "cpu:1" — None outside the multi-device engine. Lets /debug/decisions
    # attribute a latency outlier to one device.
    device_id: Optional[str] = None
    # Fused multi-window dispatch: how many serving windows shared this
    # decision's device dispatch (1 = unfused), and the solver's monotone
    # id of that dispatch — every decision of one fused batch shares the
    # id, so /debug/decisions groups the K windows one round trip served.
    fused_k: Optional[int] = None
    dispatch_id: Optional[int] = None
    # How the solve's cluster state reached the device: "full" re-upload,
    # "delta" row scatter, or "reuse" of the resident replica — a "full"
    # on a latency outlier marks a cold device replica.
    state_upload: Optional[str] = None
    # Set by the autoscaler when the demand this denial created is
    # fulfilled: {"fulfilled_at", "latency_s"}.
    demand: Optional[dict[str, float]] = None
    # Fault-tolerance provenance (ISSUE 9): True when NO device solved
    # this decision (the host greedy fallback served it under the
    # degraded-mode policy), and how many device-slot re-dispatches the
    # decision's window survived (None/0 = clean dispatch).
    degraded: Optional[bool] = None
    redispatches: Optional[int] = None
    # Policy subsystem (ISSUE 16): eviction set + costs when a preemption
    # search fired for this decision ({evicted, candidates, searched,
    # cost, search_ms}); None on the (default) no-policy path.
    preemption: Optional[dict] = None

    def to_dict(self) -> dict:
        # NOT dataclasses.asdict: its recursive deep-copy costs ~100x a
        # shallow copy and rides the serving path via the trace sink.
        out = dict(self.__dict__)
        out["failed_nodes"] = dict(self.failed_nodes)
        out["phases"] = {k: round(v, 3) for k, v in self.phases.items()}
        for key in ("solve", "demand", "preemption"):
            v = out[key]
            if v is not None:
                out[key] = dict(v)
        return out


class FlightRecorder:
    """Bounded ring of DecisionRecords + query/annotate surface."""

    # Per-record bound on the stored failure map: the reason message is
    # near-always uniform across nodes, and an unbounded map at 10k nodes
    # x 2048 ring slots is gigabytes. The extender's wire response keeps
    # the full map either way; the record keeps the first
    # MAX_FAILED_NODES entries plus a truncation marker with the count.
    MAX_FAILED_NODES = 256

    def __init__(self, capacity: int = 2048, clock=time.time):
        self._ring: deque[DecisionRecord] = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._clock = clock
        self._seq = itertools.count(1)
        self.capacity = max(1, capacity)
        self.total_recorded = 0
        # Durable trace sink (replay/trace.TraceWriter, ISSUE 17): when
        # attached, every record is ALSO journaled to the trace stream —
        # the ring stays the bounded query surface, the sink the durable
        # one. None keeps record() on the exact pre-sink path.
        self.sink = None

    def attach_sink(self, sink) -> None:
        self.sink = sink

    def record(
        self,
        *,
        namespace: str,
        pod_name: str,
        app_id: str,
        instance_group: str,
        role: str,
        verdict: str,
        node: Optional[str] = None,
        message: str = "",
        failed_nodes: Optional[dict[str, str]] = None,
        queue_position: Optional[int] = None,
        phases: Optional[dict[str, float]] = None,
        solve: Optional[dict] = None,
        device_id: Optional[str] = None,
        state_upload: Optional[str] = None,
        fused_k: Optional[int] = None,
        dispatch_id: Optional[int] = None,
        degraded: Optional[bool] = None,
        redispatches: Optional[int] = None,
        preemption: Optional[dict] = None,
    ) -> DecisionRecord:
        if (
            failed_nodes
            and len(failed_nodes) > self.MAX_FAILED_NODES
            # A map the producer already capped (build_failure_map) —
            # re-truncating would clobber its count with an
            # off-by-the-marker one.
            and TRUNCATION_KEY not in failed_nodes
        ):
            total = len(failed_nodes)
            failed_nodes = dict(
                itertools.islice(
                    failed_nodes.items(), self.MAX_FAILED_NODES
                )
            )
            failed_nodes[TRUNCATION_KEY] = _truncation_marker(
                total - self.MAX_FAILED_NODES
            )
        rec = DecisionRecord(
            seq=next(self._seq),
            time=self._clock(),
            namespace=namespace,
            pod_name=pod_name,
            app_id=app_id,
            instance_group=instance_group,
            role=role,
            verdict=verdict,
            node=node,
            message=message,
            failed_nodes=failed_nodes or {},
            queue_position=queue_position,
            phases=phases or {},
            solve=solve,
            device_id=device_id,
            state_upload=state_upload,
            fused_k=fused_k,
            dispatch_id=dispatch_id,
            degraded=degraded,
            redispatches=redispatches,
            preemption=preemption,
        )
        with self._lock:
            self._ring.append(rec)
            self.total_recorded += 1
        s = self.sink
        if s is not None:
            s.on_decision(rec)
        return rec

    def build_failure_map(self, node_names, reason: str) -> dict[str, str]:
        """A per-node failure map capped at MAX_FAILED_NODES entries (plus
        the truncation marker), built WITHOUT materializing the full map —
        the producer-side half of the truncation protocol (record() guards
        against double-truncating a map built here)."""
        out: dict[str, str] = {}
        names = list(node_names)
        for name in names:
            if len(out) >= self.MAX_FAILED_NODES:
                out[TRUNCATION_KEY] = _truncation_marker(
                    len(names) - self.MAX_FAILED_NODES
                )
                break
            out[name] = reason
        return out

    def query(
        self,
        app: Optional[str] = None,
        verdict: Optional[str] = None,
        role: Optional[str] = None,
        namespace: Optional[str] = None,
        limit: int = 100,
        instance_group: Optional[str] = None,
        since_seq: Optional[int] = None,
    ) -> list[dict]:
        """Newest-first records matching the filters. `verdict` matches
        exactly, or by prefix when it ends with '*' ("failure-*");
        `since_seq` keeps only records NEWER than that sequence number
        (incident-triage tailing: poll with the last seq you saw)."""
        out: list[dict] = []
        with self._lock:
            records = list(self._ring)
        for rec in reversed(records):
            if since_seq is not None and rec.seq <= since_seq:
                continue
            if app is not None and rec.app_id != app:
                continue
            if namespace is not None and rec.namespace != namespace:
                continue
            if role is not None and rec.role != role:
                continue
            if instance_group is not None and rec.instance_group != instance_group:
                continue
            if verdict is not None:
                if verdict.endswith("*"):
                    if not rec.verdict.startswith(verdict[:-1]):
                        continue
                elif rec.verdict != verdict:
                    continue
            out.append(rec.to_dict())
            if len(out) >= max(1, limit):
                break
        return out

    def latest_for_app(
        self, namespace: str, app_id: str, role: str = "driver"
    ) -> Optional[DecisionRecord]:
        """The newest record for (namespace, app_id, role) — the soak's
        verdict-vs-placement cross-check reads this."""
        with self._lock:
            for rec in reversed(self._ring):
                if (
                    rec.namespace == namespace
                    and rec.app_id == app_id
                    and rec.role == role
                ):
                    return rec
        return None

    def annotate_demand_fulfilled(
        self, namespace: str, pod_name: str, latency_s: float, now: float
    ) -> bool:
        """Stamp the newest DEMAND-CREATING denial of `pod_name` with its
        demand's fulfillment — called by the autoscaler when a demand this
        scheduler created flips to fulfilled. Only fit/earlier-driver
        denials create demands, so only those match (a later
        failure-internal retry of the same pod must not swallow the
        annotation). Returns False when no matching denial is in the ring
        (aged out, or the demand predates this process)."""
        with self._lock:
            for rec in reversed(self._ring):
                if (
                    rec.namespace == namespace
                    and rec.pod_name == pod_name
                    and rec.verdict in DEMAND_CREATING_VERDICTS
                ):
                    rec.demand = {
                        "fulfilled_at": now,
                        "latency_s": round(latency_s, 6),
                    }
                    return True
        return False

    def stats(self) -> dict:
        with self._lock:
            size = len(self._ring)
        return {
            "capacity": self.capacity,
            "size": size,
            "total_recorded": self.total_recorded,
            "dropped": max(0, self.total_recorded - size),
        }
