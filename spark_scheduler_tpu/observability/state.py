"""GET /debug/state — one point-in-time snapshot of the scheduler's world.

The reference's operators reconstruct this by joining four kubectl queries
(reservations, demands, pending pods, node list); here it is one gated
endpoint: hard reservations (driver + executor slots with bound pods), soft
reservations, the FIFO queue in enforcement order with per-driver queue
positions, the unschedulable set (PodExceedsClusterCapacity), the demand
ledger, and the node fleet (with the autoscaler's view when it runs
in-process). Point-in-time, not transactional: each section lists its own
store, the same consistency every reporter tick has.
"""

from __future__ import annotations

import time

from spark_scheduler_tpu.core.sparkpods import (
    SPARK_APP_ID_LABEL,
    find_instance_group,
)
from spark_scheduler_tpu.core.unschedulable import (
    POD_EXCEEDS_CLUSTER_CAPACITY_CONDITION,
)


def debug_state_snapshot(app, clock=time.time) -> dict:
    now = clock()

    hard = []
    for rr in app.rr_cache.list():
        hard.append(
            {
                "namespace": rr.namespace,
                "name": rr.name,
                "reservations": {
                    slot: r.node for slot, r in rr.spec.reservations.items()
                },
                "bound_pods": dict(rr.status.pods),
            }
        )

    soft = {
        app_id: {name: r.node for name, r in sr.reservations.items()}
        for app_id, sr in app.soft_store.get_all_copy().items()
    }

    ig_label = app.pod_lister.instance_group_label
    fifo = []
    for pos, pod in enumerate(app.pod_lister.list_pending_drivers()):
        fifo.append(
            {
                "position": pos,
                "namespace": pod.namespace,
                "name": pod.name,
                "app_id": pod.labels.get(SPARK_APP_ID_LABEL, ""),
                "instance_group": find_instance_group(pod, ig_label) or "",
                "age_s": round(max(0.0, now - pod.creation_timestamp), 3),
            }
        )

    unschedulable = []
    for pod in app.backend.list_pods():
        cond = pod.get_condition(POD_EXCEEDS_CLUSTER_CAPACITY_CONDITION)
        if cond is not None and cond.status:
            unschedulable.append(
                {"namespace": pod.namespace, "name": pod.name}
            )

    try:
        demand_objs = app.backend.list("demands")
    except Exception:  # backend without the Demand CRD surface
        demand_objs = []
    demands = [
        {
            "namespace": d.namespace,
            "name": d.name,
            "phase": d.status.phase,
            "instance_group": d.spec.instance_group,
        }
        for d in demand_objs
    ]

    nodes = app.backend.list_nodes()
    by_zone: dict[str, int] = {}
    schedulable = 0
    for n in nodes:
        by_zone[n.zone] = by_zone.get(n.zone, 0) + 1
        if not n.unschedulable and n.ready:
            schedulable += 1
    fleet = {
        "count": len(nodes),
        "schedulable": schedulable,
        "by_zone": by_zone,
    }
    if app.autoscaler is not None:
        fleet["autoscaler"] = {
            "enabled": True,
            "max_cluster_size": app.autoscaler.max_cluster_size,
        }

    out = {
        "time": now,
        "hard_reservations": hard,
        "soft_reservations": soft,
        "fifo_queue": fifo,
        "unschedulable": unschedulable,
        "demands": demands,
        "nodes": fleet,
    }
    recorder = getattr(app, "recorder", None)
    if recorder is not None:
        out["flight_recorder"] = recorder.stats()
    trace_writer = getattr(app, "trace_writer", None)
    if trace_writer is not None:
        out["trace"] = trace_writer.stats()
    features = getattr(getattr(app, "extender", None), "features", None)
    if features is not None:
        # Host feature store: how often per-window featurize actually
        # re-walked state vs served the resident snapshot (the O(changed)
        # evidence, live).
        out["feature_store"] = features.stats()
    solver = getattr(app, "solver", None)
    if solver is not None:
        # Fault tolerance (ISSUE 9): device-slot quarantine state, the
        # degraded-mode controller, and how many partitions were ever
        # re-dispatched onto a survivor — the operator's first stop when
        # readiness reports degraded.
        health = solver.device_health()
        faults = {
            "device": health,
            "redispatches": solver.redispatch_count,
        }
        degraded = getattr(solver, "degraded", None)
        if degraded is not None:
            faults["degraded"] = degraded.snapshot()
        out["faults"] = faults
        prune = getattr(solver, "prune_stats", None)
        if prune is not None and prune.get("windows"):
            # Two-tier solve: pruned-window volume, kept-row ratio, the
            # certificate-escalation ledger by reason, and (ISSUE 12) the
            # O(K + changed) planner evidence — phase-time means, reuse
            # hits and the rows-scanned ledger. Deep-copy the nested
            # reasons ledger: sharing the live dict with the solve
            # thread would let a concurrent escalation resize it under
            # this snapshot's JSON serialization.
            windows = max(int(prune.get("windows", 0)), 1)
            block = {**prune, "reasons": dict(prune["reasons"])}
            for phase in ("plan", "gather", "offset"):
                block[f"{phase}_ms_mean"] = round(
                    prune.get(f"{phase}_ms", 0.0) / windows, 4
                )
            planner = getattr(solver, "_planner", None)
            if planner is not None:
                block["planner"] = planner.index_stats()
            out["prune"] = block
        # Million-node tier (ISSUE 11): device-state upload mix (full vs
        # availability-delta vs static-row-delta, with total bytes) and
        # the scale-tier escalation re-solve ledger when engaged.
        dev_state = getattr(solver, "device_state_stats", None)
        if dev_state is not None:
            out["device_state"] = dict(dev_state)
        # O(K + changed) tensor build (ISSUE 13): per-window build wall
        # time, the dense-sweep vs dirty-set row ledgers (the "O(changed)
        # is a counter, not a narrative" block), and the incremental vs
        # full resident-snapshot mix.
        build = getattr(solver, "build_stats", None)
        if build is not None and build.get("builds"):
            # `pooled_debit_rows` rides along (ISSUE 15): the rows pooled
            # fetches debited sparsely — the pooled path's O(placed)
            # mirror-sync evidence next to `mirror_dense_syncs`.
            block = dict(build)
            block["build_ms_mean"] = round(
                build["build_ms"] / max(int(build["builds"]), 1), 4
            )
            out["build"] = block
        # Multi-device engine: per-slot upload mix + the delta-synced
        # availability-mirror counters (ISSUE 15 — catchup/delta_rows/
        # dense per slot).
        pool_stats = solver.device_pool_stats()
        if pool_stats:
            out["device_pool"] = pool_stats
        scale = getattr(solver, "scale_tier_stats", None)
        if scale is not None and any(scale.values()):
            out["scale_tier"] = dict(scale)
    autoscaler = getattr(app, "autoscaler", None)
    census = getattr(autoscaler, "_census", None)
    if census is not None:
        # Control-loop census: the resident node/busy/reserved mirrors the
        # autoscaler and drainer read instead of per-pass full walks.
        out["census"] = census.stats()
    return out
