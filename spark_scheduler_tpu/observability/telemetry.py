"""SolverTelemetry — the solver-internals hook surface.

The reference publishes every serving subsystem's internals as tagged
metrics (metrics/metrics.go discipline); the JAX hot path has internals the
Go original never had — XLA compiles, padding-bucket occupancy, the
dispatch-before-fetch pipeline, host<->device transfers — and this module
makes them first-class `foundry.spark.scheduler.solver.*` series.

Compile accounting rides jax.monitoring: ONE process-wide listener counts
`backend_compile_duration` events (each is one real XLA/Mosaic compile; the
jitted-call fast path emits nothing, so an unchanged count across a
dispatch IS a compile-cache hit). A listener per SolverTelemetry would leak
— jax offers no unregister, and the test matrix builds hundreds of apps —
so instances read the shared totals against a construction-time baseline.
"""

from __future__ import annotations

import threading

from spark_scheduler_tpu.metrics.registry import MetricRegistry

JIT_COMPILES = "foundry.spark.scheduler.solver.jit.compiles"
JIT_COMPILE_SECONDS = "foundry.spark.scheduler.solver.jit.compile.seconds"
WINDOW_DISPATCHES = "foundry.spark.scheduler.solver.window.dispatches"
BUCKET_OCCUPANCY = "foundry.spark.scheduler.solver.bucket.occupancy"
PIPELINE_EVENTS = "foundry.spark.scheduler.solver.pipeline.events"
TRANSFER_BYTES = "foundry.spark.scheduler.solver.transfer.bytes"
SOLO_PACKS = "foundry.spark.scheduler.solver.packs"

# The one real-compile event (trace/lowering events also fire per compile
# but would triple-count).
_COMPILE_EVENT = "backend_compile"

_totals = {"count": 0, "seconds": 0.0}
_totals_lock = threading.Lock()
_listener_state = {"installed": False}


def _install_listener() -> None:
    if _listener_state["installed"]:
        return
    with _totals_lock:
        if _listener_state["installed"]:
            return
        try:
            from jax import monitoring

            def _on_duration(event: str, duration: float, **kw) -> None:
                if _COMPILE_EVENT in event:
                    # jax calls listeners from the compiling thread; the
                    # GIL makes these two updates effectively atomic
                    # enough for telemetry, but take the lock anyway —
                    # compiles are rare and the lock is uncontended.
                    with _totals_lock:
                        _totals["count"] += 1
                        _totals["seconds"] += float(duration)

            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            pass  # jax without monitoring: compile stats stay zero
        _listener_state["installed"] = True


def compile_stats() -> dict:
    """Process-wide XLA compile totals since the listener was installed."""
    _install_listener()
    with _totals_lock:
        return dict(_totals)


class SolverTelemetry:
    """Publishes solver internals into a tagged registry. Hook methods are
    cheap (a counter/histogram touch) and only ever called guarded by
    `solver.telemetry is not None`."""

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()
        _install_listener()
        # Baseline so this scheduler reports ITS compiles, not the whole
        # process's history (test matrices build many apps per process).
        self._base = compile_stats()

    # -- compiles ------------------------------------------------------------

    def compile_count(self) -> int:
        return compile_stats()["count"] - self._base["count"]

    def sync_compile_gauges(self) -> None:
        cur = compile_stats()
        self.registry.gauge(JIT_COMPILES).set(
            cur["count"] - self._base["count"]
        )
        self.registry.gauge(JIT_COMPILE_SECONDS).set(
            round(cur["seconds"] - self._base["seconds"], 6)
        )

    # -- windows / packs -----------------------------------------------------

    def on_window_dispatch(
        self,
        path: str,
        *,
        nodes: int,
        rows: int,
        row_bucket: int,
        segment_bucket: int = 1,
    ) -> None:
        """One dispatched window solve: count it per device path and record
        how full its padding bucket was (padding is pure waste the compile
        cache buys; occupancy says whether the bucket grid fits the
        workload)."""
        self.registry.counter(WINDOW_DISPATCHES, path=path).inc()
        denom = max(1, row_bucket * segment_bucket)
        self.registry.histogram(
            BUCKET_OCCUPANCY,
            nodes=str(nodes),
            apps=str(row_bucket * segment_bucket),
            path=path,
        ).update(min(1.0, rows / denom))
        self.sync_compile_gauges()

    def on_pack(self, *, nodes: int, emax: int) -> None:
        self.registry.counter(
            SOLO_PACKS, nodes=str(nodes), emax=str(emax)
        ).inc()
        self.sync_compile_gauges()

    # -- pipeline ------------------------------------------------------------

    def on_pipeline_event(self, event: str) -> None:
        """drain | discard | fetch-failure — the pipelined serving loop's
        exceptional paths, countable so a drain storm is visible."""
        self.registry.counter(PIPELINE_EVENTS, event=event).inc()

    # -- transfers -----------------------------------------------------------

    def on_transfer(self, direction: str, nbytes: int) -> None:
        """Host->device ("h2d") / device->host ("d2h") bytes the serving
        path actually ships (delta rows, full uploads, decision blobs)."""
        if nbytes > 0:
            self.registry.counter(TRANSFER_BYTES, direction=direction).inc(
                int(nbytes)
            )
