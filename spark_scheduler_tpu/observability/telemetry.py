"""SolverTelemetry — the solver-internals hook surface.

The reference publishes every serving subsystem's internals as tagged
metrics (metrics/metrics.go discipline); the JAX hot path has internals the
Go original never had — XLA compiles, padding-bucket occupancy, the
dispatch-before-fetch pipeline, host<->device transfers — and this module
makes them first-class `foundry.spark.scheduler.solver.*` series.

Compile accounting rides jax.monitoring: ONE process-wide listener counts
`backend_compile_duration` events (each is one real XLA/Mosaic compile; the
jitted-call fast path emits nothing, so an unchanged count across a
dispatch IS a compile-cache hit). A listener per SolverTelemetry would leak
— jax offers no unregister, and the test matrix builds hundreds of apps —
so instances read the shared totals against a construction-time baseline.
"""

from __future__ import annotations

import threading

from spark_scheduler_tpu.metrics.registry import MetricRegistry

JIT_COMPILES = "foundry.spark.scheduler.solver.jit.compiles"
JIT_COMPILE_SECONDS = "foundry.spark.scheduler.solver.jit.compile.seconds"
WINDOW_DISPATCHES = "foundry.spark.scheduler.solver.window.dispatches"
BUCKET_OCCUPANCY = "foundry.spark.scheduler.solver.bucket.occupancy"
PIPELINE_EVENTS = "foundry.spark.scheduler.solver.pipeline.events"
TRANSFER_BYTES = "foundry.spark.scheduler.solver.transfer.bytes"
SOLO_PACKS = "foundry.spark.scheduler.solver.packs"
# Multi-device window-solve engine (core/solver.py _DevicePool): per-slot
# series tagged device=<label>.
DEVICE_UPLOADS = "foundry.spark.scheduler.solver.device.uploads"
DEVICE_INFLIGHT = "foundry.spark.scheduler.solver.device.inflight"
DEVICE_SOLVE_MS = "foundry.spark.scheduler.solver.device.solve.ms"
DEVICE_FETCH_MS = "foundry.spark.scheduler.solver.device.fetch.ms"
DEVICE_RESIDENT_AGE = (
    "foundry.spark.scheduler.solver.device.resident.age.seconds"
)
# Per-slot delta-synced availability mirrors (ISSUE 15): rows scattered
# by delta catch-ups, full availability re-ships ("dense" syncs — the
# number the pooled tier drives to 0 on pruned traffic), and catch-up
# events, each tagged device=<label>.
DEVICE_MIRROR_DELTA_ROWS = (
    "foundry.spark.scheduler.solver.device.mirror.delta.rows"
)
DEVICE_MIRROR_DENSE_SYNCS = (
    "foundry.spark.scheduler.solver.device.mirror.dense.syncs"
)
DEVICE_MIRROR_CATCHUP = (
    "foundry.spark.scheduler.solver.device.mirror.catchup"
)
# Device-slot quarantine/recovery (ISSUE 9, core/solver.py _DevicePool):
# events tagged event=quarantine|reinstate|redispatch|probe-failed and a
# live count of quarantined slots.
DEVICE_QUARANTINE_EVENTS = (
    "foundry.spark.scheduler.solver.device.quarantine.events"
)
DEVICE_QUARANTINE_ACTIVE = (
    "foundry.spark.scheduler.solver.device.quarantine.active"
)
# Fault-tolerance subsystem (spark_scheduler_tpu/faults/): injected-fault
# counts per surface, retry-ladder activity, breaker state, and the
# degraded-mode gauge readiness keys on.
FAULTS_INJECTED = "foundry.spark.scheduler.faults.injected"
FAULTS_DEGRADED_ACTIVE = "foundry.spark.scheduler.faults.degraded.active"
RETRY_ATTEMPTS = "foundry.spark.scheduler.retry.attempts"
RETRY_BACKOFF_MS = "foundry.spark.scheduler.retry.backoff.ms"
RETRY_BREAKER_STATE = "foundry.spark.scheduler.retry.breaker.state"
RETRY_BREAKER_OPENS = "foundry.spark.scheduler.retry.breaker.opens"

# Breaker-state gauge encoding (a label would fragment the series).
BREAKER_STATE_VALUES = {"closed": 0, "half-open": 1, "open": 2}
# Fused multi-window dispatch engine (core/solver.py
# pack_windows_dispatch): how many windows each device dispatch carried,
# the per-window share of the dispatch->decisions round trip, and how
# busy the dispatch surface (pool slots / in-flight pipeline) was when a
# new dispatch launched — the upload/solve/fetch overlap actually
# engaging.
PRUNE_WINDOWS = "foundry.spark.scheduler.solver.prune.windows"
PRUNE_ESCALATIONS = "foundry.spark.scheduler.solver.prune.escalations"
PRUNE_KEPT_ROWS = "foundry.spark.scheduler.solver.prune.kept.rows"
PRUNE_KEPT_RATIO = "foundry.spark.scheduler.solver.prune.kept.ratio"
# O(K + changed) planning (ISSUE 12): per-window prune phase wall times
# (prefilter plan / statics+mask gather / zone-sum offset derivation) and
# the statics-gather reuse hits that skip the host gather + re-upload.
PRUNE_PLAN_MS = "foundry.spark.scheduler.solver.prune.plan.ms"
PRUNE_GATHER_MS = "foundry.spark.scheduler.solver.prune.gather.ms"
PRUNE_OFFSET_MS = "foundry.spark.scheduler.solver.prune.offset.ms"
PRUNE_GATHER_REUSE = "foundry.spark.scheduler.solver.prune.gather.reuse"
DISPATCH_FUSED_K = "foundry.spark.scheduler.solver.dispatch.fused.k"
DISPATCH_AMORTIZED_RTT_MS = (
    "foundry.spark.scheduler.solver.dispatch.amortized.rtt.ms"
)
DISPATCH_OVERLAP_OCCUPANCY = (
    "foundry.spark.scheduler.solver.dispatch.overlap.occupancy"
)
# Host featurize (core/feature_store.py): per-window sub-phase wall times
# tagged phase=snapshot|tensors|domains|fifo, and the store's O(changed)
# evidence counters (roster re-walks vs snapshots served resident).
FEATURIZE_MS = "foundry.spark.scheduler.solver.featurize.ms"
FEATURIZE_SNAPSHOTS = "foundry.spark.scheduler.solver.featurize.snapshots"
FEATURIZE_ROSTER_REBUILDS = (
    "foundry.spark.scheduler.solver.featurize.roster.rebuilds"
)
FEATURIZE_USAGE_REFRESHES = (
    "foundry.spark.scheduler.solver.featurize.usage.refreshes"
)
FEATURIZE_OVERHEAD_REFRESHES = (
    "foundry.spark.scheduler.solver.featurize.overhead.refreshes"
)
# O(K + changed) tensor build (ISSUE 13): per-window build wall time, rows
# the DENSE mirror sweep examined (0 in steady state — the fallback), and
# rows the event-fed dirty-set sync examined instead.
BUILD_MS = "foundry.spark.scheduler.solver.build.ms"
BUILD_ROWS_COMPARED = "foundry.spark.scheduler.solver.build.rows.compared"
BUILD_DIRTY_ROWS = "foundry.spark.scheduler.solver.build.dirty.rows"
# Batched multi-arm replay sweeps (ISSUE 18, replay/sweep.py): arm/stream
# shape of the last sweep, lockstep throughput, stacked cross-arm window
# dispatches vs per-lane fallbacks, cross-lane candidate-mask memo hits,
# and the XLA compile wall time booked out of the latency quantiles.
# Published in the sweep report and surfaced under `/debug/trace`.
REPLAY_ARMS = "foundry.spark.scheduler.replay.arms"
REPLAY_STREAMS = "foundry.spark.scheduler.replay.streams"
REPLAY_WINDOWS_PER_S = "foundry.spark.scheduler.replay.windows.per.s"
REPLAY_SHARED_BUILD_HITS = (
    "foundry.spark.scheduler.replay.shared.build.hits"
)
REPLAY_STACKED_DISPATCHES = (
    "foundry.spark.scheduler.replay.stacked.dispatches"
)
REPLAY_LANE_FALLBACKS = "foundry.spark.scheduler.replay.lane.fallbacks"
REPLAY_COMPILE_MS = "foundry.spark.scheduler.replay.compile.ms"

# The one real-compile event (trace/lowering events also fire per compile
# but would triple-count).
_COMPILE_EVENT = "backend_compile"

_totals = {"count": 0, "seconds": 0.0}
_totals_lock = threading.Lock()
_listener_state = {"installed": False}


def _install_listener() -> None:
    if _listener_state["installed"]:
        return
    with _totals_lock:
        if _listener_state["installed"]:
            return
        try:
            from jax import monitoring

            def _on_duration(event: str, duration: float, **kw) -> None:
                if _COMPILE_EVENT in event:
                    # jax calls listeners from the compiling thread; the
                    # GIL makes these two updates effectively atomic
                    # enough for telemetry, but take the lock anyway —
                    # compiles are rare and the lock is uncontended.
                    with _totals_lock:
                        _totals["count"] += 1
                        _totals["seconds"] += float(duration)

            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            pass  # jax without monitoring: compile stats stay zero
        _listener_state["installed"] = True


def compile_stats() -> dict:
    """Process-wide XLA compile totals since the listener was installed."""
    _install_listener()
    with _totals_lock:
        return dict(_totals)


class SolverTelemetry:
    """Publishes solver internals into a tagged registry. Hook methods are
    cheap (a counter/histogram touch) and only ever called guarded by
    `solver.telemetry is not None`."""

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()
        _install_listener()
        # Baseline so this scheduler reports ITS compiles, not the whole
        # process's history (test matrices build many apps per process).
        self._base = compile_stats()

    # -- compiles ------------------------------------------------------------

    def compile_count(self) -> int:
        return compile_stats()["count"] - self._base["count"]

    def sync_compile_gauges(self) -> None:
        cur = compile_stats()
        self.registry.gauge(JIT_COMPILES).set(
            cur["count"] - self._base["count"]
        )
        self.registry.gauge(JIT_COMPILE_SECONDS).set(
            round(cur["seconds"] - self._base["seconds"], 6)
        )

    # -- windows / packs -----------------------------------------------------

    def on_window_dispatch(
        self,
        path: str,
        *,
        nodes: int,
        rows: int,
        row_bucket: int,
        segment_bucket: int = 1,
    ) -> None:
        """One dispatched window solve: count it per device path and record
        how full its padding bucket was (padding is pure waste the compile
        cache buys; occupancy says whether the bucket grid fits the
        workload)."""
        self.registry.counter(WINDOW_DISPATCHES, path=path).inc()
        denom = max(1, row_bucket * segment_bucket)
        self.registry.histogram(
            BUCKET_OCCUPANCY,
            nodes=str(nodes),
            apps=str(row_bucket * segment_bucket),
            path=path,
        ).update(min(1.0, rows / denom))
        self.sync_compile_gauges()

    def on_featurize(self, phases: dict, store=None) -> None:
        """One serving window's host-featurize breakdown. `phases` maps
        record keys ("featurize_snapshot_ms", ...) to wall ms; `store` is
        the HostFeatureStore whose counters become gauges (how often the
        roster/usage/overhead actually refreshed vs served resident)."""
        for key, ms in phases.items():
            phase = key[len("featurize_"):]
            if phase.endswith("_ms"):
                phase = phase[:-3]
            self.registry.histogram(FEATURIZE_MS, phase=phase).update(ms)
        if store is not None:
            self.registry.gauge(FEATURIZE_SNAPSHOTS).set(store.snapshots)
            self.registry.gauge(FEATURIZE_ROSTER_REBUILDS).set(
                store.roster_rebuilds
            )
            self.registry.gauge(FEATURIZE_USAGE_REFRESHES).set(
                store.usage_refreshes
            )
            self.registry.gauge(FEATURIZE_OVERHEAD_REFRESHES).set(
                store.overhead_refreshes
            )

    def on_pack(self, *, nodes: int, emax: int) -> None:
        self.registry.counter(
            SOLO_PACKS, nodes=str(nodes), emax=str(emax)
        ).inc()
        self.sync_compile_gauges()

    # -- fused dispatch ------------------------------------------------------

    def on_fused_dispatch(self, fused_k: int, occupancy: float) -> None:
        """One fused multi-window dispatch: its batch size (fused_k = 1
        means the fused claim found only one window's worth of backlog)
        and the dispatch surface's busy fraction at launch."""
        self.registry.histogram(DISPATCH_FUSED_K).update(fused_k)
        self.registry.histogram(DISPATCH_OVERLAP_OCCUPANCY).update(
            round(occupancy, 4)
        )

    def on_dispatch_complete(
        self, amortized_rtt_ms: float, fused_k: int
    ) -> None:
        """Dispatch -> decisions-on-host wall time per WINDOW of the
        dispatch (the fused batch divides one device round trip by K)."""
        self.registry.histogram(
            DISPATCH_AMORTIZED_RTT_MS, fused=str(fused_k)
        ).update(round(amortized_rtt_ms, 3))

    # -- device pool ---------------------------------------------------------

    def on_device_upload(self, device: str, kind: str, nbytes: int = 0) -> None:
        """One resident-replica decision on a pool slot: kind is
        "full" (statics re-uploaded) or "reuse" (resident copy served)."""
        self.registry.counter(DEVICE_UPLOADS, device=device, kind=kind).inc()
        if nbytes > 0:
            self.on_transfer("h2d", nbytes)

    def on_device_mirror(
        self, device: str, kind: str, rows: int, nbytes: int = 0
    ) -> None:
        """One per-slot availability-mirror sync (ISSUE 15): "catchup" =
        a lagging slot scattered `rows` journaled rows instead of taking
        the full [N,3] base; "dense" = the full re-ship (no replica, a
        journal gap, or an unknowable epoch in the chain)."""
        if kind == "catchup":
            self.registry.counter(DEVICE_MIRROR_CATCHUP, device=device).inc()
            if rows:
                self.registry.counter(
                    DEVICE_MIRROR_DELTA_ROWS, device=device
                ).inc(int(rows))
        else:
            self.registry.counter(
                DEVICE_MIRROR_DENSE_SYNCS, device=device
            ).inc()
        if nbytes > 0:
            self.on_transfer("h2d", nbytes)

    def on_device_inflight(self, device: str, inflight: int) -> None:
        """Dispatched-but-unfetched window solves currently on the slot."""
        self.registry.gauge(DEVICE_INFLIGHT, device=device).set(inflight)

    def on_device_age(self, device: str, age_s: float) -> None:
        """Seconds since the slot's resident state was last fully uploaded
        — a cold replica explains a latency outlier on that device."""
        self.registry.gauge(DEVICE_RESIDENT_AGE, device=device).set(
            round(age_s, 3)
        )

    def on_device_window(
        self, device: str, solve_ms: float, fetch_ms: float,
        inflight: int | None = None,
    ) -> None:
        """Per-slot phase wall times of one window (or window partition):
        device solve vs decision-blob fetch."""
        self.registry.histogram(DEVICE_SOLVE_MS, device=device).update(
            solve_ms
        )
        self.registry.histogram(DEVICE_FETCH_MS, device=device).update(
            fetch_ms
        )
        if inflight is not None:
            self.on_device_inflight(device, inflight)

    # -- quarantine / degraded (ISSUE 9) -------------------------------------

    def on_slot_event(self, event: str, device: str) -> None:
        """quarantine | reinstate | redispatch | probe-failed — the
        slot-failure recovery machinery's countable transitions."""
        self.registry.counter(
            DEVICE_QUARANTINE_EVENTS, event=event, device=device
        ).inc()

    def on_quarantine_count(self, count: int) -> None:
        self.registry.gauge(DEVICE_QUARANTINE_ACTIVE).set(int(count))

    def on_degraded(self, active: bool) -> None:
        self.registry.gauge(FAULTS_DEGRADED_ACTIVE).set(1 if active else 0)

    # -- candidate pruning (the two-tier solve) ------------------------------

    def on_prune_dispatch(self, kept_rows: int, candidate_rows: int) -> None:
        """One window (or pooled partition) served over a pruned top-K
        gather: how many rows the device actually solved vs the domain's
        full candidate count."""
        self.registry.counter(PRUNE_WINDOWS).inc()
        self.registry.histogram(PRUNE_KEPT_ROWS).update(kept_rows)
        if candidate_rows > 0:
            self.registry.histogram(PRUNE_KEPT_RATIO).update(
                round(kept_rows / candidate_rows, 4)
            )

    def on_prune_escalation(self, reason: str) -> None:
        """A failed soundness certificate: the window re-solved on the
        exact full path. Labeled by the first failed test so a hot
        escalation reason is visible."""
        self.registry.counter(PRUNE_ESCALATIONS, reason=reason).inc()

    def on_prune_phases(
        self, plan_ms: float, gather_ms: float, offset_ms: float
    ) -> None:
        """One pruned window's host-side phase split: prefilter planning,
        statics/mask gather (+ upload staging), and the zone-sum offset
        derivation — the O(K + changed) claim as wall times."""
        self.registry.histogram(PRUNE_PLAN_MS).update(round(plan_ms, 4))
        self.registry.histogram(PRUNE_GATHER_MS).update(round(gather_ms, 4))
        self.registry.histogram(PRUNE_OFFSET_MS).update(round(offset_ms, 4))

    def on_prune_gather_reuse(self) -> None:
        """A pruned window re-served the previous window's gathered
        statics sub-blob (kept rows and their static fields unchanged):
        no host gather, no h2d re-upload."""
        self.registry.counter(PRUNE_GATHER_REUSE).inc()

    # -- tensor build (ISSUE 13) ---------------------------------------------

    def on_build(
        self, ms: float, rows_compared: int, dirty_rows: int
    ) -> None:
        """One pipelined tensor build: wall time, rows the dense mirror
        sweep examined (the fallback — 0 in steady state, the O(changed)
        claim as a counter), and rows the event-fed dirty-set sync
        examined."""
        self.registry.histogram(BUILD_MS).update(round(ms, 4))
        if rows_compared:
            self.registry.counter(BUILD_ROWS_COMPARED).inc(
                int(rows_compared)
            )
        if dirty_rows:
            self.registry.counter(BUILD_DIRTY_ROWS).inc(int(dirty_rows))

    # -- pipeline ------------------------------------------------------------

    def on_pipeline_event(self, event: str) -> None:
        """drain | discard | fetch-failure — the pipelined serving loop's
        exceptional paths, countable so a drain storm is visible."""
        self.registry.counter(PIPELINE_EVENTS, event=event).inc()

    # -- transfers -----------------------------------------------------------

    def on_transfer(self, direction: str, nbytes: int) -> None:
        """Host->device ("h2d") / device->host ("d2h") bytes the serving
        path actually ships (delta rows, full uploads, decision blobs)."""
        if nbytes > 0:
            self.registry.counter(TRANSFER_BYTES, direction=direction).inc(
                int(nbytes)
            )


class RetryTelemetry:
    """`foundry.spark.scheduler.retry.*` — the shared retry ladder's
    activity, tagged by consumer (kube-write-back, lease, reflector,
    autoscaler) so one hammering consumer is attributable."""

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()

    def on_retry(self, consumer: str, attempt: int, backoff_s: float) -> None:
        self.registry.counter(RETRY_ATTEMPTS, consumer=consumer).inc()
        self.registry.histogram(RETRY_BACKOFF_MS, consumer=consumer).update(
            round(backoff_s * 1e3, 3)
        )

    def retry_hook(self, consumer: str):
        """fn(attempt, exc, pause) for RetryPolicy.call's on_retry."""

        def hook(attempt, exc, pause) -> None:
            self.on_retry(consumer, attempt, pause)

        return hook

    def breaker_hook(self, consumer: str):
        """fn(old, new) for CircuitBreaker's on_transition."""

        def hook(old: str, new: str) -> None:
            self.registry.gauge(
                RETRY_BREAKER_STATE, consumer=consumer
            ).set(BREAKER_STATE_VALUES.get(new, -1))
            if new == "open":
                self.registry.counter(
                    RETRY_BREAKER_OPENS, consumer=consumer
                ).inc()

        return hook

    def fault_hook(self):
        """fn(surface, action) for FaultInjector.on_fire: per-surface
        injected-fault counts, so a chaos run's blast radius reads
        straight off /metrics."""

        def hook(surface: str, action: str) -> None:
            self.registry.counter(
                FAULTS_INJECTED, surface=surface, action=action
            ).inc()

        return hook


class TransportTelemetry:
    """`foundry.spark.scheduler.server.*` — HTTP transport internals.

    The event-loop transport mutates the phase accumulators (`parse_s`,
    `queue_s`, `write_s`, `bytes_in/out`) directly from its single loop
    thread — no lock on the hot path; the method hooks (connections,
    requests, sheds) take the lock because the threaded transport calls
    them from many handler threads. `stats()` renders the snapshot that
    GET /metrics surfaces (JSON key `server_transport`, Prometheus extra
    gauges under the server prefix) — the same pull discipline as the
    predicate batcher's stats."""

    def __init__(self, transport: str, ingest: str = "python"):
        self.transport = transport
        # Which ingest lane the server resolved to (post-degrade): rides
        # the transport snapshot so a scrape shows transport x ingest.
        self.ingest = ingest
        self._lock = threading.Lock()
        self.open_connections = 0
        self.connections_total = 0
        self.requests_total = 0
        # Requests beyond the first on a persistent connection: the
        # keep-alive reuse the transport actually delivered.
        self.keepalive_requests = 0
        self.connection_sheds = 0  # max-connections 503s
        self.queue_sheds = 0  # batcher-depth 503s (routing layer)
        self.body_rejections = 0  # max-body-bytes 413s
        # Phase accumulators (seconds + sample counts): request parse,
        # dispatch->respond (the batcher window for predicates), and
        # response assembly+write.
        self.parse_s = 0.0
        self.parse_samples = 0
        self.queue_s = 0.0
        self.queue_samples = 0
        self.write_s = 0.0
        self.write_samples = 0
        self.bytes_in = 0
        self.bytes_out = 0

    def on_connection_open(self) -> None:
        with self._lock:
            self.open_connections += 1
            self.connections_total += 1

    def on_connection_close(self) -> None:
        with self._lock:
            self.open_connections = max(0, self.open_connections - 1)

    def on_connection_shed(self) -> None:
        with self._lock:
            self.connection_sheds += 1

    def on_queue_shed(self) -> None:
        with self._lock:
            self.queue_sheds += 1

    def on_request(self, *, reused: bool) -> None:
        with self._lock:
            self.requests_total += 1
            if reused:
                self.keepalive_requests += 1

    def on_body_rejected(self) -> None:
        with self._lock:
            self.body_rejections += 1

    def on_bytes_out(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_out += nbytes

    @staticmethod
    def _mean_ms(total_s: float, samples: int):
        return round(total_s * 1e3 / samples, 4) if samples else None

    def stats(self) -> dict:
        requests = self.requests_total
        return {
            "transport": self.transport,
            "ingest": self.ingest,
            "open_connections": self.open_connections,
            "connections_total": self.connections_total,
            "requests_total": requests,
            "keepalive_requests": self.keepalive_requests,
            "keepalive_reuse_ratio": round(
                self.keepalive_requests / requests, 4
            )
            if requests
            else 0.0,
            "connection_sheds": self.connection_sheds,
            "queue_sheds": self.queue_sheds,
            "body_rejections": self.body_rejections,
            "parse_mean_ms": self._mean_ms(self.parse_s, self.parse_samples),
            "queue_mean_ms": self._mean_ms(self.queue_s, self.queue_samples),
            "write_mean_ms": self._mean_ms(self.write_s, self.write_samples),
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
        }


# HA replica runtime (spark_scheduler_tpu/ha/): role, fencing epoch, lease
# age, promotion/reconcile wall times, and fenced-write rejects — the
# series an operator's failover dashboard keys on.
HA_ROLE = "foundry.spark.scheduler.ha.role"
HA_EPOCH = "foundry.spark.scheduler.ha.epoch"
HA_LEASE_AGE = "foundry.spark.scheduler.ha.lease.age.seconds"
HA_PROMOTION_MS = "foundry.spark.scheduler.ha.promotion.ms"
HA_RECONCILE_MS = "foundry.spark.scheduler.ha.reconcile.ms"
HA_FENCED_REJECTS = "foundry.spark.scheduler.ha.fenced.write.rejects"
HA_TAILED_EVENTS = "foundry.spark.scheduler.ha.standby.tailed.events"

# Role gauge encoding (a label would fragment the series per role flip).
HA_ROLE_VALUES = {"standby": 0, "leader": 1, "active": 2, "deposed": -1}


class HATelemetry:
    """`foundry.spark.scheduler.ha.*` — one replica's election state."""

    def __init__(self, registry: MetricRegistry | None = None, replica: str = ""):
        self.registry = registry or MetricRegistry()
        self.replica = replica

    def _tags(self) -> dict:
        return {"replica": self.replica} if self.replica else {}

    def on_role(self, role: str) -> None:
        self.registry.gauge(HA_ROLE, **self._tags()).set(
            HA_ROLE_VALUES.get(role, -1)
        )

    def on_lease(self, epoch: int, age_s) -> None:
        tags = self._tags()
        self.registry.gauge(HA_EPOCH, **tags).set(int(epoch))
        if age_s is not None:
            self.registry.gauge(HA_LEASE_AGE, **tags).set(round(age_s, 3))

    def on_promotion(self, promotion_ms: float, reconcile_ms: float) -> None:
        tags = self._tags()
        self.registry.histogram(HA_PROMOTION_MS, **tags).update(
            round(promotion_ms, 3)
        )
        self.registry.histogram(HA_RECONCILE_MS, **tags).update(
            round(reconcile_ms, 3)
        )

    def on_fenced_reject(self) -> None:
        self.registry.counter(HA_FENCED_REJECTS, **self._tags()).inc()

    def on_tailed(self, applied: int) -> None:
        self.registry.gauge(HA_TAILED_EVENTS, **self._tags()).set(applied)


FLEET_CLUSTERS_LIVE = "foundry.spark.scheduler.fleet.clusters.live"
FLEET_DECISIONS = "foundry.spark.scheduler.fleet.decisions"
FLEET_ROUTER_PICKS = "foundry.spark.scheduler.fleet.router.picks"
FLEET_FORWARDED = "foundry.spark.scheduler.fleet.forwarded"
FLEET_SPILLOVERS = "foundry.spark.scheduler.fleet.spillovers"
FLEET_SPILLOVER_DENIED = "foundry.spark.scheduler.fleet.spillover.denied"
FLEET_ORPHANS_REROUTED = "foundry.spark.scheduler.fleet.orphans.rerouted"
FLEET_AGG_EVENTS = "foundry.spark.scheduler.fleet.aggregate.events.applied"
# Fused fleet dispatch (fleet/dispatch.py, ISSUE 20): stacked launches,
# windows-per-launch, fallback singles, and how long a deferred window
# waited in the gather before its flush.
FLEET_DISPATCH_STACKED = "foundry.spark.scheduler.fleet.dispatch.stacked"
FLEET_DISPATCH_ARMS = "foundry.spark.scheduler.fleet.dispatch.arms"
FLEET_DISPATCH_FALLBACKS = "foundry.spark.scheduler.fleet.dispatch.fallbacks"
FLEET_DISPATCH_GATHER_WAIT_MS = (
    "foundry.spark.scheduler.fleet.dispatch.gather.wait.ms"
)


class FleetTelemetry:
    """`foundry.spark.scheduler.fleet.*` — the facade's two-level serving
    surface: live cluster count, per-cluster decision counters, router
    pick reasons, spillovers by (from, to), and aggregate freshness."""

    def __init__(self, registry: MetricRegistry | None = None):
        self.registry = registry or MetricRegistry()

    def on_live(self, live: int) -> None:
        self.registry.gauge(FLEET_CLUSTERS_LIVE).set(int(live))

    def on_decision(self, cluster: int) -> None:
        self.registry.counter(FLEET_DECISIONS, cluster=str(cluster)).inc()

    def on_pick(self, reason: str) -> None:
        self.registry.counter(FLEET_ROUTER_PICKS, reason=reason).inc()

    def on_forwarded(self) -> None:
        self.registry.counter(FLEET_FORWARDED).inc()

    def on_spillover(self, home: int, sibling: int) -> None:
        self.registry.counter(
            FLEET_SPILLOVERS, from_cluster=str(home), to_cluster=str(sibling)
        ).inc()

    def on_spillover_denied(self, home: int) -> None:
        self.registry.counter(
            FLEET_SPILLOVER_DENIED, from_cluster=str(home)
        ).inc()

    def on_orphans_rerouted(self, n: int) -> None:
        if n:
            self.registry.counter(FLEET_ORPHANS_REROUTED).inc(n)

    def on_aggregate_events(self, cluster: int, applied: int) -> None:
        self.registry.gauge(FLEET_AGG_EVENTS, cluster=str(cluster)).set(
            int(applied)
        )

    # -- fused fleet dispatch (fleet/dispatch.py) ----------------------------

    def on_stacked_dispatch(self, arms: int) -> None:
        self.registry.counter(FLEET_DISPATCH_STACKED).inc()
        self.registry.counter(FLEET_DISPATCH_ARMS).inc(arms)

    def on_stack_fallback(self, reason: str) -> None:
        self.registry.counter(FLEET_DISPATCH_FALLBACKS, reason=reason).inc()

    def on_gather_wait(self, wait_ms: float) -> None:
        self.registry.histogram(FLEET_DISPATCH_GATHER_WAIT_MS).update(
            round(wait_ms, 3)
        )
