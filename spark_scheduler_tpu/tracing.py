"""Request-scoped tracing + safe-param structured logging (SURVEY.md §5.1).

The reference gets zipkin tracing with b3 propagation from witchcraft
middleware (vendor/github.com/palantir/witchcraft-go-tracing) and svc1log
structured logging with *safe params* (internal/logging.go:22-45,
lib pkg/logging/logging.go:23-55). This module provides both natively:

  - `Tracer`: thread-local span stacks; `span()` context manager; b3
    single+multi header extraction/injection (x-b3-traceid / x-b3-spanid /
    x-b3-sampled); finished spans land in a bounded ring buffer (pollable
    at GET /debug/traces) and optionally as JSON lines in a trace log.
  - `svc1log`: JSON-line service log with explicit safe-param dicts —
    `pod_safe_params`, `demand_safe_params`, `rr_safe_params` mirror the
    reference's safe-param helpers so log pipelines receive identical keys.
  - JAX profiler hooks: `start_jax_profile(dir)` / `stop_jax_profile()`
    wrap jax.profiler start/stop_trace for the server's /debug/profile
    routes — a captured trace is inspectable with TensorBoard/XProf.
"""

from __future__ import annotations

import collections
import itertools
import json
import random
import secrets
import sys
import threading
import time
from typing import Any, Optional

_span_counter = itertools.count(1)


class Span:
    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "start",
        "end",
        "tags",
        "sampled",
    )

    def __init__(self, name, trace_id, span_id, parent_id, sampled=True):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = 0.0
        self.end = 0.0
        self.tags: dict[str, Any] = {}
        self.sampled = sampled

    @property
    def duration_ms(self) -> float:
        return (self.end - self.start) * 1e3

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "id": self.span_id,
            **({"parentId": self.parent_id} if self.parent_id else {}),
            "timestamp_s": self.start,
            "duration_ms": round(self.duration_ms, 3),
            "tags": dict(self.tags),
        }


class _SpanContext:
    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def tag(self, key: str, value) -> None:
        self.span.tags[key] = value

    def __enter__(self) -> "_SpanContext":
        self.span.start = self._tracer._clock()
        self._tracer._push(self.span)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.span.end = self._tracer._clock()
        if exc is not None:
            self.span.tags["error"] = repr(exc)
        self._tracer._pop(self.span)


# Span/trace ids need uniqueness, not cryptographic strength — and
# secrets.token_hex is a syscall (urandom) per id, which profiled at ~1 ms
# per serving DECISION at 10k nodes (two ids per select-node span). A
# process-local PRNG seeded once from urandom keeps the id format and
# collision odds while costing nanoseconds. Thread-local: random.Random is
# not safe under concurrent getrandbits.
_id_rng = threading.local()


def _new_id(bits: int = 64) -> str:
    rng = getattr(_id_rng, "rng", None)
    if rng is None:
        rng = _id_rng.rng = random.Random(secrets.randbits(64))
    return f"{rng.getrandbits(bits):0{bits // 4}x}"


class _AttachedContext:
    """Adopt an EXISTING span as another thread's current span: children
    created inside join its trace; the span itself is NOT finished on exit
    (its owner finishes it). Used by the predicate batcher to carry the
    handler thread's b3 context onto the dispatcher thread."""

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> "_AttachedContext":
        self._tracer._push(self.span)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        stack = self._tracer._stack()
        if stack and stack[-1] is self.span:
            stack.pop()


class Tracer:
    """Thread-local span stack + bounded finished-span ring buffer."""

    def __init__(self, capacity: int = 512, log_stream=None, clock=time.time):
        self._local = threading.local()
        self._finished: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._log_stream = log_stream
        self._clock = clock

    # -- context management --------------------------------------------------

    def _stack(self) -> list[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        if span.sampled:
            # Stream write stays under the lock: concurrent handler threads
            # finishing spans must not interleave JSONL lines.
            with self._lock:
                self._finished.append(span)
                if self._log_stream is not None:
                    self._log_stream.write(json.dumps(span.to_dict()) + "\n")

    # -- span creation -------------------------------------------------------

    def span(self, name: str, **tags) -> _SpanContext:
        """Child of the thread's current span, or a new root."""
        parent = self.current()
        if parent is not None:
            s = Span(name, parent.trace_id, _new_id(), parent.span_id, parent.sampled)
        else:
            s = Span(name, _new_id(128), _new_id(), None)
        s.tags.update(tags)
        return _SpanContext(self, s)

    def attach(self, span: Span) -> _AttachedContext:
        """Adopt `span` as this thread's current span (see _AttachedContext)."""
        return _AttachedContext(self, span)

    def root_from_headers(self, headers, name: str, **tags) -> _SpanContext:
        """Continue a b3-propagated trace (witchcraft middleware slot).
        Accepts multi-header b3 (X-B3-TraceId/SpanId/Sampled) and the
        single `b3: traceid-spanid-sampled` form."""
        get = headers.get
        trace_id = get("X-B3-TraceId") or get("x-b3-traceid")
        parent_id = get("X-B3-SpanId") or get("x-b3-spanid")
        sampled_raw = get("X-B3-Sampled") or get("x-b3-sampled")
        single = get("b3") or get("B3")
        if single and not trace_id:
            parts = single.split("-")
            if len(parts) == 1:
                # lone sampling decision: "b3: 0" (deny) / "1" / "d"
                sampled_raw = parts[0]
            if len(parts) >= 2:
                trace_id, parent_id = parts[0], parts[1]
            if len(parts) >= 3:
                sampled_raw = parts[2]
        sampled = sampled_raw not in ("0", "false", "False")
        if trace_id:
            s = Span(name, trace_id, _new_id(), parent_id, sampled)
        else:
            # New root — the sampling decision still applies (lone "b3: 0").
            s = Span(name, _new_id(128), _new_id(), None, sampled)
        s.tags.update(tags)
        return _SpanContext(self, s)

    def inject_headers(self) -> dict[str, str]:
        """b3 headers for outbound calls from the current span."""
        cur = self.current()
        if cur is None:
            return {}
        return {
            "X-B3-TraceId": cur.trace_id,
            "X-B3-SpanId": cur.span_id,
            "X-B3-Sampled": "1" if cur.sampled else "0",
        }

    # -- detached spans ------------------------------------------------------

    def begin_detached(self, span: Span) -> None:
        """Start a span WITHOUT pushing it on this thread's stack. The
        event-loop serving transport cannot hold a request's root span
        open on its (shared, interleaved) loop thread the way a dedicated
        handler thread can — detached spans are timed by hand and land in
        the finished ring via finish_detached."""
        span.start = self._clock()

    def finish_detached(self, span: Span) -> None:
        span.end = self._clock()
        if span.sampled:
            with self._lock:
                self._finished.append(span)
                if self._log_stream is not None:
                    self._log_stream.write(json.dumps(span.to_dict()) + "\n")

    # -- inspection ----------------------------------------------------------

    def finished_spans(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._finished]

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


# Process-wide default tracer: instrumentation points (extender, solver,
# async write-back) call tracer() so embedding programs can swap the sink.
_default_tracer = Tracer()


def tracer() -> Tracer:
    return _default_tracer


def set_tracer(t: Tracer) -> Tracer:
    global _default_tracer
    _default_tracer = t
    return t


# --------------------------------------------------------- JAX profiler

_profile_lock = threading.Lock()
_profile_dir: Optional[str] = None


def start_jax_profile(log_dir: str) -> bool:
    """Start a JAX profiler trace into `log_dir` (device + host timelines).
    Returns False if a trace is already running."""
    global _profile_dir
    import jax

    with _profile_lock:
        if _profile_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _profile_dir = log_dir
        return True


def stop_jax_profile() -> Optional[str]:
    """Stop the running trace; returns its directory (None if not running)."""
    global _profile_dir
    import jax

    with _profile_lock:
        if _profile_dir is None:
            return None
        out, _profile_dir = _profile_dir, None
        # Flag cleared BEFORE stop_trace, and jax's internal profile state
        # force-reset if the flush fails (deleted/unwritable dir): stop_trace
        # skips its own reset() on exception, which would otherwise wedge
        # every future start with "Profile has already been started".
        try:
            jax.profiler.stop_trace()
        except Exception:
            try:
                from jax._src import profiler as _jax_profiler

                with _jax_profiler._profile_state.lock:
                    _jax_profiler._profile_state.reset()
            except Exception:
                pass
            raise
        return out


# --------------------------------------------------- svc1log + safe params


def pod_safe_params(pod) -> dict:
    """internal/logging.go:22-33 (podName/podNamespace + spark labels)."""
    return {
        "podName": pod.name,
        "podNamespace": pod.namespace,
        "podSparkRole": pod.labels.get("spark-role", ""),
        "podSparkAppID": pod.labels.get("spark-app-id", ""),
    }


def demand_safe_params(demand) -> dict:
    """internal/logging.go:35-45 (demand identity + units)."""
    return {
        "demandName": demand.name,
        "demandNamespace": demand.namespace,
        "demandUnits": [
            {"count": u.count, "cpu": u.resources.cpu_milli, "memoryKib": u.resources.mem_kib}
            for u in demand.spec.units
        ],
        "demandInstanceGroup": demand.spec.instance_group,
    }


def rr_safe_params(rr) -> dict:
    """lib pkg/logging/logging.go:23-55 (reservation names/nodes/pods)."""
    return {
        "reservationName": rr.name,
        "reservationNamespace": rr.namespace,
        "reservationNodes": sorted({r.node for r in rr.spec.reservations.values()}),
        "reservationPodNames": sorted(rr.status.pods.values()),
    }


class Svc1Logger:
    """svc1log-shaped JSON lines: explicit params vs unsafe free text is the
    reference's logging discipline; every entry carries the active trace
    context so logs and traces join."""

    LEVELS = {"DEBUG": 0, "INFO": 1, "WARN": 2, "ERROR": 3}

    def __init__(
        self,
        stream=None,
        origin: str = "spark-scheduler-tpu",
        clock=time.time,
        level: str = "INFO",
    ):
        self._stream = stream if stream is not None else sys.stderr
        self._origin = origin
        self._clock = clock
        self._lock = threading.Lock()
        self._min_level = self.LEVELS.get(str(level).upper(), 1)

    def set_level(self, level: str) -> None:
        """Live log-level change — the witchcraft runtime-config reload slot
        (config/config.go:24-47 Runtime embed)."""
        self._min_level = self.LEVELS.get(str(level).upper(), self._min_level)

    @property
    def level(self) -> str:
        for name, rank in self.LEVELS.items():
            if rank == self._min_level:
                return name
        return "INFO"

    def _log(self, level: str, message: str, params: dict | None) -> None:
        if self.LEVELS.get(level, 1) < self._min_level:
            return
        entry = {
            "type": "service.1",
            "level": level,
            "time": self._clock(),
            "origin": self._origin,
            "message": message,
            "params": params or {},
        }
        cur = tracer().current()
        if cur is not None:
            entry["traceId"] = cur.trace_id
            entry["spanId"] = cur.span_id
        with self._lock:
            self._stream.write(json.dumps(entry) + "\n")

    def request(
        self,
        method: str,
        path: str,
        status: int,
        duration_us: int,
        *,
        protocol: str = "HTTP/1.1",
        trace_id: str | None = None,
    ) -> None:
        """Structured per-request access log — the witchcraft req2log slot
        (middleware/route.go:28-48): every HTTP call gets one line with
        method, path, status, duration (microseconds) and trace id.
        Bypasses the service-log level filter (request logs are their own
        stream type in the reference)."""
        entry = {
            "type": "request.2",
            "time": self._clock(),
            "origin": self._origin,
            "method": method,
            "protocol": protocol,
            "path": path,
            "status": int(status),
            "duration": int(duration_us),
        }
        if trace_id:
            entry["traceId"] = trace_id
        with self._lock:
            self._stream.write(json.dumps(entry) + "\n")

    def debug(self, message: str, **params) -> None:
        self._log("DEBUG", message, params)

    def info(self, message: str, **params) -> None:
        self._log("INFO", message, params)

    def warn(self, message: str, **params) -> None:
        self._log("WARN", message, params)

    def error(self, message: str, **params) -> None:
        self._log("ERROR", message, params)


_default_logger = Svc1Logger()


def svc1log() -> Svc1Logger:
    return _default_logger


def set_svc1log(logger: Svc1Logger) -> Svc1Logger:
    global _default_logger
    _default_logger = logger
    return logger
