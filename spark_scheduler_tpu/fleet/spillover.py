"""SpilloverCoordinator — the Demand hand-off routed to a sibling cluster.

The reference's Demand signal (demand.go:58-126) tells an autoscaler
"this gang did not fit — buy capacity". In a fleet there is a cheaper
fulfiller first: a sibling cluster that already HAS the capacity. A
driver denied FAILURE_FIT at its home cluster (its Demand CRD just
created by the extender, exactly as standalone) is retried on the best
siblings in aggregate-headroom order, bounded by `max_hops`:

  placed on a sibling   the home copy is released (pod + demand deleted —
                        the demand was routed to a sibling instead of an
                        autoscaler), the app's affinity re-binds to the
                        sibling so its executors follow, and the hand-off
                        is journaled in the home cluster's FlightRecorder.
  denied everywhere     every sibling copy is released (its denial AND
                        the release are ordinary ops in that sibling's
                        stream — it stays byte-identical to standalone),
                        the home demand STANDS, and the autoscaler path
                        takes over exactly as a single cluster.

Executors never spill: the gang's home is wherever its driver's
reservation lives — spilling an executor would split the gang across
clusters and void the per-cluster byte-identity contract.
"""

from __future__ import annotations

import copy
import dataclasses

from spark_scheduler_tpu.core.extender import ExtenderFilterResult
from spark_scheduler_tpu.core.sparkpods import SPARK_APP_ID_LABEL


@dataclasses.dataclass
class FleetDecision:
    """A facade decision: the in-cluster result plus its fleet routing."""

    result: ExtenderFilterResult
    cluster: int
    spilled_from: int | None = None
    spillover_attempts: int = 0
    unavailable: bool = False

    @property
    def ok(self) -> bool:
        return self.result.ok


class SpilloverCoordinator:
    def __init__(self, stacks, router, telemetry, max_hops: int = 1):
        self._stacks = stacks
        self._router = router
        self._tel = telemetry
        self.max_hops = max(0, int(max_hops))
        self.spilled = 0
        self.denied = 0

    def try_spillover(
        self, pod, app_id: str, group: str, home: int, home_result
    ) -> FleetDecision:
        attempts = 0
        for sib in self._router.siblings(home, group):
            if attempts >= self.max_hops:
                break
            attempts += 1
            # The sibling serves a COPY: the home backend still owns the
            # original pod object until the hand-off commits.
            pod_copy = copy.deepcopy(pod)
            res = self._stacks[sib].schedule(pod_copy, None)
            self._tel.on_decision(sib)
            if res.ok:
                self._stacks[home].release(pod)
                self._router.bind(app_id, sib)
                self._tel.on_spillover(home, sib)
                self.spilled += 1
                self._journal(pod, group, home, sib, res)
                return FleetDecision(
                    res, sib, spilled_from=home,
                    spillover_attempts=attempts,
                )
            # Keep the sibling standalone-equivalent: the failed copy (and
            # the demand its denial created) leaves through the same ops a
            # standalone operator would issue.
            self._stacks[sib].release(pod_copy)
        if attempts:
            self._tel.on_spillover_denied(home)
            self.denied += 1
        return FleetDecision(
            home_result, home, spillover_attempts=attempts
        )

    def _journal(self, pod, group: str, home: int, sib: int, res) -> None:
        recorder = self._stacks[home].app.recorder
        if recorder is None:
            return
        recorder.record(
            namespace=pod.namespace,
            pod_name=pod.name,
            app_id=pod.labels.get(SPARK_APP_ID_LABEL, pod.name),
            instance_group=group,
            role="driver",
            verdict="spillover",
            node=res.node_names[0] if res.node_names else None,
            message=(
                f"demand spilled: home cluster {home} denied fit, "
                f"placed on sibling cluster {sib}"
            ),
        )
