"""FleetRouter — the fleet-level (coarse) half of two-level placement.

Borg's cell pick over per-cell state, Omega's coarse/fine split: choose
the home cluster in O(F) from resident ClusterAggregates, then let that
cluster's unchanged solver stack do the fine placement. Three rules, in
order:

  affinity   an app already routed (driver placed or in flight) keeps its
             home — executors must land beside their driver's
             reservation, and gang identity must stay within one cluster
             for the byte-identity contract to mean anything.
  hosting    only clusters whose node roster hosts the pod's instance
             group are candidates (a group's gangs only place on that
             group's nodes — the PR 4 domain boundary, now fleet-wide).
  headroom   among hosts, argmax free-capacity score with a
             deterministic lowest-index tie-break; no host at all falls
             back to the stable CRC32 membership hash (StableMembership,
             shared with ha/shard.py), so routing stays a pure function
             of (key, membership) even for never-seen groups.
"""

from __future__ import annotations

import threading

from spark_scheduler_tpu.core.membership import StableMembership


class FleetRouter:
    def __init__(self, n_clusters: int, aggregates):
        self.members = StableMembership(n_clusters)
        self._aggs = list(aggregates)
        self._lock = threading.RLock()
        self._affinity: dict[str, int] = {}  # app_id -> home cluster
        self.picks = {"affinity": 0, "hosting": 0, "headroom": 0, "hash": 0}
        self.rerouted_orphans = 0

    # -- affinity ------------------------------------------------------------

    def bind(self, app_id: str, cluster: int) -> None:
        with self._lock:
            self._affinity[app_id] = cluster

    def unbind(self, app_id: str) -> None:
        with self._lock:
            self._affinity.pop(app_id, None)

    def affinity_of(self, app_id: str):
        with self._lock:
            return self._affinity.get(app_id)

    def drop_pending_affinity(self, cluster: int, placed) -> int:
        """A cluster died: apps never PLACED there (no durable
        reservation) lose their affinity so the next retry re-routes to a
        survivor — the orphaned-gang re-route. Apps already placed keep
        their binding (their state lives in the dead cluster; releasing
        them elsewhere would double-place the gang)."""
        with self._lock:
            orphans = [
                a for a, c in self._affinity.items()
                if c == cluster and a not in placed
            ]
            for a in orphans:
                del self._affinity[a]
            self.rerouted_orphans += len(orphans)
            return len(orphans)

    # -- the O(F) pick -------------------------------------------------------

    def route(self, app_id: str, instance_group: str) -> tuple[int, str]:
        """Return (home cluster, pick reason)."""
        with self._lock:
            home = self._affinity.get(app_id)
            if home is not None:
                self.picks["affinity"] += 1
                return home, "affinity"
            live = self.members.live()
            hosts = [
                i for i in live
                if self._aggs[i].hosts_group(instance_group)
            ]
            if len(hosts) == 1:
                reason = "hosting"
                choice = hosts[0]
            elif hosts:
                reason = "headroom"
                choice = max(
                    hosts,
                    key=lambda i: (self._score(i), -i),
                )
            else:
                reason = "hash"
                choice = self.members.owner(instance_group)
            self.picks[reason] += 1
            self._affinity[app_id] = choice
            return choice, reason

    def siblings(self, home: int, instance_group: str) -> list[int]:
        """Spillover candidates: live hosts of the group, best headroom
        first, home excluded."""
        with self._lock:
            live = [i for i in self.members.live() if i != home]
            hosts = [
                i for i in live
                if self._aggs[i].hosts_group(instance_group)
            ]
            def key(i):
                top, free = self._score(i)
                return (-top, -free, i)

            return sorted(hosts, key=key)

    def _score(self, i: int):
        free = self._aggs[i].free_total()
        top = self._aggs[i].top_node_free()
        # Headroom score: best-node fit first (can a gang member land at
        # all), then the free sum (how many can).
        return (top[0] + top[1] // 1024 + top[2],
                free[0] + free[1] // 1024 + free[2])

    def describe(self) -> dict:
        with self._lock:
            return {
                "clusters": self.members.n_slots,
                "live": self.members.live(),
                "apps_routed": len(self._affinity),
                "picks": dict(self.picks),
                "rerouted_orphans": self.rerouted_orphans,
            }
