"""ClusterAggregates — resident per-cluster capacity totals for routing.

The ZoneAggregates / ClusterCensus pattern lifted one level: each cluster
in the fleet keeps free/allocatable/reserved totals plus a top-node
headroom vector RESIDENT and event-maintained from its own backend's
node + reservation events, so the fleet router's home-cluster pick is
O(F) over numbers that already exist — no cluster is walked on the
serving path.

Totals are exact int64 sums over (cpu_milli, mem_kib, gpu_milli).
Reserved counts HARD reservations only (the durable commit record): soft
reservations are a compaction hint, not capacity, and the router only
needs a routing signal — `rebuild()` is the walk-oracle twin that
defines the contract and backs the equivalence tests.
"""

from __future__ import annotations

import threading

RESERVATIONS_KIND = "resourcereservations"

_ZERO = (0, 0, 0)


def _res_tuple(r) -> tuple[int, int, int]:
    return (int(r.cpu_milli), int(r.mem_kib), int(r.gpu_milli))


def _add(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def _sub(a, b):
    return (a[0] - b[0], a[1] - b[1], a[2] - b[2])


def _fits(need, have) -> bool:
    return need[0] <= have[0] and need[1] <= have[1] and need[2] <= have[2]


class ClusterAggregates:
    """Event-maintained capacity aggregates for ONE cluster's backend."""

    __slots__ = (
        "_backend", "_label", "_lock",
        "_node_alloc", "_node_groups", "_rr_per_node",
        "_reserved_by_node", "_alloc_total", "_reserved_total",
        "_top_dirty", "_top_free",
        "events_applied", "rebuilds",
    )

    def __init__(self, backend, instance_group_label: str):
        self._backend = backend
        self._label = instance_group_label
        self._lock = threading.RLock()
        # name -> (cpu, mem, gpu) allocatable, and name -> group label.
        self._node_alloc: dict[str, tuple[int, int, int]] = {}
        self._node_groups: dict[str, str] = {}
        # rr name -> {node -> (cpu, mem, gpu)} per-reservation totals, and
        # the per-node reserved sum they roll up into.
        self._rr_per_node: dict[str, dict[str, tuple[int, int, int]]] = {}
        self._reserved_by_node: dict[str, tuple[int, int, int]] = {}
        self._alloc_total = _ZERO
        self._reserved_total = _ZERO
        # Top-node free headroom is recomputed lazily: events only mark it
        # dirty, the router's read pays the O(nodes) max when stale.
        self._top_dirty = True
        self._top_free = _ZERO
        self.events_applied = 0
        self.rebuilds = 0
        backend.subscribe(
            "nodes",
            on_add=self._on_node_add,
            on_update=self._on_node_update,
            on_delete=self._on_node_delete,
        )
        backend.subscribe(
            RESERVATIONS_KIND,
            on_add=self._on_rr_upsert,
            on_update=self._on_rr_update,
            on_delete=self._on_rr_delete,
        )
        self.rebuild()

    # -- event feed ----------------------------------------------------------

    def _on_node_add(self, node) -> None:
        with self._lock:
            self.events_applied += 1
            prev = self._node_alloc.get(node.name, _ZERO)
            cur = _res_tuple(node.allocatable)
            self._node_alloc[node.name] = cur
            self._node_groups[node.name] = node.labels.get(self._label, "")
            self._alloc_total = _add(_sub(self._alloc_total, prev), cur)
            self._top_dirty = True

    def _on_node_update(self, old, new) -> None:
        self._on_node_add(new)

    def _on_node_delete(self, node) -> None:
        with self._lock:
            self.events_applied += 1
            prev = self._node_alloc.pop(node.name, None)
            self._node_groups.pop(node.name, None)
            if prev is not None:
                self._alloc_total = _sub(self._alloc_total, prev)
            self._top_dirty = True

    def _on_rr_upsert(self, rr) -> None:
        with self._lock:
            self.events_applied += 1
            self._retire_rr(rr.name)
            per_node: dict[str, tuple[int, int, int]] = {}
            for resv in rr.spec.reservations.values():
                t = _res_tuple(resv.resources)
                per_node[resv.node] = _add(per_node.get(resv.node, _ZERO), t)
            self._rr_per_node[rr.name] = per_node
            for node, t in per_node.items():
                self._reserved_by_node[node] = _add(
                    self._reserved_by_node.get(node, _ZERO), t
                )
                self._reserved_total = _add(self._reserved_total, t)
            self._top_dirty = True

    def _on_rr_update(self, old, new) -> None:
        self._on_rr_upsert(new)

    def _on_rr_delete(self, rr) -> None:
        with self._lock:
            self.events_applied += 1
            self._retire_rr(rr.name)
            self._top_dirty = True

    def _retire_rr(self, name: str) -> None:
        prev = self._rr_per_node.pop(name, None)
        if not prev:
            return
        for node, t in prev.items():
            left = _sub(self._reserved_by_node.get(node, _ZERO), t)
            if left == _ZERO:
                self._reserved_by_node.pop(node, None)
            else:
                self._reserved_by_node[node] = left
            self._reserved_total = _sub(self._reserved_total, t)

    # -- queries -------------------------------------------------------------

    def _refresh_top(self) -> None:
        best = _ZERO
        best_key = (-1, -1, -1)
        for name, alloc in self._node_alloc.items():
            free = _sub(alloc, self._reserved_by_node.get(name, _ZERO))
            key = (free[0], free[1], free[2])
            if key > best_key:
                best_key = key
                best = free
        self._top_free = best
        self._top_dirty = False

    def free_total(self) -> tuple[int, int, int]:
        with self._lock:
            return _sub(self._alloc_total, self._reserved_total)

    def top_node_free(self) -> tuple[int, int, int]:
        """Free headroom of the single best node — the gang-fit ceiling a
        router can check without walking the cluster."""
        with self._lock:
            if self._top_dirty:
                self._refresh_top()
            return self._top_free

    def hosts_group(self, group: str) -> bool:
        with self._lock:
            return group in self._node_groups.values()

    def groups(self) -> set[str]:
        with self._lock:
            return {g for g in self._node_groups.values() if g}

    def could_fit(self, per_pod: tuple[int, int, int], count: int) -> bool:
        """Optimistic admission test: the gang's total fits the cluster's
        free sum AND one pod fits the best node. Optimistic by design —
        the in-cluster solver is the truth; this only ranks siblings."""
        total = (per_pod[0] * count, per_pod[1] * count, per_pod[2] * count)
        return _fits(total, self.free_total()) and _fits(
            per_pod, self.top_node_free()
        )

    def stats(self) -> dict:
        with self._lock:
            free = _sub(self._alloc_total, self._reserved_total)
            if self._top_dirty:
                self._refresh_top()
            return {
                "nodes": len(self._node_alloc),
                "allocatable": list(self._alloc_total),
                "reserved": list(self._reserved_total),
                "free": list(free),
                "top_node_free": list(self._top_free),
                "groups": sorted(self.groups()),
                "events_applied": self.events_applied,
                "rebuilds": self.rebuilds,
            }

    # -- oracle --------------------------------------------------------------

    def rebuild(self) -> None:
        """From-scratch walk over the backend — the oracle twin the
        consistency tests diff the event-maintained state against."""
        with self._lock:
            self.rebuilds += 1
            self._node_alloc = {
                n.name: _res_tuple(n.allocatable)
                for n in self._backend.list_nodes()
            }
            self._node_groups = {
                n.name: n.labels.get(self._label, "")
                for n in self._backend.list_nodes()
            }
            self._alloc_total = _ZERO
            for t in self._node_alloc.values():
                self._alloc_total = _add(self._alloc_total, t)
            self._rr_per_node = {}
            self._reserved_by_node = {}
            self._reserved_total = _ZERO
            for rr in self._backend.list(RESERVATIONS_KIND):
                per_node: dict[str, tuple[int, int, int]] = {}
                for resv in rr.spec.reservations.values():
                    t = _res_tuple(resv.resources)
                    per_node[resv.node] = _add(
                        per_node.get(resv.node, _ZERO), t
                    )
                self._rr_per_node[rr.name] = per_node
                for node, t in per_node.items():
                    self._reserved_by_node[node] = _add(
                        self._reserved_by_node.get(node, _ZERO), t
                    )
                    self._reserved_total = _add(self._reserved_total, t)
            self._top_dirty = True

    def oracle_equals(self) -> bool:
        """Compare the resident state against a fresh walk (test hook)."""
        with self._lock:
            snap = (
                dict(self._node_alloc),
                dict(self._reserved_by_node),
                self._alloc_total,
                self._reserved_total,
            )
            applied, rebuilds = self.events_applied, self.rebuilds
            self.rebuild()
            ok = snap == (
                dict(self._node_alloc),
                dict(self._reserved_by_node),
                self._alloc_total,
                self._reserved_total,
            )
            self.events_applied, self.rebuilds = applied, rebuilds
            return ok
