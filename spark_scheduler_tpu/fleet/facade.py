"""FleetFacade — one serving endpoint over F independent cluster stacks.

The ha/shard.py ShardMap generalized one level: instead of N replicas
sharing one backend partitioned by instance group, the fleet runs F
FULLY independent per-cluster solver stacks — own backend, feature
store, planner, solver, extender — each serialized behind its own
dedicated worker thread, so per-cluster order is exactly a standalone
cluster's while windows on DIFFERENT clusters run concurrently
(aggregate decisions/s scales with F instead of serializing behind one
pipeline; XLA dispatch and the simulated device RTT both release the
GIL, so even the 2-core CPU rig overlaps them).

Byte-identity is the contract, mechanically enforced: every operation a
cluster serves (node add, schedule, release, terminate, delete) is an
ordinary single-cluster op executed on that cluster's thread, optionally
journaled in a per-cluster OPLOG. `replay_standalone()` re-serves a
cluster's oplog on a fresh standalone stack and
`verify_cluster_equivalence()` diffs every decision (ok / node_names /
outcome) and the durable reservation state byte-for-byte — the HA-shard
equivalence bar, lifted to clusters, asserted in-arm by the fleet bench.

Routing is two-level (router.py): O(F) home pick from resident
aggregates, then the unchanged in-cluster kernel. A driver denied a
capacity fit at home spills to the best sibling (spillover.py). Cluster
kill/rejoin rides StableMembership: a dead cluster's PENDING apps are
re-routed to survivors, PLACED apps keep their (unavailable) home so a
gang can never be placed twice.
"""

from __future__ import annotations

import copy
import threading
from concurrent.futures import ThreadPoolExecutor

from spark_scheduler_tpu.core.extender import (
    FAILURE_FIT,
    FAILURE_INTERNAL,
    ExtenderArgs,
    ExtenderFilterResult,
)
from spark_scheduler_tpu.core.sparkpods import (
    ROLE_DRIVER,
    SPARK_APP_ID_LABEL,
    SPARK_ROLE_LABEL,
    find_instance_group,
)
from spark_scheduler_tpu.fleet.aggregates import (
    RESERVATIONS_KIND,
    ClusterAggregates,
)
from spark_scheduler_tpu.fleet.router import FleetRouter
from spark_scheduler_tpu.fleet.spillover import (
    FleetDecision,
    SpilloverCoordinator,
)
from spark_scheduler_tpu.observability.telemetry import FleetTelemetry
from spark_scheduler_tpu.server.app import build_scheduler_app
from spark_scheduler_tpu.server.config import InstallConfig
from spark_scheduler_tpu.store.backend import DEMAND_CRD, InMemoryBackend

CLUSTER_UNAVAILABLE = "cluster unavailable"


class ClusterStack:
    """One cluster's complete scheduler stack behind one worker thread.

    All mutating ops go through `_run` — a dedicated single worker per
    cluster — so per-cluster serving order is total (a standalone
    cluster's order) while different clusters overlap. A standalone
    replay executes the same `_do_*` methods on the calling thread:
    same code, same order, same bytes.
    """

    def __init__(
        self,
        index: int,
        config: InstallConfig,
        *,
        clock=None,
        record_ops: bool = False,
        suppress_resync: bool = True,
        threaded: bool = True,
    ):
        self.index = index
        self.config = config
        self.backend = InMemoryBackend()
        self.backend.register_crd(DEMAND_CRD)
        self.app = build_scheduler_app(self.backend, config, clock=clock)
        self.extender = self.app.extender
        if suppress_resync:
            # Deterministic serving: the clock-gap resync heuristic would
            # make decisions depend on wall time (the Harness suppression).
            self.extender._last_request = float("inf")
        self._label = config.instance_group_label
        self.aggregates = ClusterAggregates(self.backend, self._label)
        self.oplog: list | None = [] if record_ops else None
        self.decisions = 0
        self._worker = (
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"fleet-c{index}"
            )
            if threaded
            else None
        )

    # -- execution -----------------------------------------------------------

    def _run(self, fn, *args):
        if self._worker is None:
            return fn(*args)
        return self._worker.submit(fn, *args).result()

    def _log(self, entry) -> None:
        if self.oplog is not None:
            self.oplog.append(entry)

    def _run_logged(self, entry_of, fn, *args):
        """Execute an op on the worker AND journal it there, so oplog
        order IS execution order even when multiple client threads hit
        the same cluster concurrently (spillover from a sibling's pump
        thread, the stacked-dispatch soak's concurrent offered load). A
        client-side `_log` around `_run` could journal two racing ops in
        the opposite order the worker served them, and the standalone
        replay would then diverge for reasons that are artifacts of the
        journal, not the decisions. `entry_of(result)` builds the entry
        from already-deepcopied inputs."""

        def body():
            result = fn(*args)
            self._log(entry_of(result))
            return result

        return self._run(body)

    # -- ops (public: thread-dispatched + oplogged) --------------------------

    def add_node(self, node) -> None:
        pristine = copy.deepcopy(node)
        self._run_logged(
            lambda _: ("add_node", pristine), self._do_add_node, node
        )

    def schedule(self, pod, node_names=None) -> ExtenderFilterResult:
        pristine = copy.deepcopy(pod)
        if node_names is None:
            node_names = self.group_node_names(
                find_instance_group(pod, self._label) or ""
            )
        names = list(node_names)
        return self._run_logged(
            lambda r: ("schedule", pristine, tuple(names), r),
            self._do_schedule,
            pod,
            names,
        )

    def release(self, pod) -> None:
        """Delete the pod AND its demand — the spillover hand-off's home
        cleanup (and the sibling cleanup after a failed attempt)."""
        pristine = copy.deepcopy(pod)
        self._run_logged(
            lambda _: ("release", pristine), self._do_release, pod
        )

    def terminate_pod(self, pod) -> None:
        pristine = copy.deepcopy(pod)
        self._run_logged(
            lambda _: ("terminate", pristine), self._do_terminate, pod
        )

    def delete_pod(self, pod) -> None:
        pristine = copy.deepcopy(pod)
        self._run_logged(
            lambda _: ("delete_pod", pristine), self._do_delete_pod, pod
        )

    # -- op bodies (single-cluster semantics, worker-thread only) ------------

    def _do_add_node(self, node) -> None:
        self.backend.add_node(node)

    def _do_schedule(self, pod, node_names) -> ExtenderFilterResult:
        if self.backend.get("pods", pod.namespace, pod.name) is None:
            self.backend.add_pod(pod)
        result = self.extender.predicate(
            ExtenderArgs(pod=pod, node_names=node_names)
        )
        if result.ok:
            self.backend.bind_pod(pod, result.node_names[0])
        self.decisions += 1
        return result

    def _do_release(self, pod) -> None:
        self.app.demand_manager.delete_demand_if_exists(pod, source="fleet")
        if self.backend.get("pods", pod.namespace, pod.name) is not None:
            self.backend.delete_pod(pod)

    def _do_terminate(self, pod) -> None:
        cur = self.backend.get("pods", pod.namespace, pod.name)
        if cur is None:
            return
        for c in cur.containers:
            c.terminated = True
        self.backend.update_pod(cur)

    def _do_delete_pod(self, pod) -> None:
        if self.backend.get("pods", pod.namespace, pod.name) is not None:
            self.backend.delete_pod(pod)

    # -- queries -------------------------------------------------------------

    def group_node_names(self, group: str) -> list[str]:
        return [
            n.name
            for n in self.backend.list_nodes()
            if not group or n.labels.get(self._label, "") == group
        ]

    def reservation_specs(self) -> dict:
        """Durable placement state, serialized for byte-for-byte diffing."""
        out = {}
        for rr in self.backend.list(RESERVATIONS_KIND):
            out[(rr.namespace, rr.name)] = {
                pod: (
                    resv.node,
                    resv.resources.cpu_milli,
                    resv.resources.mem_kib,
                    resv.resources.gpu_milli,
                )
                for pod, resv in rr.spec.reservations.items()
            }
        return out

    def stop(self) -> None:
        if self._worker is not None:
            self._worker.shutdown(wait=True)
        self.app.stop()


def _synthesized_unavailable() -> ExtenderFilterResult:
    return ExtenderFilterResult(
        node_names=[],
        failed_nodes={},
        outcome=FAILURE_INTERNAL,
    )


class FleetFacade:
    def __init__(
        self,
        n_clusters: int,
        config: InstallConfig | None = None,
        *,
        clock=None,
        registry=None,
        record_ops: bool = False,
        max_spillover_hops: int = 1,
        suppress_resync: bool = True,
        stack_window_ms: float | None = None,
    ):
        if n_clusters < 1:
            raise ValueError("n_clusters must be >= 1")
        base = config or InstallConfig(fifo=True, sync_writes=True)
        self._label = base.instance_group_label
        self.stacks = [
            ClusterStack(
                i,
                copy.deepcopy(base),
                clock=clock,
                record_ops=record_ops,
                suppress_resync=suppress_resync,
            )
            for i in range(n_clusters)
        ]
        self.router = FleetRouter(
            n_clusters, [s.aggregates for s in self.stacks]
        )
        self.telemetry = FleetTelemetry(registry)
        self.spillover = SpilloverCoordinator(
            self.stacks,
            self.router,
            self.telemetry,
            max_hops=max_spillover_hops,
        )
        self.telemetry.on_live(n_clusters)
        self.forwarded = 0
        self.unavailable_denials = 0
        self._lock = threading.RLock()
        # Fused fleet dispatch (ISSUE 20): when `fleet.stack-window-ms`
        # is > 0 and at least two clusters exist, every stack's solver
        # gets the shared FleetDispatchCoordinator as its deferred-
        # dispatch lane — concurrent per-cluster windows gather and
        # launch as ONE stacked device dispatch. None/0 = off: the lane
        # stays None and every serving path is byte-identical to the
        # unstacked fleet.
        if stack_window_ms is None:
            stack_window_ms = base.fleet_stack_window_ms
        self.dispatch = None
        if stack_window_ms and stack_window_ms > 0 and n_clusters >= 2:
            from spark_scheduler_tpu.fleet.dispatch import (
                FleetDispatchCoordinator,
            )

            self.dispatch = FleetDispatchCoordinator(
                stack_window_ms,
                expected=n_clusters,
                telemetry=self.telemetry,
            )
            for s in self.stacks:
                s.app.solver._dispatch_lane = self.dispatch

    # -- topology ------------------------------------------------------------

    def add_node(self, cluster: int, node) -> None:
        self.stacks[cluster].add_node(node)

    def kill_cluster(self, cluster: int) -> int:
        """Remove a cluster from serving. Apps PLACED there (durable
        reservation exists) keep their affinity and deny while it is down
        — re-placing them on a sibling would double-place the gang.
        PENDING apps are orphans: their affinity drops so the next retry
        re-routes to a survivor. Returns the orphan count."""
        with self._lock:
            placed = {
                rr.name
                for rr in self.stacks[cluster].backend.list(RESERVATIONS_KIND)
            }
            self.router.members.remove(cluster)
            orphans = self.router.drop_pending_affinity(cluster, placed)
        if self.dispatch is not None:
            # Survivors' gathers must stop waiting on the dead peer, and
            # its own parked window (if any — kill can land mid-gather)
            # resolves via the forced single-window fallback.
            self.dispatch.set_expected(len(self.router.members.live()))
            self.dispatch.expel(self.stacks[cluster].app.solver)
        self.telemetry.on_live(len(self.router.members.live()))
        self.telemetry.on_orphans_rerouted(orphans)
        return orphans

    def rejoin_cluster(self, cluster: int) -> None:
        with self._lock:
            self.router.members.rejoin(cluster)
        if self.dispatch is not None:
            self.dispatch.set_expected(len(self.router.members.live()))
        self.telemetry.on_live(len(self.router.members.live()))

    # -- serving -------------------------------------------------------------

    def schedule(self, pod, node_names=None, via: int | None = None) -> FleetDecision:
        """Serve one predicate + bind cycle, fleet-routed.

        `via` models which cluster endpoint kube-scheduler hit: when the
        pod routes elsewhere the call is forwarded (counted, like the
        ShardMap's wrong-shard forwarding) — the decision bytes are the
        owner's either way.
        """
        app_id = pod.labels.get(SPARK_APP_ID_LABEL, pod.name)
        group = find_instance_group(pod, self._label) or ""
        home, reason = self.router.route(app_id, group)
        self.telemetry.on_pick(reason)
        if via is not None and via != home:
            self.forwarded += 1
            self.telemetry.on_forwarded()
        if not self.router.members.is_live(home):
            # NOT an op in any cluster's stream: the cluster never saw it.
            self.unavailable_denials += 1
            return FleetDecision(
                _synthesized_unavailable(), home, unavailable=True
            )
        result = self.stacks[home].schedule(pod, node_names)
        self.telemetry.on_decision(home)
        if result.ok:
            return FleetDecision(result, home)
        is_driver = pod.labels.get(SPARK_ROLE_LABEL) == ROLE_DRIVER
        if not is_driver or result.outcome != FAILURE_FIT:
            return FleetDecision(result, home)
        return self.spillover.try_spillover(
            pod, app_id, group, home, result
        )

    def schedule_app(self, pods, node_names=None) -> list[FleetDecision]:
        return [self.schedule(p, node_names) for p in pods]

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        for s in self.stacks:
            self.telemetry.on_aggregate_events(
                s.index, s.aggregates.events_applied
            )
        return {
            "router": self.router.describe(),
            "spillover": {
                "max_hops": self.spillover.max_hops,
                "spilled": self.spillover.spilled,
                "denied": self.spillover.denied,
            },
            "stacking": (
                self.dispatch.describe()
                if self.dispatch is not None
                else {"enabled": False}
            ),
            "forwarded": self.forwarded,
            "unavailable_denials": self.unavailable_denials,
            "clusters": [
                {
                    "index": s.index,
                    "live": self.router.members.is_live(s.index),
                    "decisions": s.decisions,
                    "aggregates": s.aggregates.stats(),
                }
                for s in self.stacks
            ],
        }

    def stop(self) -> None:
        if self.dispatch is not None:
            # Release any gather still parked on a worker thread before
            # the per-stack shutdown joins those workers.
            self.dispatch.drain()
        for s in self.stacks:
            s.stop()


# -- the equivalence oracle ---------------------------------------------------


def replay_standalone(
    oplog, config: InstallConfig, *, clock=None
) -> tuple[ClusterStack, list]:
    """Re-serve a cluster's oplog on a fresh STANDALONE stack (no fleet,
    no worker thread) and return (stack, per-schedule results)."""
    stack = ClusterStack(
        0, copy.deepcopy(config), clock=clock, threaded=False
    )
    results = []
    for entry in oplog:
        kind = entry[0]
        if kind == "add_node":
            stack.add_node(copy.deepcopy(entry[1]))
        elif kind == "schedule":
            results.append(
                stack.schedule(copy.deepcopy(entry[1]), list(entry[2]))
            )
        elif kind == "release":
            stack.release(copy.deepcopy(entry[1]))
        elif kind == "terminate":
            stack.terminate_pod(copy.deepcopy(entry[1]))
        elif kind == "delete_pod":
            stack.delete_pod(copy.deepcopy(entry[1]))
        else:  # pragma: no cover - oplog writers above are exhaustive
            raise ValueError(f"unknown oplog op {kind!r}")
    return stack, results


def verify_cluster_equivalence(facade: FleetFacade) -> dict:
    """Diff every fleet cluster against a standalone replay of its oplog:
    each decision's (ok, node_names, outcome) and the final durable
    reservation specs must match byte-for-byte. Returns a per-cluster
    report; raises AssertionError on any mismatch (the in-arm bench
    assertion and the soak's invariant)."""
    report = {}
    for s in facade.stacks:
        if s.oplog is None:
            raise ValueError(
                "facade was not built with record_ops=True"
            )
        fleet_decisions = [
            (e[3].ok, tuple(e[3].node_names), e[3].outcome)
            for e in s.oplog
            if e[0] == "schedule"
        ]
        standalone, results = replay_standalone(s.oplog, s.config)
        try:
            solo_decisions = [
                (r.ok, tuple(r.node_names), r.outcome) for r in results
            ]
            assert fleet_decisions == solo_decisions, (
                f"cluster {s.index}: fleet decisions diverge from "
                f"standalone replay"
            )
            fleet_specs = s.reservation_specs()
            solo_specs = standalone.reservation_specs()
            assert fleet_specs == solo_specs, (
                f"cluster {s.index}: reservation state diverges from "
                f"standalone replay"
            )
        finally:
            standalone.stop()
        report[s.index] = {
            "decisions": len(fleet_decisions),
            "reservations": len(s.reservation_specs()),
            "identical": True,
        }
    return report
