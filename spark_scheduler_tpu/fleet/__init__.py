"""Fleet federation: many clusters behind one scheduler facade.

Two-level placement (ROADMAP item 4): a FleetFacade owns F fully
independent per-cluster solver stacks running concurrently, a FleetRouter
picks the home cluster in O(F) from resident ClusterAggregates, and a
SpilloverCoordinator retries capacity-denied drivers on the best sibling.
Per-cluster decisions stay byte-identical to a standalone cluster —
`verify_cluster_equivalence` is the mechanical oracle.
"""

from spark_scheduler_tpu.fleet.aggregates import ClusterAggregates  # noqa: F401
from spark_scheduler_tpu.fleet.dispatch import (  # noqa: F401
    FleetDispatchCoordinator,
)
from spark_scheduler_tpu.fleet.facade import (  # noqa: F401
    ClusterStack,
    FleetFacade,
    replay_standalone,
    verify_cluster_equivalence,
)
from spark_scheduler_tpu.fleet.router import FleetRouter  # noqa: F401
from spark_scheduler_tpu.fleet.spillover import (  # noqa: F401
    FleetDecision,
    SpilloverCoordinator,
)

__all__ = [
    "ClusterAggregates",
    "ClusterStack",
    "FleetDecision",
    "FleetDispatchCoordinator",
    "FleetFacade",
    "FleetRouter",
    "SpilloverCoordinator",
    "replay_standalone",
    "verify_cluster_equivalence",
]
